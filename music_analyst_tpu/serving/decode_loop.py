"""Continuous-batching scheduler: admit → prefill → decode over KV slots.

The dynamic batcher (``batcher.py``) coalesces *independent* requests
into one-shot batches; generation is different — a request occupies the
device for its whole output length, and a static batch holds every row
hostage to the slowest one.  This scheduler runs the iteration-level
loop instead (the continuous-batching idea of Orca/vLLM, shaped for
fixed-program TPU dispatch): ``n_slots`` sequences decode side by side
in the slot-indexed KV cache (``ops/kv_slots.py``), an admitted request
claims a free slot *mid-flight*, its prompt is prefilled in fixed-size
chunks between decode dispatches, and EOS or token-budget completion
frees the slot immediately so the reply is emitted while neighbors keep
decoding.  No device program ever retraces as requests come and go.

The KV cache behind the slots is paged by default (``ops/kv_pages.py``):
a fixed device-resident pool of pow2-sized pages, mapped per slot through
an int32 page table.  At admit the scheduler consults a host-side radix
tree keyed on the prompt's token ids — a prefix hit pins the shared pages
(refcounted), maps them into the slot's row, copy-on-writes the
partially-filled boundary page, and prefills only the suffix chunks; a
completed prefill's pages are adopted into the tree, completion unpins,
and a refcount-aware LRU evicts cold pages when the pool fills.  A failed
or corrupted radix lookup (fault site ``kv_pages.lookup``) falls back to
a full prefill — a cache problem can cost time, never correctness.  Pass
``page_size=0`` for the PR-10 monolithic slot cache (kept for A/B).

Reused ``DynamicBatcher`` machinery: the same bounded-admission contract
(``queue_full`` shed under overload), the same structured-error poison
isolation (a request whose prefill raises fails alone; co-resident
slots keep decoding), the same ``RetryPolicy`` around the device edge
(site ``decode.step``, the ``chaos`` suite's injection point), and the
same watchdog instrumentation (kind ``decode`` → taxonomy
``decode_stall``: a wedged dispatch trips the heartbeat monitor instead
of hanging the server mutely).

Telemetry: slot-occupancy gauge + histogram, tokens/s, and TTFT/TPOT
reservoir quantiles (``serving.ttft_seconds`` / ``serving.tpot_seconds``
land in the run manifest next to the batcher's latency quantiles, where
``telemetry-report`` picks them up).

Speculative decoding (``--speculate-k`` / ``$MUSICAAL_SERVE_SPECULATE_K``,
0 = off): greedy decode is one device round-trip per ``decode_span``
tokens, and the round-trip — not compute — is the measured bottleneck
(PERFORMANCE.md).  With ``k > 0`` the decode tick runs the fixed-shape
*verify* program instead (``slots.verify`` / ``pages.verify``): a
host-side self-drafter (prompt-lookup over each slot's prompt + emitted
tokens — no second model) proposes up to ``k`` tokens per slot, the
device scores the ``[n_slots, k+1]`` block (carry + drafts) in ONE
dispatch, and the host commits the longest accepted prefix plus the
first-mismatch correction token — between 1 and ``k+1`` tokens per slot
per dispatch, never fewer than plain stepping.  Acceptance is exact:
a draft commits only when it equals the device argmax under the same
committed context, and the correction token is itself that argmax, so
output tokens are byte-identical to non-speculative decode at every
``k`` (the drafter can only change *when* tokens commit, never *which*).
A per-slot acceptance-rate EWMA adapts the proposed depth inside the
fixed ``k+1`` program shape (zero retraces); a draft-fault
(``spec.draft``) tick degrades to one plain decode dispatch — counted
in ``speculation.fallbacks``, identical bytes.

In-batch dedup at the admission edge: N concurrently-live ``generate``
requests with identical (tenant, prompt, budget) occupy ONE slot — the
first is the primary, later arrivals ride as followers and the settled
reply (success or failure) fans out to each under its own request id
(``dedup_folded`` in stats; greedy decode is deterministic, so the
shared reply is exactly what each would have computed).

SLO enforcement (``serving/slo.py``): the admission queue is a
:class:`FairQueue` (strict priority classes, per-tenant WFQ) with
per-tenant token buckets and the batcher's full shed contract
(``queue_full`` / ``slo_unattainable``, each carrying ``retry_after_ms``).
When a TTFT target is configured (``--ttft-slo-ms``) and a waiting
higher-priority admit would miss it, the scheduler **preempts**: it
slot-steals from the longest-running strictly-lower-priority decode —
the victim's fully-prefilled prompt pages are first adopted into the
radix tree, its slot is released through the normal host-side free path
(no device zeroing: nothing faulted, so the reuse invariants hold), and
the original request is requeued at the head of its tenant queue.
Resume is **O(1)**: preemption checkpoints the victim's decode state
(paged — a pinned copy of its page-table row; monolithic — a device-side
copy of its slot rows via ``slots.snapshot``), and re-admission restores
it straight into decode with zero prefill chunks.  A periodic checkpoint
tick (``MUSICAAL_SERVE_CKPT_INTERVAL`` decode dispatches) additionally
bounds the work a failed dispatch loses: a resubmitted request id
resumes from the last checkpoint instead of the prompt.  Greedy decode
is deterministic, so resumed tokens are byte-identical to the
undisturbed run at zero retraces.  An injected
``scheduler.preempt`` fault aborts the steal BEFORE any state mutation —
the degraded mode is "no steal this tick", never a half-zeroed slot.  A
TPOT target (``--tpot-slo-ms``) throttles new admissions while the
per-token EWMA is over target, shrinking the multiprogramming level
instead of letting every resident stream miss together.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.observability.engine_ledger import EngineLedger
from music_analyst_tpu.ops.kv_pages import PagePool, RadixIndex
from music_analyst_tpu.resilience.faults import fault_point, InjectedFault
from music_analyst_tpu.resilience.policy import RetryPolicy
from music_analyst_tpu.serving.batcher import (
    _LATENCY_BUCKETS,
    _OCCUPANCY_BUCKETS,
    _RETRY_AFTER_CAP_MS,
    _resolve,
    DEFAULT_TENANT,
    ServeRequest,
    resolve_kv_pages,
    resolve_kv_quant,
    resolve_max_queue,
    resolve_page_size,
    resolve_prefill_chunk,
    resolve_priority,
    resolve_slots,
    resolve_speculate_k,
    resolve_tenant_budget,
    resolve_tpot_slo_ms,
    resolve_ttft_slo_ms,
)
from music_analyst_tpu.serving.response_cache import normalize_text, try_answer
from music_analyst_tpu.serving.slo import FairQueue, RateMeter, TokenBucket
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.telemetry.reqtrace import get_reqtrace
from music_analyst_tpu.telemetry.core import Histogram
from music_analyst_tpu.utils.labels import normalise_label

# Per-token latency buckets: decode steps are ms-scale on-device, up to
# second-scale on the CPU-emulated mesh.
_TOKEN_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0,
)

# Accepted tokens per verify dispatch lives in [1, k+1]; upper bins cover
# the largest draft depths anyone sensibly runs.
_ACCEPTED_BUCKETS = (1, 2, 3, 4, 5, 6, 8, 10, 12, 16, 24, 32)

# N-gram widths the self-drafter tries, longest first: a longer match is
# a stronger continuation signal; the unigram floor keeps short cycles
# (a tiny model latching onto one token) draftable.
_DRAFT_NGRAMS = (3, 2, 1)

# Speculation pays only when drafts mostly land: a verify dispatch runs
# k+1 sequential device steps, so at low acceptance it nets barely more
# than the 1-step plain program at many times the cost.  Below this
# acceptance-EWMA threshold a slot stops proposing drafts (the tick
# degrades to plain decode) and instead probes with a single draft token
# once every _PROBE_EVERY_TICKS ticks, which bounds the cost of
# speculation on an unpredictable stream while keeping the EWMA able to
# recover the moment the stream turns repetitive.
_SPECULATE_EWMA_MIN = 0.6
_PROBE_EVERY_TICKS = 6


def _draft_from_history(hist: List[int], k: int) -> List[int]:
    """Prompt-lookup self-drafting: propose up to ``k`` continuation
    tokens for a token stream (prompt + emitted + carry).

    Finds the most recent *earlier* occurrence of the stream's trailing
    n-gram and proposes the tokens that followed it, then re-matches on
    the extended stream so a short cycle drafts through the whole block.
    Pure host-side heuristic: a wrong draft costs device compute (the
    verify program rejects it), never a wrong token.
    """
    out: List[int] = []
    work = list(hist)
    while len(out) < k:
        nxt: Optional[List[int]] = None
        L = len(work)
        for n in _DRAFT_NGRAMS:
            if L <= n:
                continue
            gram = work[L - n:]
            for j in range(L - 1, n - 1, -1):
                if work[j - n:j] == gram:
                    nxt = work[j:min(j + k - len(out), L)]
                    break
            if nxt:
                break
        if not nxt:
            break
        out.extend(nxt)
        work.extend(nxt)
    return out[:k]


class _Slot:
    """Host-side state of one occupied KV slot."""

    __slots__ = ("req", "ids", "plen", "next_chunk", "budget", "steps",
                 "tokens", "carry", "done", "active", "t_first",
                 "pages", "kv_shared", "skipped", "hist", "accept_ewma",
                 "probe")

    def __init__(self, req: ServeRequest, ids: np.ndarray, plen: int,
                 budget: int) -> None:
        self.req = req
        self.ids = ids
        self.plen = int(plen)
        self.next_chunk = 0        # next prefill chunk offset; -1 = prefilled
        self.budget = int(budget)
        self.steps = 0             # decode steps taken so far
        self.tokens: List[int] = []  # emitted token ids
        self.carry = 0             # current input token for the next step
        self.done = False          # emitted EOS (static-path done semantics)
        self.active = False        # in the decode phase
        self.t_first: Optional[float] = None  # first-token wall time (TTFT)
        self.pages: Optional[List[int]] = None  # paged: this slot's table row
        self.kv_shared = 0         # paged: tokens served from shared pages
        self.skipped = 0           # paged: prefill chunks skipped by the hit
        # Speculation: cached drafter stream (prompt + emitted + carry;
        # None = rebuild) and this slot's acceptance-rate EWMA, which
        # adapts the proposed draft depth inside the fixed program shape.
        self.hist: Optional[List[int]] = None
        self.accept_ewma = 1.0
        self.probe = 0             # ticks since the EWMA drove depth to 0


def _ckpt_key(rid: Any) -> str:
    """Canonical checkpoint-registry key for an arbitrary JSON request id
    (same canonicalization as the journal's dedup index)."""
    try:
        return json.dumps(rid, sort_keys=True, separators=(",", ":"))
    except (TypeError, ValueError):
        return repr(rid)


class _Checkpoint:
    """O(1)-resume snapshot of one in-flight generation.

    Taken at preemption and on the periodic checkpoint tick; holds the
    host progress fields (emitted tokens, step/carry/done) plus the KV
    needed to re-enter decode without a single prefill chunk: the paged
    backend pins the victim's page-table row (its own refcount, so the
    row survives the slot's release *and* the zeroing failure path, which
    only touches fully-unreferenced pages); the monolithic backend keeps
    a device-side copy of the slot's rows (``slots.snapshot``).  The KV
    lives on the device only — a SIGKILL still loses it, so cross-crash
    journal replay recomputes from the prompt (byte-identical greedy
    text); O(1) resume is the in-process guarantee.
    """

    __slots__ = ("key", "ids", "plen", "budget", "steps", "tokens",
                 "carry", "done", "t_first", "pages", "kv")

    def __init__(self, key: str, slot: "_Slot") -> None:
        self.key = key
        self.ids = slot.ids
        self.plen = slot.plen
        self.budget = slot.budget
        self.steps = slot.steps
        self.tokens = list(slot.tokens)
        self.carry = slot.carry
        self.done = slot.done
        self.t_first = slot.t_first
        self.pages: Optional[List[int]] = None  # paged: pinned row copy
        self.kv: Optional[Any] = None  # monolithic: (keys, values, length)


class ContinuousScheduler:
    """Admit→prefill→decode loop over a backend's slot runtime.

    ``backend`` must expose ``slot_runtime(...)`` (capability probe),
    ``params``, and ``tokenizer`` — ``models/llama.py``'s zero-shot
    classifier is the canonical one.  Usable two ways: synchronously
    (``submit(...)`` then :meth:`run_until_idle`, the batch-generation
    path) or threaded (:meth:`start` / :meth:`drain`, the server path).
    """

    def __init__(
        self,
        backend,
        n_slots: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        prompt_region: Optional[int] = None,
        max_new_tokens: int = 16,
        decode_span: int = 4,
        max_queue: Optional[int] = None,
        page_size: Optional[int] = None,
        kv_pages: Optional[int] = None,
        kv_quant: Optional[str] = None,
        prefix_cache: bool = True,
        ttft_slo_ms: Optional[float] = None,
        tpot_slo_ms: Optional[float] = None,
        tenant_budget: Optional[float] = None,
        priority: Optional[int] = None,
        checkpoint_interval: Optional[int] = None,
        speculate_k: Optional[int] = None,
        ledger_interval_ms: Optional[Any] = None,
        ledger_dir: Optional[str] = None,
        response_cache=None,
    ) -> None:
        self.backend = backend
        # Cross-request response cache (serving/response_cache.py),
        # consulted in submit() BEFORE the shed ladder and tenant
        # metering — a hit settles without a slot, a dispatch, or a
        # chip-second; None leaves every request on the compute path.
        self.response_cache = response_cache
        self.n_slots = resolve_slots(n_slots)
        self.prefill_chunk = resolve_prefill_chunk(prefill_chunk)
        self.max_queue = resolve_max_queue(max_queue)
        self.ttft_slo_ms = resolve_ttft_slo_ms(ttft_slo_ms)
        self.tpot_slo_ms = resolve_tpot_slo_ms(tpot_slo_ms)
        self.tenant_budget = resolve_tenant_budget(tenant_budget)
        self.default_priority = resolve_priority(priority)
        # Decode dispatches between periodic checkpoint refreshes (0 =
        # preemption-time checkpoints only).  At the default span a short
        # generation completes before the first tick fires, so the tick
        # costs nothing until requests are long enough to need it.
        self.checkpoint_interval = int(_resolve(
            checkpoint_interval, "MUSICAAL_SERVE_CKPT_INTERVAL", 32,
            integer=True, minimum=0,
        ))
        page = resolve_page_size(page_size)
        self.paged = bool(page) and hasattr(backend, "paged_runtime")
        self.kv_quant = resolve_kv_quant(kv_quant)
        self._kv_quant_degraded = False
        if self.kv_quant != "none" and not self.paged:
            raise ValueError(
                "kv_quant requires the paged KV backend; it cannot combine "
                "with --page-size 0 (the monolithic slot cache)"
            )
        if self.kv_quant != "none":
            # Degrade seam: a fault here (site ``kv_quant.dequant``)
            # means the quantized read path is unavailable — fall back to
            # the unquantized pool *before* any page is written, so every
            # reply is byte-identical to an unquantized scheduler's.
            try:
                fault_point("kv_quant.dequant", scheme=self.kv_quant)
            except InjectedFault:
                self.kv_quant = "none"
                self._kv_quant_degraded = True
        if self.paged:
            self.runtime = backend.paged_runtime(
                n_slots=self.n_slots,
                prefill_chunk=self.prefill_chunk,
                max_new_tokens=max_new_tokens,
                prompt_region=prompt_region,
                decode_span=decode_span,
                page_size=page,
                kv_pages=resolve_kv_pages(kv_pages, self.n_slots),
                kv_quant=self.kv_quant,
            )
        else:
            self.runtime = backend.slot_runtime(
                n_slots=self.n_slots,
                prefill_chunk=self.prefill_chunk,
                max_new_tokens=max_new_tokens,
                prompt_region=prompt_region,
                decode_span=decode_span,
            )
        self.plan = self.runtime.plan
        # Draft depth: k drafts + the carry make a [n_slots, k+1] verify
        # block whose KV write must fit the decode region from any
        # participating step, so k is capped at max_new - 1 (ticks where
        # a slot is within k steps of max_new fall back to plain
        # stepping — see _decode_tick).
        self.speculate_k = min(
            resolve_speculate_k(speculate_k), max(0, self.plan.max_new - 1)
        )
        self.caches = self.runtime.init_caches()
        if self.paged:
            plan = self.plan
            self._pool: Optional[PagePool] = PagePool(plan.n_pages)
            self._radix: Optional[RadixIndex] = (
                RadixIndex(plan.page_size) if prefix_cache else None
            )
            # Free slots' rows point every entry at the trash page so the
            # fixed-shape decode dispatch can't scribble on recycled pages.
            self._table = np.full(
                (plan.n_slots, plan.pages_per_slot), plan.trash_page,
                np.int32,
            )
            self._prefix: Dict[str, Any] = {
                "lookups": 0, "hits": 0, "tokens_shared": 0,
                "pages_shared": 0, "chunks_skipped": 0, "cow_copies": 0,
                "evictions": 0, "adopted_pages": 0, "fallbacks": 0,
                "deferred": 0, "fresh_pages": 0,
            }
        else:
            self._pool = None
            self._radix = None
            self._table = None
            self._prefix = {}
        self._slots: List[Optional[_Slot]] = [None] * self.plan.n_slots
        self._queue = FairQueue()
        self._buckets: Dict[str, TokenBucket] = {}
        self._cond = threading.Condition()
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._retry = RetryPolicy(base_s=0.05, cap_s=1.0)
        self._ttft = Histogram(_LATENCY_BUCKETS)
        self._tpot = Histogram(_TOKEN_BUCKETS)
        self._occupancy = Histogram(_OCCUPANCY_BUCKETS)
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "shed": 0, "completed": 0, "failed": 0,
            "tokens_generated": 0, "prefill_dispatches": 0,
            "decode_dispatches": 0, "decode_seconds": 0.0,
            "queue_depth_max": 0,
            "preemptions": 0, "preempt_faults": 0, "resumed": 0,
            "checkpoints_taken": 0, "checkpoints_released": 0,
            "resumed_o1": 0, "resume_chunks_skipped": 0,
            "tpot_throttle_ticks": 0, "ttft_slo_misses": 0,
            "tpot_slo_misses": 0, "retry_after_ms_last": None,
            "shed_queue_full": 0, "shed_slo_unattainable": 0,
            "shed_tenant_budget": 0, "shed_evicted": 0,
            "dedup_folded": 0, "cache_hits": 0,
        }
        # Speculation counters (stats()["speculation"] → manifest
        # ``serving.decode.speculation``).
        self._spec: Dict[str, Any] = {
            "dispatches": 0,         # verify dispatches
            "drafted": 0,            # draft tokens proposed
            "accepted": 0,           # draft tokens accepted
            "tokens_committed": 0,   # tokens emitted by verify dispatches
            "fallbacks": 0,          # draft-fault → plain-decode ticks
            "plain_ticks": 0,        # tail/fallback plain dispatches at k>0
        }
        self._accept_hist = Histogram(_OCCUPANCY_BUCKETS)
        self._block_hist = Histogram(_ACCEPTED_BUCKETS)
        # Rolling-window rates (serving/slo.py RateMeter) so a live
        # ``stats`` poll reads req/s, tokens/s, shed/s directly.
        self._rates = {
            "req_s": RateMeter(), "tokens_s": RateMeter(),
            "shed_s": RateMeter(),
        }
        # In-batch dedup: live generate primaries by (tenant, text,
        # budget); guarded by _cond (submit side) — fan-out pops under
        # the same lock.
        self._dedup_live: Dict[Any, ServeRequest] = {}
        # Live checkpoints keyed by canonical request id, oldest first.
        # Bounded (LRU release) so abandoned checkpoints can't pin the
        # page pool or hold monolithic KV copies forever.
        self._ckpts: "OrderedDict[str, _Checkpoint]" = OrderedDict()
        self._ckpt_limit = 2 * self.plan.n_slots
        # Per-tenant admission ledger (manifest ``serving.slo`` section).
        self._tenants: Dict[str, Dict[str, int]] = {}
        # TTFT/TPOT EWMAs (seconds): the drain estimate behind
        # ``slo_unattainable`` sheds and the TPOT admission throttle.
        self._ttft_ewma_s = 0.0
        self._tpot_ewma_s = 0.0
        self._t_started = time.monotonic()
        self._warmup_record: Optional[Dict[str, Any]] = None
        # Engine goodput ledger (observability/engine_ledger.py): per-tick
        # wall-time attribution + occupancy + per-tenant chip-seconds.
        # Recording is always on (host-side float adds — no device work,
        # no readbacks, no per-tick allocation); file flushing rides the
        # metrics cadence and only arms when a profile dir is resolved.
        self._ledger = EngineLedger(
            self.plan.n_slots,
            interval_ms=ledger_interval_ms,
            directory=ledger_dir,
        )
        self._ledger.attach_occupancy(self._ledger_occupancy_sample)
        # Per-tick attribution scratch — reset at tick start, consumed by
        # record_tick; plain float/int adds on the hot path.
        self._led_prefill_s = 0.0
        self._led_chunks_cold = 0
        self._led_chunks_shared = 0
        self._led_decode_s = 0.0
        self._led_useful_frac = 1.0
        self._led_committed = 0
        self._led_preempt_s = 0.0
        # Tenant slot shares captured right after admission — settle frees
        # slots mid-tick, so reading occupancy at record time would drop
        # the attribution for requests that finish within their tick.
        self._led_shares: Dict[str, int] = {}

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ContinuousScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="decode-loop", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting, run every queued/in-flight request to its reply
        (or a structured error), stop the loop thread."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        self._thread = None
        if thread is None:
            # Synchronous use: drain means "finish the backlog inline".
            self.run_until_idle()
        self._ledger.close()

    @property
    def draining(self) -> bool:
        return self._draining

    def warmup(self) -> Dict[str, Any]:
        """Compile every decode program before the first request.

        Monolithic: one dummy prefill chunk + one decode dispatch + one
        free (three programs).  Paged: prefill is run *twice through two
        different page rows* (the page-table-churn witness: the second
        mapping must reuse the first executable), then a full-table decode
        dispatch, a page copy, and a pool-wide free — four programs, after
        which the pool is zeroed again.  Every steady-state dispatch
        reuses these executables (the zero-retrace contract;
        ``compiled_variants`` should stay flat).
        """
        import jax.numpy as jnp

        tel = get_telemetry()
        before = tel.compile_stats()
        variants_before = self.runtime.compiled_variants()
        t0 = time.perf_counter()
        zero = jnp.asarray(0, jnp.int32)
        chunk_ids = jnp.zeros((self.plan.prefill_chunk,), jnp.int32)
        n = self.plan.n_slots
        if self.paged:
            plan = self.plan
            pps = plan.pages_per_slot
            length_after = jnp.asarray(plan.prefill_chunk, jnp.int32)
            # Warm every page count a slot can occupy: two prefills through
            # shifted page rows (the churn ladder — proves remapping never
            # retraces), one decode through a full table, one CoW copy.
            # All of it writes into free pages; the closing free zeroes
            # the pool, so warmup leaves no residue behind.
            for shift in (0, 1):
                row = (
                    np.arange(pps, dtype=np.int32) + shift
                ) % plan.n_pages
                self.caches, _ = self.runtime.prefill_chunk(
                    self.backend.params, self.caches, jnp.asarray(row),
                    zero, chunk_ids, zero, length_after, zero,
                )
            table = (
                np.arange(n * pps, dtype=np.int32).reshape(n, pps)
                % plan.n_pages
            )
            self.caches, _, _, _, _ = self.runtime.decode_step(
                self.backend.params, self.caches, jnp.asarray(table),
                jnp.zeros((n,), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.zeros((n,), bool),
                jnp.zeros((n,), bool),
            )
            self.caches = self.runtime.copy_page(
                self.caches, zero,
                jnp.asarray(min(1, plan.n_pages - 1), jnp.int32),
            )
            if self.speculate_k > 0:
                # Verify joins the warmup ladder so the first live
                # speculative request never compiles.
                self.caches, _ = self.runtime.verify_block(
                    self.backend.params, self.caches, jnp.asarray(table),
                    jnp.zeros((n, self.speculate_k + 1), jnp.int32),
                    jnp.ones((n,), jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                )
            self.caches = self.runtime.free_pages(
                self.caches,
                jnp.ones((plan.n_pages + 1,), bool),
                jnp.ones((n,), bool),
            )
        else:
            self.caches, _ = self.runtime.prefill_chunk(
                self.backend.params, self.caches, zero, chunk_ids, zero,
                jnp.asarray(self.plan.prefill_chunk, jnp.int32), zero,
            )
            self.caches, _, _, _, _ = self.runtime.decode_step(
                self.backend.params, self.caches,
                jnp.zeros((n,), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.ones((n,), jnp.int32),
                jnp.zeros((n,), bool),
                jnp.zeros((n,), bool),
            )
            if self.speculate_k > 0:
                self.caches, _ = self.runtime.verify_block(
                    self.backend.params, self.caches,
                    jnp.zeros((n, self.speculate_k + 1), jnp.int32),
                    jnp.ones((n,), jnp.int32),
                    jnp.zeros((n,), jnp.int32),
                )
            self.caches = self.runtime.free_slots(
                self.caches, jnp.ones((n,), bool)
            )
            # Checkpoint pair (O(1) preempt-resume): snapshot a zeroed
            # slot and restore it in place — compiles both programs, no
            # residue.
            snap_k, snap_v, snap_len = self.runtime.snapshot_slot(
                self.caches, zero
            )
            self.caches = self.runtime.restore_slot(
                self.caches, snap_k, snap_v, zero, snap_len
            )
        warm_s = time.perf_counter() - t0
        after = tel.compile_stats()
        record = {
            "seconds": round(warm_s, 6),
            "compiles": after["count"] - before["count"],
            "programs": self.runtime.compiled_variants() - variants_before,
            "n_slots": self.plan.n_slots,
            "prefill_chunk": self.plan.prefill_chunk,
            "kv_backend": "paged" if self.paged else "slots",
            "speculate_k": self.speculate_k,
        }
        if self.paged:
            record.update(
                page_size=self.plan.page_size,
                kv_pages=self.plan.n_pages,
                pages_per_slot=self.plan.pages_per_slot,
                kv_quant=self.kv_quant,
            )
        self._warmup_record = record
        tel.annotate(decode_warmup=record)
        return record

    # ----------------------------------------------------------- admission

    def submit(self, rid: Any, text: str, op: str = "generate",
               max_new_tokens: Optional[int] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> ServeRequest:
        """Admit (or shed) one generation request; mirrors the batcher's
        bounded-admission contract, including the full SLO shed ladder
        (token bucket → ``slo_unattainable`` → priority-aware eviction →
        ``queue_full``), every shed carrying ``retry_after_ms``."""
        tel = get_telemetry()
        budget = int(max_new_tokens or self.plan.max_new)
        budget = max(1, min(budget, self.plan.max_new))
        if deadline_ms is None and self.ttft_slo_ms > 0.0:
            deadline_ms = self.ttft_slo_ms
        req = ServeRequest(
            rid, op, text, meta={"max_new_tokens": budget},
            tenant=tenant or DEFAULT_TENANT,
            priority=(
                self.default_priority if priority is None else int(priority)
            ),
            deadline_ms=deadline_ms,
        )
        # Trace attach BEFORE the shed ladder: sheds carry trace ids too.
        get_reqtrace().begin_request(req)
        # Response cache BEFORE the shed ladder and the tenant meter: a
        # repeat of a settled generation is answered for ~a hash +
        # lookup — no slot, no dispatch, no token-bucket charge, no
        # ledger chip-seconds — and a repeat that would shed
        # queue_full/slo_unattainable is answered instead.
        if try_answer(self.response_cache, req, budget=budget):
            with self._stats_lock:
                self._stats["cache_hits"] += 1
            self._rates["req_s"].mark()
            tel.count("serving.decode_cache_hits")
            return req
        with self._cond:
            if self._draining:
                req.fail("draining", "server is draining; not admitting")
                self._shed(req, None, None)
                return req
            # Per-tenant token bucket: the saturating tenant sheds at its
            # OWN budget while everyone else keeps admitting.
            if self.tenant_budget > 0.0:
                bucket = self._buckets.get(req.tenant)
                if bucket is None:
                    bucket = self._buckets[req.tenant] = TokenBucket(
                        self.tenant_budget
                    )
                if not bucket.take():
                    hint_ms = max(
                        bucket.retry_after_ms(), self.retry_after_ms(1)
                    )
                    req.fail(
                        "queue_full",
                        f"tenant {req.tenant!r} over its admission budget "
                        f"({self.tenant_budget:g} req/s); retry after "
                        f"{hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_tenant_budget", hint_ms)
                    return req
            # In-batch dedup at the admission edge: an identical live
            # generate (same tenant, prompt, and budget) is already
            # queued or decoding — ride its slot as a follower instead
            # of occupying another; the settled reply fans out at settle
            # under each follower's own id.  Checked before capacity: a
            # fold consumes no queue depth, so it never evicts anyone.
            if op == "generate":
                # Identity is normalize_text — the same definition the
                # batcher's row fold and the response-cache key use, so
                # every repeat-detection tier agrees.
                dedup_key = (req.tenant, normalize_text(text), budget)
                primary = self._dedup_live.get(dedup_key)
                if primary is not None and not primary.done:
                    primary.meta.setdefault(
                        "dedup_followers", []
                    ).append(req)
                    with self._stats_lock:
                        self._stats["admitted"] += 1
                        self._stats["dedup_folded"] += 1
                        self._tenant_ledger(req.tenant)["admitted"] += 1
                    self._rates["req_s"].mark()
                    tel.count("serving.decode_admitted")
                    tel.count("serving.decode_dedup_folded")
                    return req
            else:
                dedup_key = None
            # Deadline check BEFORE capacity: a request the drain
            # estimate already dooms must not evict anyone.
            if req.deadline_ms is not None and req.deadline_ms > 0.0:
                est_ms = self._ttft_estimate_ms(req.priority)
                if est_ms is not None and est_ms > req.deadline_ms:
                    hint_ms = self.retry_after_ms(len(self._queue))
                    req.fail(
                        "slo_unattainable",
                        f"TTFT estimate {est_ms:.0f} ms already exceeds "
                        f"the {req.deadline_ms:.0f} ms deadline; retry "
                        f"after {hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                        estimate_ms=round(est_ms, 3),
                    )
                    self._shed(req, "shed_slo_unattainable", hint_ms)
                    return req
            depth = len(self._queue)
            if depth >= self.max_queue:
                # Priority-aware eviction: shed queued lower-priority /
                # over-represented work before the newcomer.
                victim = self._queue.shed_candidate(req.tenant, req.priority)
                hint_ms = self.retry_after_ms(depth)
                if victim is None:
                    req.fail(
                        "queue_full",
                        f"decode admission queue full "
                        f"({depth}/{self.max_queue}); retry after "
                        f"{hint_ms:.0f} ms",
                        retry_after_ms=hint_ms,
                    )
                    self._shed(req, "shed_queue_full", hint_ms)
                    return req
                victim.fail(
                    "queue_full",
                    f"evicted for a priority-{req.priority} admit with the "
                    f"queue full ({depth}/{self.max_queue}); retry after "
                    f"{hint_ms:.0f} ms",
                    retry_after_ms=hint_ms,
                )
                self._shed(victim, "shed_evicted", hint_ms)
                self._fanout_locked(victim)
            if dedup_key is not None:
                # Past the shed ladder: this request is the live primary
                # later identical arrivals fold onto.
                req.meta["dedup_key"] = dedup_key
                self._dedup_live[dedup_key] = req
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["admitted"] += 1
            self._tenant_ledger(req.tenant)["admitted"] += 1
            if depth > self._stats["queue_depth_max"]:
                self._stats["queue_depth_max"] = depth
        self._rates["req_s"].mark()
        tel.count("serving.decode_admitted")
        return req

    def _tenant_ledger(self, tenant: str) -> Dict[str, int]:
        """Caller holds ``_stats_lock``."""
        ledger = self._tenants.get(tenant)
        if ledger is None:
            ledger = self._tenants[tenant] = {
                "admitted": 0, "completed": 0, "shed": 0,
                "tpot_ewma_ms": 0.0,
            }
        return ledger

    def _shed(self, req: ServeRequest, kind_stat: Optional[str],
              hint_ms: Optional[float]) -> None:
        with self._stats_lock:
            self._stats["shed"] += 1
            if kind_stat in self._stats:
                self._stats[kind_stat] += 1
            if hint_ms is not None:
                self._stats["retry_after_ms_last"] = hint_ms
            self._tenant_ledger(req.tenant)["shed"] += 1
        self._rates["shed_s"].mark()
        get_telemetry().count("serving.shed")

    def _fanout(self, req: ServeRequest) -> None:
        """Fan a settled dedup primary's reply (success OR failure) out to
        its followers under each follower's own request id, and retire
        the registry entry.  No-op for requests that never registered."""
        with self._cond:
            self._fanout_locked(req)

    def _fanout_locked(self, req: ServeRequest) -> None:
        """Caller holds ``_cond``."""
        key = req.meta.pop("dedup_key", None)
        if key is not None and self._dedup_live.get(key) is req:
            del self._dedup_live[key]
        followers = req.meta.pop("dedup_followers", None)
        if not followers or req.response is None:
            return
        ok = bool(req.response.get("ok"))
        served = 0
        for f in followers:
            if f.done:
                continue
            payload = dict(req.response)
            payload["id"] = f.id
            f.complete(payload)
            served += 1
            with self._stats_lock:
                if ok:
                    self._stats["completed"] += 1
                    self._tenant_ledger(f.tenant)["completed"] += 1
                else:
                    self._stats["failed"] += 1
        if served:
            get_telemetry().count(
                "serving.decode_completed" if ok
                else "serving.request_failed",
                served,
            )

    def _settle_rate(self) -> float:
        """Observed settle throughput (requests/s since construction) —
        the denominator of the retry hint and the TTFT drain estimate."""
        with self._stats_lock:
            settled = self._stats["completed"] + self._stats["failed"]
        elapsed = time.monotonic() - self._t_started
        return settled / elapsed if elapsed > 0.0 and settled else 0.0

    def retry_after_ms(self, depth: Optional[int] = None) -> float:
        """Backoff hint for a shed client: estimated time to drain the
        queue ahead at the observed settle rate, floored at 1 ms and
        capped so a stale estimate can't park clients for minutes.
        Before the first settle there is no rate — fall back to a
        per-queued-request pessimistic constant."""
        if depth is None:
            with self._cond:
                depth = len(self._queue)
        rate = self._settle_rate()
        if rate > 0.0:
            hint = (depth + 1) / rate * 1000.0
        else:
            hint = 50.0 * max(depth, 1)
        return round(min(max(hint, 1.0), _RETRY_AFTER_CAP_MS), 3)

    def _ttft_estimate_ms(self, priority: int) -> Optional[float]:
        """EWMA estimate of a newcomer's TTFT at ``priority`` (caller
        holds cond): queue-drain time ahead of it plus the observed
        prefill latency.  None before the first completion — no
        observation means no grounds to shed on."""
        rate = self._settle_rate()
        with self._stats_lock:
            ttft_ewma_s = self._ttft_ewma_s
        if rate <= 0.0 or ttft_ewma_s <= 0.0:
            return None
        ahead = self._queue.depth_ahead(priority)
        return ahead / rate * 1000.0 + ttft_ewma_s * 1000.0

    def _bump(self, **deltas: Any) -> None:
        with self._stats_lock:
            for key, n in deltas.items():
                self._stats[key] += n

    # ------------------------------------------------------------ the loop

    def _loop(self) -> None:
        while True:
            did_work = self._tick()
            if did_work:
                watchdog.beat("decode.loop")
                continue
            with self._cond:
                if self._draining and not self._queue and not self._occupied():
                    return
                t_wait = time.perf_counter()
                self._cond.wait(0.005)
                self._ledger.idle_wait(t_wait, time.perf_counter())

    def run_until_idle(self, max_ticks: int = 1_000_000) -> None:
        """Synchronous driver: tick until queue and slots are empty."""
        for _ in range(max_ticks):
            if not self._tick():
                with self._cond:
                    if not self._queue and not self._occupied():
                        return
        raise RuntimeError("run_until_idle exceeded its tick bound")

    def _occupied(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def _tick(self) -> bool:
        """One scheduler iteration: admit waiting requests into free slots,
        advance one prefill chunk per mid-prefill slot, run one decode
        dispatch over all slots, settle completions.  Returns whether any
        work happened."""
        t0 = time.perf_counter()
        self._led_prefill_s = 0.0
        self._led_chunks_cold = 0
        self._led_chunks_shared = 0
        self._led_decode_s = 0.0
        self._led_useful_frac = 1.0
        self._led_committed = 0
        self._led_preempt_s = 0.0
        did = self._admit()
        shares = self._led_shares
        shares.clear()
        for s in self._slots:
            if s is not None:
                tenant = s.req.tenant
                shares[tenant] = shares.get(tenant, 0) + 1
        did = self._prefill_tick() or did
        did = self._decode_tick() or did
        self._publish_gauges()
        self._ledger.record_tick(
            t0, time.perf_counter(),
            prefill_s=self._led_prefill_s,
            chunks_cold=self._led_chunks_cold,
            chunks_shared=self._led_chunks_shared,
            decode_s=self._led_decode_s,
            useful_frac=self._led_useful_frac,
            committed=self._led_committed,
            preempt_s=self._led_preempt_s,
            shares=shares,
        )
        self._ledger.maybe_flush()
        return did

    # ------------------------------------------------------------ admit

    def _admit(self) -> bool:
        did = False
        while True:
            with self._cond:
                head = self._queue.peek()
                if head is not None and head.done:
                    # Settled while queued (shouldn't normally happen —
                    # eviction removes its victim): discard and move on.
                    self._queue.popleft()
                    continue
            if head is None:
                return did
            free = next(
                (i for i, s in enumerate(self._slots) if s is None), None
            )
            if free is None:
                free = self._maybe_preempt()
                if free is None:
                    return did
            elif self._tpot_throttled(head):
                return did
            with self._cond:
                req = self._queue.popleft()
            if req is None:
                return did
            if req.done:  # already shed/settled
                continue
            rt = get_reqtrace()
            if rt.enabled:
                # Slot claim closes the wait phase: ``queue`` for a fresh
                # admit, ``gap.preempt`` for a preemption victim coming
                # back (the visible hole preemption punched).
                tt = req.meta.get("trace_t")
                if tt is not None:
                    name = (
                        "gap.preempt" if tt.pop("preempted_at", None)
                        else "queue"
                    )
                    now_w = time.time()
                    rt.phase(req, name, tt.get("cursor"), now_w, slot=free)
                    tt["cursor"] = now_w
            # A re-admitted request with a live checkpoint (preempted
            # victim, or a failed/replayed id resubmitted) skips tokenize,
            # page mapping, and every prefill chunk: O(1) resume.
            if self._ckpts:
                ck = self._ckpts.pop(_ckpt_key(req.id), None)
                if ck is not None:
                    self._resume(free, req, ck)
                    did = True
                    continue
            try:
                ids, plen = self.backend.tokenizer.encode(
                    req.text, self.plan.prompt_region
                )
            except Exception as exc:  # noqa: BLE001 — poison isolation
                req.fail("request_failed",
                         f"{type(exc).__name__}: {exc}"[:300])
                self._bump(failed=1)
                get_telemetry().count("serving.request_failed")
                self._fanout(req)
                continue
            slot = _Slot(
                req, np.asarray(ids, np.int32), plen,
                req.meta.get("max_new_tokens", self.plan.max_new),
            )
            if self.paged:
                mapped = self._map_pages(free, slot)
                # Pressure valve: live checkpoints pin pages eviction
                # can't touch — release the oldest until the admit fits
                # (a released checkpoint degrades its owner to prefix-hit
                # / full re-prefill resume: slower, still byte-identical).
                while not mapped and self._ckpts:
                    _, stale = self._ckpts.popitem(last=False)
                    self._release_ckpt(stale)
                    mapped = self._map_pages(free, slot)
                if not mapped:
                    # Not even eviction could free enough pages: put the
                    # request back and stop admitting this tick — in-flight
                    # sequences completing will release pages.
                    with self._cond:
                        self._queue.requeue(req)
                    with self._stats_lock:
                        self._prefix["deferred"] += 1
                    return did
            self._slots[free] = slot
            did = True
        return did

    def _maybe_preempt(self) -> Optional[int]:
        """Slot-steal for a waiting higher-priority admit that would miss
        its TTFT target; returns the freed slot index, or None ("no steal
        this tick").

        Victim = the longest-running decode in the lowest priority class
        strictly below the queue head's.  The injected-fault gate
        (``scheduler.preempt``) sits BEFORE any state mutation, so a
        fault degrades to no steal at all — never a half-released slot.
        The steal itself is the normal completion path run early: adopt
        the fully-prefilled prompt pages into the radix tree, checkpoint
        the victim's decode state, requeue the request at the head of
        its tenant queue, release the slot host-side (no device zeroing
        — nothing faulted, so the reuse invariants hold).  Resume
        restores the checkpoint into the next free slot in O(1) — zero
        prefill chunks; greedy decode is deterministic, so the resumed
        tokens are byte-identical to an undisturbed run.
        """
        if self.ttft_slo_ms <= 0.0:
            return None
        with self._cond:
            head = self._queue.peek()
            if head is None or head.done:
                return None
            est_ms = self._ttft_estimate_ms(head.priority)
        candidates = [
            (s.req.priority, -s.steps, i)
            for i, s in enumerate(self._slots)
            if s is not None and s.active and s.req.priority < head.priority
        ]
        if not candidates:
            return None
        waited_ms = (time.monotonic() - head.t_enqueue) * 1000.0
        # Unknown estimate projects to +inf: when we cannot show the head
        # makes its target by waiting, strict priority wins.
        projected_ms = waited_ms + (
            est_ms if est_ms is not None else float("inf")
        )
        if projected_ms < self.ttft_slo_ms:
            return None
        _, _, idx = min(candidates)
        victim = self._slots[idx]
        try:
            fault_point(
                "scheduler.preempt", slot=idx, steps=victim.steps,
                victim_priority=victim.req.priority,
                admit_priority=head.priority,
            )
        except Exception:  # noqa: BLE001 — degraded mode: no steal
            self._bump(preempt_faults=1)
            get_telemetry().count("serving.preempt_faults")
            return None
        # Ledger: the whole steal window counts once as preempt_overhead
        # (the embedded _checkpoint times itself — rebase on the snapshot
        # so it isn't double-counted).
        pre_t0 = time.perf_counter()
        led_before = self._led_preempt_s
        if self.paged and self._radix is not None:
            self._adopt(victim)  # no-op when prefill already adopted them
        # Checkpoint BEFORE the slot is released: the victim re-enters
        # decode in O(1) (zero prefill chunks) when its turn comes back.
        if victim.active:
            self._checkpoint(idx, victim)
        victim.req.meta["preempted"] = (
            victim.req.meta.get("preempted", 0) + 1
        )
        rt = get_reqtrace()
        if rt.enabled:
            # Close the victim's running phase at the steal and mark the
            # hole so re-admission names it ``gap.preempt``; preempted
            # traces always flush (tail sampling).
            now_w = rt.advance(
                victim.req,
                "prefill" if victim.t_first is None else "decode",
                slot=idx, steps=victim.steps, preempted=True,
            )
            tt = victim.req.meta.get("trace_t")
            if tt is not None and now_w is not None:
                tt["preempted_at"] = now_w
            rt.keep(victim.req, "preempted")
        with self._cond:
            self._queue.requeue(victim.req)
        self._free([idx])
        self._bump(preemptions=1)
        get_telemetry().count("serving.preemptions")
        self._led_preempt_s = led_before + (time.perf_counter() - pre_t0)
        return idx

    def _tpot_throttled(self, head: ServeRequest) -> bool:
        """Defer admitting ``head`` this tick while the per-token EWMA is
        over the TPOT target — shrinking the multiprogramming level
        recovers the resident streams instead of letting every one miss.
        An idle scheduler always admits (no deadlock), and an admit that
        outranks every resident (the preemption class) still lands."""
        if self.tpot_slo_ms <= 0.0:
            return False
        with self._stats_lock:
            ewma_ms = self._tpot_ewma_s * 1000.0
        if ewma_ms <= self.tpot_slo_ms:
            return False
        if self._occupied() == 0:
            return False
        max_resident = max(
            (s.req.priority for s in self._slots if s is not None),
            default=-1,
        )
        if head.priority > max_resident:
            return False
        self._bump(tpot_throttle_ticks=1)
        return True

    def _map_pages(self, idx: int, slot: _Slot) -> bool:
        """Build the slot's page-table row, sharing what the radix tree
        already holds.

        A prefix hit pins the matched full pages in place and maps them;
        the partially-filled boundary page is copy-on-write'd so shared
        tokens are never overwritten; the remainder is freshly allocated,
        evicting cold unpinned pages if the pool is full.  A failed or
        corrupted lookup (fault site ``kv_pages.lookup``) degrades to a
        full prefill with zero sharing — identical output bytes, just no
        savings.  Returns False when the pool can't cover the row even
        after eviction (the caller defers admission)."""
        import jax.numpy as jnp

        plan = self.plan
        pool = self._pool
        shared: List[int] = []
        cow_src: Optional[int] = None
        kv_shared = 0
        if self._radix is not None:
            try:
                fault_point("kv_pages.lookup", tokens=slot.plen)
                match = self._radix.match(slot.ids[:slot.plen])
                shared = list(match.pages)
                kv_shared = match.tokens
                if match.partial_tokens:
                    cow_src = match.partial_phys
            except Exception:  # noqa: BLE001 — cache-miss semantics
                shared, cow_src, kv_shared = [], None, 0
                with self._stats_lock:
                    self._prefix["fallbacks"] += 1
                get_telemetry().count("serving.prefix_lookup_fallback")
        bp = len(shared)  # slot-local index of the first private page
        for phys in shared:
            pool.pin(phys)
        if cow_src is not None:
            pool.pin(cow_src)  # protect the CoW source from eviction
        needed = plan.pages_per_slot - bp
        if pool.free_count < needed and self._radix is not None:
            evicted = self._radix.evict(pool, needed - pool.free_count)
            if evicted:
                with self._stats_lock:
                    self._prefix["evictions"] += evicted
        fresh = pool.alloc(needed)
        if fresh is None and (shared or cow_src is not None):
            # The match itself is starving the pool: its pinned shared/CoW
            # pages are exactly what eviction would have to free, while
            # the row still needs ``pages_per_slot - bp`` fresh pages — on
            # a pool sized to one slot that demand can never be met, and
            # the admit would defer forever.  Drop the match and retry as
            # a full no-sharing prefill: identical bytes, just no savings.
            for phys in shared:
                pool.unpin(phys)
            if cow_src is not None:
                pool.unpin(cow_src)
            shared, cow_src, kv_shared = [], None, 0
            bp = 0
            needed = plan.pages_per_slot
            if pool.free_count < needed and self._radix is not None:
                evicted = self._radix.evict(
                    pool, needed - pool.free_count
                )
                if evicted:
                    with self._stats_lock:
                        self._prefix["evictions"] += evicted
            fresh = pool.alloc(needed)
            if fresh is not None:
                with self._stats_lock:
                    self._prefix["fallbacks"] += 1
        if fresh is None:
            for phys in shared:
                pool.unpin(phys)
            if cow_src is not None:
                pool.unpin(cow_src)
            return False
        for phys in fresh:
            pool.pin(phys)
        row = shared + fresh
        self._table[idx] = np.asarray(row, np.int32)
        slot.pages = row
        slot.kv_shared = kv_shared
        if cow_src is not None:
            self.caches = self.runtime.copy_page(
                self.caches, jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(row[bp], jnp.int32),
            )
            pool.unpin(cow_src)
        # Skip the fully-shared prefill chunks.  The boundary chunk reruns
        # (rows below kv_shared recompute to identical bytes; rows at or
        # above it land in the CoW/fresh pages), and the final chunk always
        # runs, so the first-token logits come from the same program and
        # inputs as a cold prefill — byte-identical greedy tokens.
        C = plan.prefill_chunk
        eff = min(kv_shared, max(slot.plen, 1) - 1)
        slot.next_chunk = (eff // C) * C
        slot.skipped = slot.next_chunk // C
        with self._stats_lock:
            self._prefix["lookups"] += 1
            if kv_shared > 0:
                self._prefix["hits"] += 1
            self._prefix["tokens_shared"] += kv_shared
            self._prefix["pages_shared"] += bp
            self._prefix["chunks_skipped"] += slot.skipped
            self._prefix["fresh_pages"] += len(fresh)
            if cow_src is not None:
                self._prefix["cow_copies"] += 1
        return True

    def _adopt(self, slot: _Slot) -> None:
        """Offer a completed prefill's prompt pages to the radix tree so
        future prompts can share them; runs already cached aren't
        re-adopted (the slot's duplicates free on completion)."""
        try:
            n = min(slot.plen, self.plan.prompt_region)
            adopted = self._radix.insert(slot.ids[:n], slot.pages, self._pool)
        except Exception:  # noqa: BLE001 — cache trouble must not fail a request
            return
        if adopted:
            with self._stats_lock:
                self._prefix["adopted_pages"] += adopted

    # -------------------------------------------------------- checkpoints

    def _checkpoint(self, idx: int, slot: _Slot) -> None:
        """Snapshot one resident slot's decode state for O(1) resume.

        Paged: pin the slot's page-table row once more — the checkpoint's
        own refcount, so adoption/eviction/slot-release can't recycle the
        pages under it.  Monolithic: copy the slot's KV rows into
        stand-alone device buffers (``slots.snapshot``; no host readback).
        Replacing an existing checkpoint for the same request releases the
        stale one first; the registry is LRU-bounded so orphans (a client
        that never resubmits a failed id) can't pin memory forever.
        """
        import jax.numpy as jnp

        pre_t0 = time.perf_counter()
        key = _ckpt_key(slot.req.id)
        old = self._ckpts.pop(key, None)
        if old is not None:
            self._release_ckpt(old)
        ck = _Checkpoint(key, slot)
        if self.paged:
            self._pool.pin_row(slot.pages)
            ck.pages = list(slot.pages)
        else:
            ck.kv = self.runtime.snapshot_slot(
                self.caches, jnp.asarray(idx, jnp.int32)
            )
        self._ckpts[key] = ck
        while len(self._ckpts) > self._ckpt_limit:
            _, evicted = self._ckpts.popitem(last=False)
            self._release_ckpt(evicted)
        self._bump(checkpoints_taken=1)
        get_telemetry().count("serving.checkpoints_taken")
        self._led_preempt_s += time.perf_counter() - pre_t0

    def _release_ckpt(self, ck: _Checkpoint) -> None:
        """Drop a checkpoint's KV hold (unpin the row / free the copy)."""
        if ck.pages is not None and self._pool is not None:
            self._pool.unpin_row(ck.pages)
        ck.pages = None
        ck.kv = None
        self._bump(checkpoints_released=1)

    def _drop_ckpt_for(self, req: ServeRequest) -> None:
        """A settled request never resumes — release its checkpoint."""
        if not self._ckpts:
            return
        ck = self._ckpts.pop(_ckpt_key(req.id), None)
        if ck is not None:
            self._release_ckpt(ck)

    def _resume(self, idx: int, req: ServeRequest, ck: _Checkpoint) -> None:
        """Re-enter decode from a checkpoint in O(1) — zero prefill chunks.

        Paged: write the checkpointed row back into the table; the
        checkpoint's page pins transfer to the slot (the release path
        unpins exactly once either way).  Monolithic: ``slots.restore``
        writes the KV copy into the granted slot — any slot, the layout
        is slot-index independent.  Greedy decode then continues from the
        checkpointed step/carry/done, so the remaining tokens are
        byte-identical to an undisturbed run.
        """
        import jax.numpy as jnp

        pre_t0 = time.perf_counter()
        slot = _Slot(req, ck.ids, ck.plen, ck.budget)
        slot.tokens = list(ck.tokens)
        slot.steps = ck.steps
        slot.carry = ck.carry
        slot.done = ck.done
        slot.t_first = ck.t_first
        slot.next_chunk = -1  # fully prefilled: straight to decode
        slot.active = True
        chunks = len(self.runtime.prompt_chunks(ck.plen))
        slot.skipped = chunks
        if self.paged:
            row = list(ck.pages)
            ck.pages = None  # pins transfer to the slot — no unpin here
            self._table[idx] = np.asarray(row, np.int32)
            slot.pages = row
            slot.kv_shared = ck.plen
            with self._stats_lock:
                self._prefix["chunks_skipped"] += chunks
        else:
            keys, values, length = ck.kv
            ck.kv = None
            self.caches = self.runtime.restore_slot(
                self.caches, keys, values, jnp.asarray(idx, jnp.int32),
                length,
            )
        self._slots[idx] = slot
        self._bump(resumed_o1=1, resume_chunks_skipped=chunks)
        get_telemetry().count("serving.resumed_o1")
        self._led_preempt_s += time.perf_counter() - pre_t0

    # ------------------------------------------------------------ prefill

    def _device_prefill(self, idx: int, slot: _Slot):
        """One prefill chunk for one slot (the retried/faulted edge).

        Returns the first-token logits argmax as a *device* array —
        forcing it here would serialize every slot's prefill behind a
        host readback; the caller batches the readbacks after all
        mid-prefill slots have dispatched.
        """
        import jax.numpy as jnp

        fault_point("decode.step", phase="prefill", slot=idx)
        start = slot.next_chunk
        C = self.plan.prefill_chunk
        is_last = start + C >= min(max(slot.plen, 1), self.plan.prompt_region)
        chunk = jnp.asarray(slot.ids[start:start + C])
        length_after = min(start + C, self.plan.prompt_region)
        last_index = max(0, min(slot.plen - 1 - start, C - 1))
        if self.paged:
            caches, first = self.runtime.prefill_chunk(
                self.backend.params, self.caches,
                jnp.asarray(self._table[idx]),
                jnp.asarray(idx, jnp.int32), chunk,
                jnp.asarray(start, jnp.int32),
                jnp.asarray(length_after, jnp.int32),
                jnp.asarray(last_index, jnp.int32),
            )
        else:
            caches, first = self.runtime.prefill_chunk(
                self.backend.params, self.caches,
                jnp.asarray(idx, jnp.int32), chunk,
                jnp.asarray(start, jnp.int32),
                jnp.asarray(length_after, jnp.int32),
                jnp.asarray(last_index, jnp.int32),
            )
        return caches, first, is_last

    def _prefill_tick(self) -> bool:
        """Advance every mid-prefill slot by ONE chunk (bounding the
        latency spike a long prompt injects between decode dispatches)."""
        import jax

        tel = get_telemetry()
        rt = get_reqtrace()
        did = False
        finishing = []  # (idx, slot, first_token_device_array)
        for idx, slot in enumerate(self._slots):
            if slot is None or slot.next_chunk < 0:
                continue
            did = True
            rt_t0 = time.time() if rt.enabled else None
            pf_t0 = time.perf_counter()
            try:
                with watchdog.watch("decode.dispatch", kind="decode"):
                    caches, first, is_last = self._retry.call(
                        self._device_prefill, idx, slot, site="decode.step"
                    )
            except Exception as exc:  # noqa: BLE001 — poison isolation
                self._led_prefill_s += time.perf_counter() - pf_t0
                # The poison prompt fails ALONE: its slot is freed (and
                # zeroed) while co-resident slots keep decoding.
                slot.req.fail("request_failed",
                              f"{type(exc).__name__}: {exc}"[:300])
                self._bump(failed=1)
                tel.count("serving.request_failed")
                self._fanout(slot.req)
                self._free([idx], zero=True)
                continue
            self._led_prefill_s += time.perf_counter() - pf_t0
            if slot.kv_shared or slot.skipped:
                self._led_chunks_shared += 1
            else:
                self._led_chunks_cold += 1
            self.caches = caches
            self._bump(prefill_dispatches=1)
            if rt.enabled:
                # Overlapping detail (never in the attribution sum): one
                # span per prefill chunk dispatch.
                rt.detail(
                    slot.req, "prefill.chunk", rt_t0, time.time(),
                    slot=idx,
                    chunk=slot.next_chunk // self.plan.prefill_chunk,
                )
            if is_last:
                finishing.append((idx, slot, first))
            else:
                slot.next_chunk += self.plan.prefill_chunk
        if finishing:
            pf_t0 = time.perf_counter()
            firsts = jax.device_get([f for _, _, f in finishing])
            self._led_prefill_s += time.perf_counter() - pf_t0
            for (idx, slot, _), first in zip(finishing, firsts):
                slot.next_chunk = -1
                if self.paged and self._radix is not None:
                    self._adopt(slot)
                slot.t_first = time.monotonic()
                ttft = slot.t_first - slot.req.t_enqueue
                ttft_miss = (
                    self.ttft_slo_ms > 0.0
                    and ttft * 1000.0 > self.ttft_slo_ms
                )
                self._ttft.observe(ttft)
                with self._stats_lock:
                    self._ttft_ewma_s = (
                        ttft if self._ttft_ewma_s == 0.0
                        else 0.8 * self._ttft_ewma_s + 0.2 * ttft
                    )
                    if ttft_miss:
                        self._stats["ttft_slo_misses"] += 1
                tel.observe("serving.ttft_seconds", ttft,
                            buckets=_LATENCY_BUCKETS)
                if rt.enabled:
                    rt.advance(
                        slot.req, "prefill", slot=idx,
                        chunks=len(self.runtime.prompt_chunks(slot.plen))
                        - slot.skipped,
                        chunks_skipped=slot.skipped,
                        kv_shared=slot.kv_shared,
                        pages=len(slot.pages or ()),
                    )
                    if ttft_miss:
                        rt.keep(slot.req, "ttft_slo_miss")
                slot.carry = int(first)
                if slot.carry == self.runtime.eos_id:
                    # The model's very first token is EOS: empty
                    # generation, settled without a decode step.
                    self._settle(idx, slot)
                else:
                    slot.active = True
        return did

    # ------------------------------------------------------------- decode

    def _device_decode(self, tokens, plens, steps, budgets, done, active):
        fault_point("decode.step", phase="decode",
                    active=int(active.sum()))
        import jax.numpy as jnp

        if self.paged:
            return self.runtime.decode_step(
                self.backend.params, self.caches, jnp.asarray(self._table),
                jnp.asarray(tokens), jnp.asarray(plens), jnp.asarray(steps),
                jnp.asarray(budgets), jnp.asarray(done), jnp.asarray(active),
            )
        return self.runtime.decode_step(
            self.backend.params, self.caches,
            jnp.asarray(tokens), jnp.asarray(plens), jnp.asarray(steps),
            jnp.asarray(budgets), jnp.asarray(done), jnp.asarray(active),
        )

    def _decode_tick(self) -> bool:
        occupied = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None and s.active
        ]
        if not occupied:
            return False
        if self.speculate_k > 0:
            K = self.speculate_k + 1
            # A verify dispatch writes K KV rows from every participating
            # slot's step, so a slot within K rows of the decode region's
            # end (the last k steps of a max_new-budget generation) can't
            # take the block write without clobbering committed rows —
            # those rare ticks run the plain program instead, byte-
            # identical either way.
            if all(s.steps + K <= self.plan.max_new for _, s in occupied):
                try:
                    fault_point("spec.draft", active=len(occupied),
                                k=self.speculate_k)
                    drafts = {i: self._draft(s) for i, s in occupied}
                except Exception:  # noqa: BLE001 — degrade to plain decode
                    # A broken drafter costs this tick's speedup, never a
                    # token: the plain program commits the carry exactly
                    # as non-speculative decode would.
                    with self._stats_lock:
                        self._spec["fallbacks"] += 1
                    get_telemetry().count("serving.spec_fallbacks")
                else:
                    if any(drafts.values()):
                        return self._verify_tick(occupied, drafts)
                    # Every slot declined to draft (streams currently
                    # unpredictable): the 1-step plain program commits
                    # the same carries at a fraction of the k+1-step
                    # verify cost.
            with self._stats_lock:
                self._spec["plain_ticks"] += 1
        return self._plain_decode_tick(occupied)

    def _draft(self, s: _Slot) -> List[int]:
        """Propose draft tokens for one slot.

        The per-slot draft cache is the memoized prompt+emitted+carry
        stream (invalidated by plain-tick commits, extended in place by
        verify commits); the slot's acceptance EWMA adapts the proposed
        depth inside the fixed ``k+1`` block shape — fewer drafts for a
        slot that keeps rejecting, back to full depth as acceptance
        recovers, zero retraces throughout.
        """
        if s.hist is None:
            s.hist = [int(t) for t in s.ids[:s.plen]]
            s.hist.extend(s.tokens)
            s.hist.append(s.carry)
        if s.accept_ewma < _SPECULATE_EWMA_MIN:
            # The stream is currently unpredictable: a k+1-step verify
            # dispatch would net barely more than the 1-step plain
            # program at k+1 times the device cost.  Proposing nothing
            # lets the tick degrade to plain decode; a depth-1 probe
            # every few ticks re-measures the stream so the EWMA can
            # climb back once it turns repetitive.
            s.probe += 1
            if s.probe < _PROBE_EVERY_TICKS:
                return []
            s.probe = 0
            depth = 1
        else:
            depth = max(1, min(
                self.speculate_k,
                int(round(self.speculate_k * s.accept_ewma)),
            ))
        # Tokens past the slot's budget can never commit — don't draft
        # them (the commit-side clamp would discard them anyway).
        depth = min(depth, s.budget - s.steps - 1)
        if depth <= 0:
            return []
        return _draft_from_history(s.hist, depth)

    def _device_verify(self, tokens_blk, plens, steps):
        fault_point("decode.step", phase="verify", k=self.speculate_k)
        import jax.numpy as jnp

        if self.paged:
            return self.runtime.verify_block(
                self.backend.params, self.caches, jnp.asarray(self._table),
                jnp.asarray(tokens_blk), jnp.asarray(plens),
                jnp.asarray(steps),
            )
        return self.runtime.verify_block(
            self.backend.params, self.caches,
            jnp.asarray(tokens_blk), jnp.asarray(plens), jnp.asarray(steps),
        )

    def _verify_tick(self, occupied, drafts: Dict[int, List[int]]) -> bool:
        """One speculative decode tick: score every slot's carry+drafts
        block in a single verify dispatch, commit each slot's longest
        accepted prefix plus the first-mismatch correction token.

        Acceptance is exact equality against the device argmax under the
        same committed context, and the correction token is that argmax
        itself — so every committed token equals what plain stepping
        would have produced, and every dispatch nets >= 1 token per
        participating slot (the carry always commits).
        """
        tel = get_telemetry()
        n = self.plan.n_slots
        K = self.speculate_k + 1
        tokens_blk = np.zeros((n, K), np.int32)
        plens = np.zeros(n, np.int32)
        steps = np.zeros(n, np.int32)
        for i, s in occupied:
            tokens_blk[i, 0] = s.carry
            for j, t in enumerate(drafts.get(i) or ()):
                tokens_blk[i, 1 + j] = t
            plens[i] = s.plen
            steps[i] = s.steps
        t0 = time.perf_counter()
        try:
            with watchdog.watch("decode.dispatch", kind="decode"):
                caches, preds = self._retry.call(
                    self._device_verify, tokens_blk, plens, steps,
                    site="decode.step",
                )
            import jax

            preds = jax.device_get(preds)
        except Exception as exc:  # noqa: BLE001 — the loop must survive
            detail = f"{type(exc).__name__}: {exc}"[:300]
            for i, s in occupied:
                s.req.fail("request_failed", detail)
                self._fanout(s.req)
            self._bump(failed=len(occupied))
            tel.count("serving.request_failed", len(occupied))
            self._free([i for i, _ in occupied], zero=True)
            return True
        decode_s = time.perf_counter() - t0
        self.caches = caches
        occ = len(occupied) / n
        eos = self.runtime.eos_id
        committed = drafted_total = accepted_total = 0
        rates: List[float] = []
        freed: List[int] = []
        for i, s in occupied:
            d = drafts.get(i) or []
            row = preds[i]
            acc = 0
            while acc < len(d) and d[acc] == int(row[acc]):
                acc += 1
            # Longest accepted prefix + budget freeze: never commit past
            # the slot's budget, and the carry always commits (>= 1).
            emit_n = min(acc + 1, s.budget - s.steps)
            emitted = ([s.carry] + d)[:emit_n]
            s.tokens.extend(emitted)
            s.steps += emit_n
            new_carry = int(row[emit_n - 1])
            if s.hist is not None:
                # The cache's tail was the old carry (= emitted[0]):
                # extend with the rest of the block and the new carry.
                s.hist.extend(emitted[1:])
                s.hist.append(new_carry)
            s.carry = new_carry
            if d:
                rate = acc / len(d)
                s.accept_ewma = 0.8 * s.accept_ewma + 0.2 * rate
                rates.append(rate)
                drafted_total += len(d)
                accepted_total += acc
                tt = s.req.meta.get("trace_t")
                if tt is not None:
                    # Per-request speculation outcome (settle attaches it
                    # to the decode phase's attributes).
                    tt["spec_drafted"] = tt.get("spec_drafted", 0) + len(d)
                    tt["spec_accepted"] = tt.get("spec_accepted", 0) + acc
            committed += emit_n
            saw_eos = eos in emitted
            if saw_eos:
                s.done = True
            if saw_eos or s.steps >= s.budget:
                freed.append(i)
        with self._stats_lock:
            self._stats["decode_dispatches"] += 1
            self._stats["decode_seconds"] += decode_s
            self._stats["tokens_generated"] += committed
            self._occupancy.observe(occ)
            self._spec["dispatches"] += 1
            self._spec["drafted"] += drafted_total
            self._spec["accepted"] += accepted_total
            self._spec["tokens_committed"] += committed
            for rate in rates:
                self._accept_hist.observe(rate)
            self._block_hist.observe(committed / len(occupied))
        # Ledger attribution: the verify dispatch's useful slice is the
        # committed-token fraction of the [n_occupied, k+1] block; the
        # rest of the measured device time is drafted-but-rejected work.
        self._led_decode_s += decode_s
        self._led_committed += committed
        self._led_useful_frac = committed / max(
            1, len(occupied) * tokens_blk.shape[1]
        )
        self._rates["tokens_s"].mark(committed)
        tel.observe("serving.slot_occupancy", occ,
                    buckets=_OCCUPANCY_BUCKETS)
        if self.checkpoint_interval > 0:
            with self._stats_lock:
                dispatches = self._stats["decode_dispatches"]
            if dispatches % self.checkpoint_interval == 0:
                settling = set(freed)
                for i, s in occupied:
                    if i not in settling:
                        self._checkpoint(i, s)
        for i in freed:
            self._settle(i, self._slots[i])
        return True

    def _plain_decode_tick(self, occupied) -> bool:
        tel = get_telemetry()
        n = self.plan.n_slots
        tokens = np.zeros(n, np.int32)
        plens = np.zeros(n, np.int32)
        steps = np.zeros(n, np.int32)
        budgets = np.ones(n, np.int32)
        done = np.zeros(n, bool)
        active = np.zeros(n, bool)
        for i, s in occupied:
            tokens[i] = s.carry
            plens[i] = s.plen
            steps[i] = s.steps
            budgets[i] = s.budget
            done[i] = s.done
            active[i] = True
        t0 = time.perf_counter()
        try:
            with watchdog.watch("decode.dispatch", kind="decode"):
                caches, tok_out, steps_out, done_out, emitted = (
                    self._retry.call(
                        self._device_decode, tokens, plens, steps, budgets,
                        done, active, site="decode.step",
                    )
                )
            import jax

            # One batched D2H readback instead of four serialized ones.
            emitted, tok_out, steps_out, done_out = jax.device_get(
                (emitted, tok_out, steps_out, done_out)
            )
        except Exception as exc:  # noqa: BLE001 — the loop must survive
            # Persistent decode failure: every in-flight request gets a
            # structured error; the slots are freed; the server lives on.
            detail = f"{type(exc).__name__}: {exc}"[:300]
            for i, s in occupied:
                s.req.fail("request_failed", detail)
                self._fanout(s.req)
            self._bump(failed=len(occupied))
            tel.count("serving.request_failed", len(occupied))
            self._free([i for i, _ in occupied], zero=True)
            return True
        decode_s = time.perf_counter() - t0
        self.caches = caches
        occ = len(occupied) / n
        with self._stats_lock:
            self._stats["decode_dispatches"] += 1
            self._stats["decode_seconds"] += decode_s
            self._occupancy.observe(occ)
        tel.observe("serving.slot_occupancy", occ,
                    buckets=_OCCUPANCY_BUCKETS)
        freed: List[int] = []
        emitted_total = 0
        for i, s in occupied:
            emitted_n = int(steps_out[i]) - s.steps
            s.tokens.extend(int(t) for t in emitted[:emitted_n, i])
            s.steps = int(steps_out[i])
            s.carry = int(tok_out[i])
            s.done = bool(done_out[i])
            s.hist = None  # draft cache is stale once the carry moved
            emitted_total += emitted_n
            self._bump(tokens_generated=emitted_n)
            saw_eos = emitted_n > 0 and self.runtime.eos_id in s.tokens[-emitted_n:]
            if saw_eos or s.steps >= s.budget:
                freed.append(i)
        self._led_decode_s += decode_s
        self._led_committed += emitted_total
        self._rates["tokens_s"].mark(emitted_total)
        # Periodic checkpoint tick: refresh still-running slots so a
        # later failure loses at most ``checkpoint_interval`` dispatches
        # of work — a resubmitted id resumes from here, not the prompt.
        if self.checkpoint_interval > 0:
            with self._stats_lock:
                dispatches = self._stats["decode_dispatches"]
            if dispatches % self.checkpoint_interval == 0:
                settling = set(freed)
                for i, s in occupied:
                    if i not in settling:
                        self._checkpoint(i, s)
        for i in freed:
            self._settle(i, self._slots[i])
        return True

    # ------------------------------------------------------------- settle

    def _settle(self, idx: int, slot: _Slot) -> None:
        """Emit the reply, record TTFT/TPOT, free the slot."""
        tel = get_telemetry()
        eos = self.runtime.eos_id
        toks = slot.tokens
        if eos in toks:
            toks = toks[:toks.index(eos)]
        toks = toks[:slot.budget]
        text = self.backend.tokenizer.decode(toks)
        now = time.monotonic()
        tpot_miss = False
        if slot.t_first is not None and len(toks) > 1:
            tpot = (now - slot.t_first) / (len(toks) - 1)
            tpot_miss = (
                self.tpot_slo_ms > 0.0 and tpot * 1000.0 > self.tpot_slo_ms
            )
            self._tpot.observe(tpot)
            with self._stats_lock:
                self._tpot_ewma_s = (
                    tpot if self._tpot_ewma_s == 0.0
                    else 0.8 * self._tpot_ewma_s + 0.2 * tpot
                )
                led = self._tenant_ledger(slot.req.tenant)
                prev_ms = led.get("tpot_ewma_ms", 0.0)
                tpot_ms = tpot * 1000.0
                led["tpot_ewma_ms"] = round(
                    tpot_ms if prev_ms == 0.0
                    else 0.8 * prev_ms + 0.2 * tpot_ms, 6
                )
                if tpot_miss:
                    self._stats["tpot_slo_misses"] += 1
            tel.observe("serving.tpot_seconds", tpot,
                        buckets=_TOKEN_BUCKETS)
        rt = get_reqtrace()
        if rt.enabled:
            # Close the decode phase BEFORE succeed() stamps the settle
            # clock (the complete() hook), so the cursor partition stays
            # contiguous: ... decode | commit | reply.
            tt = slot.req.meta.get("trace_t") or {}
            attrs: Dict[str, Any] = {
                "slot": idx, "tokens": len(toks), "steps": slot.steps,
            }
            if "spec_drafted" in tt:
                attrs["spec_drafted"] = tt["spec_drafted"]
                attrs["spec_accepted"] = tt.get("spec_accepted", 0)
            rt.advance(slot.req, "decode", **attrs)
            if tpot_miss:
                rt.keep(slot.req, "tpot_slo_miss")
        slot.req.succeed(
            text=text,
            label=normalise_label(text) if text.strip() else "Neutral",
            tokens=len(toks),
        )
        self._bump(completed=1)
        with self._stats_lock:
            self._tenant_ledger(slot.req.tenant)["completed"] += 1
            if slot.req.meta.get("preempted"):
                self._stats["resumed"] += 1
        tel.count("serving.decode_completed")
        tel.observe("serving.request_seconds", now - slot.req.t_enqueue,
                    buckets=_LATENCY_BUCKETS)
        self._drop_ckpt_for(slot.req)
        self._fanout(slot.req)
        self._free([idx])

    def _free(self, indices: List[int], zero: bool = False) -> None:
        """Release slots for reuse.

        Normal completion is host-only: the next occupant's prefill
        overwrites every prompt row it will attend to, the decode step
        overwrites row ``R + t`` before attending to it, and everything
        else is masked to an exact-zero attention contribution — so the
        device zeroing is semantically redundant (the continuous-vs-
        static byte-identity tests run *with* slot reuse).  Failure
        paths pass ``zero=True`` to hard-zero a poisoned slot's rows via
        the ``slots.free`` program anyway: after a fault nothing about
        the slot's contents is trusted, including the invariants above.

        Paged: completion additionally unpins the slot's pages (shared
        pages stay resident for the radix tree; exclusively-owned pages
        return to the free list) and points the table row back at the
        trash page.  The failure path hard-zeroes only pages the slot
        owned exclusively — shared/tree pages hold prompt KV written by
        prefill dispatches that *succeeded*, and decode never writes
        below ``prompt_region``.
        """
        import jax.numpy as jnp

        mask = np.zeros(self.plan.n_slots, bool)
        released: List[int] = []
        for i in indices:
            mask[i] = True
            slot = self._slots[i]
            if self.paged and slot is not None and slot.pages is not None:
                released.extend(slot.pages)
                self._table[i] = self.plan.trash_page
            self._slots[i] = None
        if self.paged:
            pool = self._pool
            for phys in released:
                pool.unpin(phys)
            if zero:
                page_mask = np.zeros(self.plan.n_pages + 1, bool)
                for phys in released:
                    if pool.slot_refs[phys] == 0 and not pool.in_tree[phys]:
                        page_mask[phys] = True
                self.caches = self.runtime.free_pages(
                    self.caches, jnp.asarray(page_mask), jnp.asarray(mask)
                )
            return
        if zero:
            self.caches = self.runtime.free_slots(
                self.caches, jnp.asarray(mask)
            )

    # ----------------------------------------------------------- readouts

    def _publish_gauges(self) -> None:
        tel = get_telemetry()
        active = sum(
            1 for s in self._slots if s is not None and s.active
        )
        prefilling = sum(
            1 for s in self._slots if s is not None and s.next_chunk >= 0
        )
        with self._cond:
            backlog = len(self._queue) + prefilling
        tel.gauge("serving.decode.active_slots", active)
        tel.gauge("serving.decode.free_slots",
                  self.plan.n_slots - self._occupied())
        tel.gauge("serving.decode.prefill_backlog", backlog)
        if self.paged:
            tel.gauge("serving.decode.pages_free", self._pool.free_count)

    def stats(self) -> Dict[str, Any]:
        """JSON-able snapshot for the ``stats`` control op, the manifest's
        ``serving.decode`` section, and the ``continuous`` bench suite."""
        with self._stats_lock:
            out: Dict[str, Any] = dict(self._stats)
            ttft = self._ttft.as_dict()
            tpot = self._tpot.as_dict()
            occ = self._occupancy.as_dict()
            spec = dict(self._spec)
            accept_hist = self._accept_hist.as_dict()
            block_hist = self._block_hist.as_dict()
        with self._cond:
            backlog = len(self._queue)
        active = sum(1 for s in self._slots if s is not None and s.active)
        prefilling = sum(
            1 for s in self._slots if s is not None and s.next_chunk >= 0
        )
        decode_s = out.pop("decode_seconds")
        out.update(
            n_slots=self.plan.n_slots,
            prefill_chunk=self.plan.prefill_chunk,
            prompt_region=self.plan.prompt_region,
            max_new_tokens=self.plan.max_new,
            decode_span=self.plan.decode_span,
            active_slots=active,
            free_slots=self.plan.n_slots - self._occupied(),
            prefill_backlog=backlog + prefilling,
            decode_seconds=round(decode_s, 6),
            tokens_per_s=(
                round(out["tokens_generated"] / decode_s, 3)
                if decode_s > 0 else None
            ),
            ttft=ttft,
            tpot=tpot,
            slot_occupancy_hist=occ,
            compiled_variants=self.runtime.compiled_variants(),
            warmup=self._warmup_record,
            kv_backend="paged" if self.paged else "slots",
            checkpoint_interval=self.checkpoint_interval,
            checkpoints_live=len(self._ckpts),
            rates={
                "window_s": self._rates["req_s"].tau_s,
                "req_s": self._rates["req_s"].rate(),
                "tokens_s": self._rates["tokens_s"].rate(),
                "shed_s": self._rates["shed_s"].rate(),
            },
        )
        out["ttft_ewma_ms"] = round(self._ttft_ewma_s * 1000.0, 3)
        out["tpot_ewma_ms"] = round(self._tpot_ewma_s * 1000.0, 3)
        spec.update(
            enabled=self.speculate_k > 0,
            k=self.speculate_k,
            acceptance_rate=(
                round(spec["accepted"] / spec["drafted"], 4)
                if spec["drafted"] else None
            ),
            accepted_tokens_per_dispatch=(
                round(spec["tokens_committed"] / spec["dispatches"], 4)
                if spec["dispatches"] else None
            ),
            acceptance_rate_hist=accept_hist,
            accepted_tokens_hist=block_hist,
        )
        out["speculation"] = spec
        if self.paged:
            plan = self.plan
            with self._stats_lock:
                prefix = dict(self._prefix)
            lookups = prefix["lookups"]
            hits = prefix["hits"]
            page_bytes = self.runtime.page_bytes()
            prefix.update(
                enabled=self._radix is not None,
                misses=lookups - hits,
                hit_rate=round(hits / lookups, 4) if lookups else None,
                bytes_saved=(
                    prefix["tokens_shared"] * self.runtime.kv_token_bytes()
                ),
                tree_pages=(
                    self._radix.page_count() if self._radix is not None else 0
                ),
                pages_free=self._pool.free_count,
                # Private HBM footprint one admitted sequence actually
                # cost, vs the unshared pages_per_slot * page_bytes.
                hbm_bytes_per_seq=(
                    round(prefix["fresh_pages"] * page_bytes / lookups)
                    if lookups else None
                ),
                hbm_bytes_per_seq_unshared=plan.pages_per_slot * page_bytes,
            )
            # KV quantization accounting: the pool's resident bytes under
            # the active scheme vs the bf16 layout it replaces.  The
            # byte counters above (kv_token_bytes / page_bytes /
            # hbm_bytes_per_seq) are already scheme-aware — int8 counts
            # codes plus the per-(page, row) f32 scales.
            pool_bytes = self.runtime.pool_bytes()
            unq_ratio = (
                self.runtime.kv_token_bytes_unquantized()
                / self.runtime.kv_token_bytes()
            )
            pool_unq = round(pool_bytes * unq_ratio)
            out.update(
                page_size=plan.page_size,
                kv_pages=plan.n_pages,
                pages_per_slot=plan.pages_per_slot,
                page_bytes=page_bytes,
                prefix_cache=prefix,
                kv_quant={
                    "scheme": self.kv_quant,
                    "degraded": self._kv_quant_degraded,
                    "pool_bytes": pool_bytes,
                    "pool_bytes_unquantized": pool_unq,
                    "bytes_saved": pool_unq - pool_bytes,
                    "hbm_bytes_per_seq": (
                        plan.pages_per_slot * page_bytes
                    ),
                    "hbm_bytes_per_seq_unquantized": round(
                        plan.pages_per_slot * page_bytes * unq_ratio
                    ),
                    "compression": round(unq_ratio, 4),
                },
            )
        # Engine goodput ledger: per-tick wall-time attribution +
        # occupancy + per-tenant chip-seconds (manifest
        # ``serving.decode.ledger``; flattened counters merge fleet-wide
        # through the metrics plane's stats-poll ingest).
        out["ledger"] = self._ledger.snapshot()
        if self.response_cache is not None:
            out["response_cache"] = self.response_cache.stats()
        return out

    def _ledger_occupancy_sample(self) -> Dict[str, Any]:
        """Occupancy snapshot for the ledger: read off the structures
        that already know the truth (slots, page pool, radix tree, KV
        byte accounting).  Called at flush/stats time only — never on
        the per-tick hot path."""
        active = self._occupied()
        occ: Dict[str, Any] = {
            "slots_active": active,
            "slots_total": self.plan.n_slots,
            "slot_occupancy": round(active / self.plan.n_slots, 6),
        }
        if self.paged and self._pool is not None:
            pool = self._pool
            pinned = sum(1 for r in pool.slot_refs if r > 0)
            shared = sum(1 for r in pool.slot_refs if r > 1)
            in_tree = sum(1 for t in pool.in_tree if t)
            # Boundary-page fragmentation: tokens reserved but unfilled
            # in each occupied slot's last mapped page.
            P = self.plan.page_size
            frag = 0
            for s in self._slots:
                if s is None or not s.pages:
                    continue
                used = min(s.plen + s.steps, len(s.pages) * P)
                frag += len(s.pages) * P - used
            occ.update(
                pages_total=pool.n_pages,
                pages_free=pool.free_count,
                pages_pinned=pinned,
                pages_shared=shared,
                pages_in_tree=in_tree,
                boundary_fragmentation_tokens=frag,
            )
            if self._radix is not None:
                occ.update(
                    radix_nodes=self._radix.node_count(),
                    radix_pinned_tokens=self._radix.token_count(),
                )
            occ.update(
                kv_pool_bytes=self.runtime.pool_bytes(),
                kv_pool_bytes_unquantized=round(
                    self.runtime.pool_bytes()
                    * self.runtime.kv_token_bytes_unquantized()
                    / self.runtime.kv_token_bytes()
                ),
            )
        else:
            kv_bytes = self.runtime.kv_bytes()
            occ.update(
                kv_pool_bytes=kv_bytes,
                kv_pool_bytes_unquantized=kv_bytes,
            )
        return occ

    def slo_snapshot(self) -> Dict[str, Any]:
        """The manifest's ``serving.slo.decode`` contribution: targets,
        preemption/throttle counters, shed taxonomy, and the per-tenant
        ledger.  Empty when the SLO layer was neither configured nor
        exercised (only-when-used, like the batcher's)."""
        with self._stats_lock:
            tenants = {t: dict(v) for t, v in self._tenants.items()}
            sheds = {
                key: self._stats[key]
                for key in ("shed_queue_full", "shed_slo_unattainable",
                            "shed_tenant_budget", "shed_evicted")
            }
            counters = {
                key: self._stats[key]
                for key in ("preemptions", "preempt_faults", "resumed",
                            "tpot_throttle_ticks", "ttft_slo_misses",
                            "tpot_slo_misses")
            }
        # Chip-second attribution (engine ledger): what each tenant's
        # slot share actually cost in engine time — the number the
        # admission ledgers alone can't provide.
        chip = self._ledger.chip_seconds()
        for t, v in tenants.items():
            v["chip_seconds"] = round(chip.get(t, 0.0), 6)
        configured = (
            self.ttft_slo_ms > 0.0 or self.tpot_slo_ms > 0.0
            or self.tenant_budget > 0.0
        )
        exercised = (
            any(sheds.values()) or any(counters.values())
            or any(t != DEFAULT_TENANT for t in tenants)
        )
        if not configured and not exercised:
            return {}
        return {
            "ttft_slo_ms": self.ttft_slo_ms,
            "tpot_slo_ms": self.tpot_slo_ms,
            "tenant_budget_req_s": self.tenant_budget,
            "default_priority": self.default_priority,
            "ttft_ewma_ms": round(self._ttft_ewma_s * 1000.0, 3),
            "tpot_ewma_ms": round(self._tpot_ewma_s * 1000.0, 3),
            **counters,
            "sheds": sheds,
            "tenants": tenants,
        }
