"""Continuous-batching scheduler: admit → prefill → decode over KV slots.

The dynamic batcher (``batcher.py``) coalesces *independent* requests
into one-shot batches; generation is different — a request occupies the
device for its whole output length, and a static batch holds every row
hostage to the slowest one.  This scheduler runs the iteration-level
loop instead (the continuous-batching idea of Orca/vLLM, shaped for
fixed-program TPU dispatch): ``n_slots`` sequences decode side by side
in the slot-indexed KV cache (``ops/kv_slots.py``), an admitted request
claims a free slot *mid-flight*, its prompt is prefilled in fixed-size
chunks between decode dispatches, and EOS or token-budget completion
frees the slot immediately so the reply is emitted while neighbors keep
decoding.  No device program ever retraces as requests come and go.

Reused ``DynamicBatcher`` machinery: the same bounded-admission contract
(``queue_full`` shed under overload), the same structured-error poison
isolation (a request whose prefill raises fails alone; co-resident
slots keep decoding), the same ``RetryPolicy`` around the device edge
(site ``decode.step``, the ``chaos`` suite's injection point), and the
same watchdog instrumentation (kind ``decode`` → taxonomy
``decode_stall``: a wedged dispatch trips the heartbeat monitor instead
of hanging the server mutely).

Telemetry: slot-occupancy gauge + histogram, tokens/s, and TTFT/TPOT
reservoir quantiles (``serving.ttft_seconds`` / ``serving.tpot_seconds``
land in the run manifest next to the batcher's latency quantiles, where
``telemetry-report`` picks them up).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy
from music_analyst_tpu.serving.batcher import (
    _LATENCY_BUCKETS,
    _OCCUPANCY_BUCKETS,
    ServeRequest,
    resolve_max_queue,
    resolve_prefill_chunk,
    resolve_slots,
)
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.telemetry.core import Histogram
from music_analyst_tpu.utils.labels import normalise_label

# Per-token latency buckets: decode steps are ms-scale on-device, up to
# second-scale on the CPU-emulated mesh.
_TOKEN_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0,
)


class _Slot:
    """Host-side state of one occupied KV slot."""

    __slots__ = ("req", "ids", "plen", "next_chunk", "budget", "steps",
                 "tokens", "carry", "done", "active", "t_first")

    def __init__(self, req: ServeRequest, ids: np.ndarray, plen: int,
                 budget: int) -> None:
        self.req = req
        self.ids = ids
        self.plen = int(plen)
        self.next_chunk = 0        # next prefill chunk offset; -1 = prefilled
        self.budget = int(budget)
        self.steps = 0             # decode steps taken so far
        self.tokens: List[int] = []  # emitted token ids
        self.carry = 0             # current input token for the next step
        self.done = False          # emitted EOS (static-path done semantics)
        self.active = False        # in the decode phase
        self.t_first: Optional[float] = None  # first-token wall time (TTFT)


class ContinuousScheduler:
    """Admit→prefill→decode loop over a backend's slot runtime.

    ``backend`` must expose ``slot_runtime(...)`` (capability probe),
    ``params``, and ``tokenizer`` — ``models/llama.py``'s zero-shot
    classifier is the canonical one.  Usable two ways: synchronously
    (``submit(...)`` then :meth:`run_until_idle`, the batch-generation
    path) or threaded (:meth:`start` / :meth:`drain`, the server path).
    """

    def __init__(
        self,
        backend,
        n_slots: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        prompt_region: Optional[int] = None,
        max_new_tokens: int = 16,
        decode_span: int = 4,
        max_queue: Optional[int] = None,
    ) -> None:
        self.backend = backend
        self.n_slots = resolve_slots(n_slots)
        self.prefill_chunk = resolve_prefill_chunk(prefill_chunk)
        self.max_queue = resolve_max_queue(max_queue)
        self.runtime = backend.slot_runtime(
            n_slots=self.n_slots,
            prefill_chunk=self.prefill_chunk,
            max_new_tokens=max_new_tokens,
            prompt_region=prompt_region,
            decode_span=decode_span,
        )
        self.plan = self.runtime.plan
        self.caches = self.runtime.init_caches()
        self._slots: List[Optional[_Slot]] = [None] * self.plan.n_slots
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._retry = RetryPolicy(base_s=0.05, cap_s=1.0)
        self._ttft = Histogram(_LATENCY_BUCKETS)
        self._tpot = Histogram(_TOKEN_BUCKETS)
        self._occupancy = Histogram(_OCCUPANCY_BUCKETS)
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "shed": 0, "completed": 0, "failed": 0,
            "tokens_generated": 0, "prefill_dispatches": 0,
            "decode_dispatches": 0, "decode_seconds": 0.0,
            "queue_depth_max": 0,
        }
        self._warmup_record: Optional[Dict[str, Any]] = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ContinuousScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="decode-loop", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting, run every queued/in-flight request to its reply
        (or a structured error), stop the loop thread."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        self._thread = None
        if thread is None:
            # Synchronous use: drain means "finish the backlog inline".
            self.run_until_idle()

    @property
    def draining(self) -> bool:
        return self._draining

    def warmup(self) -> Dict[str, Any]:
        """Compile all three slot programs before the first request.

        One dummy prefill chunk + one decode dispatch + one free — after
        this, every steady-state dispatch reuses these executables (the
        zero-retrace contract; ``compiled_variants`` should stay flat).
        """
        import jax.numpy as jnp

        tel = get_telemetry()
        before = tel.compile_stats()
        variants_before = self.runtime.compiled_variants()
        t0 = time.perf_counter()
        zero = jnp.asarray(0, jnp.int32)
        chunk_ids = jnp.zeros((self.plan.prefill_chunk,), jnp.int32)
        self.caches, _ = self.runtime.prefill_chunk(
            self.backend.params, self.caches, zero, chunk_ids, zero,
            jnp.asarray(self.plan.prefill_chunk, jnp.int32), zero,
        )
        n = self.plan.n_slots
        self.caches, _, _, _, _ = self.runtime.decode_step(
            self.backend.params, self.caches,
            jnp.zeros((n,), jnp.int32),
            jnp.ones((n,), jnp.int32),
            jnp.zeros((n,), jnp.int32),
            jnp.ones((n,), jnp.int32),
            jnp.zeros((n,), bool),
            jnp.zeros((n,), bool),
        )
        self.caches = self.runtime.free_slots(
            self.caches, jnp.ones((n,), bool)
        )
        warm_s = time.perf_counter() - t0
        after = tel.compile_stats()
        record = {
            "seconds": round(warm_s, 6),
            "compiles": after["count"] - before["count"],
            "programs": self.runtime.compiled_variants() - variants_before,
            "n_slots": self.plan.n_slots,
            "prefill_chunk": self.plan.prefill_chunk,
        }
        self._warmup_record = record
        tel.annotate(decode_warmup=record)
        return record

    # ----------------------------------------------------------- admission

    def submit(self, rid: Any, text: str, op: str = "generate",
               max_new_tokens: Optional[int] = None) -> ServeRequest:
        """Admit (or shed) one generation request; mirrors the batcher's
        bounded-admission contract."""
        tel = get_telemetry()
        budget = int(max_new_tokens or self.plan.max_new)
        budget = max(1, min(budget, self.plan.max_new))
        req = ServeRequest(rid, op, text, meta={"max_new_tokens": budget})
        with self._cond:
            if self._draining:
                req.fail("draining", "server is draining; not admitting")
                self._bump(shed=1)
                tel.count("serving.shed")
                return req
            depth = len(self._queue)
            if depth >= self.max_queue:
                req.fail(
                    "queue_full",
                    f"decode admission queue full ({depth}/{self.max_queue});"
                    " retry with backoff",
                )
                self._bump(shed=1)
                tel.count("serving.shed")
                return req
            self._queue.append(req)
            depth += 1
            self._cond.notify_all()
        with self._stats_lock:
            self._stats["admitted"] += 1
            if depth > self._stats["queue_depth_max"]:
                self._stats["queue_depth_max"] = depth
        tel.count("serving.decode_admitted")
        return req

    def _bump(self, **deltas: Any) -> None:
        with self._stats_lock:
            for key, n in deltas.items():
                self._stats[key] += n

    # ------------------------------------------------------------ the loop

    def _loop(self) -> None:
        while True:
            did_work = self._tick()
            if did_work:
                watchdog.beat("decode.loop")
                continue
            with self._cond:
                if self._draining and not self._queue and not self._occupied():
                    return
                self._cond.wait(0.005)

    def run_until_idle(self, max_ticks: int = 1_000_000) -> None:
        """Synchronous driver: tick until queue and slots are empty."""
        for _ in range(max_ticks):
            if not self._tick():
                with self._cond:
                    if not self._queue and not self._occupied():
                        return
        raise RuntimeError("run_until_idle exceeded its tick bound")

    def _occupied(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def _tick(self) -> bool:
        """One scheduler iteration: admit waiting requests into free slots,
        advance one prefill chunk per mid-prefill slot, run one decode
        dispatch over all slots, settle completions.  Returns whether any
        work happened."""
        did = self._admit()
        did = self._prefill_tick() or did
        did = self._decode_tick() or did
        self._publish_gauges()
        return did

    # ------------------------------------------------------------ admit

    def _admit(self) -> bool:
        did = False
        while True:
            free = next(
                (i for i, s in enumerate(self._slots) if s is None), None
            )
            if free is None:
                return did
            with self._cond:
                if not self._queue:
                    return did
                req = self._queue.popleft()
            if req.done:  # already shed/settled
                continue
            try:
                ids, plen = self.backend.tokenizer.encode(
                    req.text, self.plan.prompt_region
                )
            except Exception as exc:  # noqa: BLE001 — poison isolation
                req.fail("request_failed",
                         f"{type(exc).__name__}: {exc}"[:300])
                self._bump(failed=1)
                get_telemetry().count("serving.request_failed")
                continue
            self._slots[free] = _Slot(
                req, np.asarray(ids, np.int32), plen,
                req.meta.get("max_new_tokens", self.plan.max_new),
            )
            did = True
        return did

    # ------------------------------------------------------------ prefill

    def _device_prefill(self, idx: int, slot: _Slot):
        """One prefill chunk for one slot (the retried/faulted edge).

        Returns the first-token logits argmax as a *device* array —
        forcing it here would serialize every slot's prefill behind a
        host readback; the caller batches the readbacks after all
        mid-prefill slots have dispatched.
        """
        import jax.numpy as jnp

        fault_point("decode.step", phase="prefill", slot=idx)
        start = slot.next_chunk
        C = self.plan.prefill_chunk
        is_last = start + C >= min(max(slot.plen, 1), self.plan.prompt_region)
        chunk = jnp.asarray(slot.ids[start:start + C])
        length_after = min(start + C, self.plan.prompt_region)
        last_index = max(0, min(slot.plen - 1 - start, C - 1))
        caches, first = self.runtime.prefill_chunk(
            self.backend.params, self.caches,
            jnp.asarray(idx, jnp.int32), chunk,
            jnp.asarray(start, jnp.int32),
            jnp.asarray(length_after, jnp.int32),
            jnp.asarray(last_index, jnp.int32),
        )
        return caches, first, is_last

    def _prefill_tick(self) -> bool:
        """Advance every mid-prefill slot by ONE chunk (bounding the
        latency spike a long prompt injects between decode dispatches)."""
        import jax

        tel = get_telemetry()
        did = False
        finishing = []  # (idx, slot, first_token_device_array)
        for idx, slot in enumerate(self._slots):
            if slot is None or slot.next_chunk < 0:
                continue
            did = True
            try:
                with watchdog.watch("decode.dispatch", kind="decode"):
                    caches, first, is_last = self._retry.call(
                        self._device_prefill, idx, slot, site="decode.step"
                    )
            except Exception as exc:  # noqa: BLE001 — poison isolation
                # The poison prompt fails ALONE: its slot is freed (and
                # zeroed) while co-resident slots keep decoding.
                slot.req.fail("request_failed",
                              f"{type(exc).__name__}: {exc}"[:300])
                self._bump(failed=1)
                tel.count("serving.request_failed")
                self._free([idx], zero=True)
                continue
            self.caches = caches
            self._bump(prefill_dispatches=1)
            if is_last:
                finishing.append((idx, slot, first))
            else:
                slot.next_chunk += self.plan.prefill_chunk
        if finishing:
            firsts = jax.device_get([f for _, _, f in finishing])
            for (idx, slot, _), first in zip(finishing, firsts):
                slot.next_chunk = -1
                slot.t_first = time.monotonic()
                ttft = slot.t_first - slot.req.t_enqueue
                self._ttft.observe(ttft)
                tel.observe("serving.ttft_seconds", ttft,
                            buckets=_LATENCY_BUCKETS)
                slot.carry = int(first)
                if slot.carry == self.runtime.eos_id:
                    # The model's very first token is EOS: empty
                    # generation, settled without a decode step.
                    self._settle(idx, slot)
                else:
                    slot.active = True
        return did

    # ------------------------------------------------------------- decode

    def _device_decode(self, tokens, plens, steps, budgets, done, active):
        fault_point("decode.step", phase="decode",
                    active=int(active.sum()))
        import jax.numpy as jnp

        return self.runtime.decode_step(
            self.backend.params, self.caches,
            jnp.asarray(tokens), jnp.asarray(plens), jnp.asarray(steps),
            jnp.asarray(budgets), jnp.asarray(done), jnp.asarray(active),
        )

    def _decode_tick(self) -> bool:
        tel = get_telemetry()
        n = self.plan.n_slots
        occupied = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None and s.active
        ]
        if not occupied:
            return False
        tokens = np.zeros(n, np.int32)
        plens = np.zeros(n, np.int32)
        steps = np.zeros(n, np.int32)
        budgets = np.ones(n, np.int32)
        done = np.zeros(n, bool)
        active = np.zeros(n, bool)
        for i, s in occupied:
            tokens[i] = s.carry
            plens[i] = s.plen
            steps[i] = s.steps
            budgets[i] = s.budget
            done[i] = s.done
            active[i] = True
        t0 = time.perf_counter()
        try:
            with watchdog.watch("decode.dispatch", kind="decode"):
                caches, tok_out, steps_out, done_out, emitted = (
                    self._retry.call(
                        self._device_decode, tokens, plens, steps, budgets,
                        done, active, site="decode.step",
                    )
                )
            import jax

            # One batched D2H readback instead of four serialized ones.
            emitted, tok_out, steps_out, done_out = jax.device_get(
                (emitted, tok_out, steps_out, done_out)
            )
        except Exception as exc:  # noqa: BLE001 — the loop must survive
            # Persistent decode failure: every in-flight request gets a
            # structured error; the slots are freed; the server lives on.
            detail = f"{type(exc).__name__}: {exc}"[:300]
            for i, s in occupied:
                s.req.fail("request_failed", detail)
            self._bump(failed=len(occupied))
            tel.count("serving.request_failed", len(occupied))
            self._free([i for i, _ in occupied], zero=True)
            return True
        decode_s = time.perf_counter() - t0
        self.caches = caches
        occ = len(occupied) / n
        with self._stats_lock:
            self._stats["decode_dispatches"] += 1
            self._stats["decode_seconds"] += decode_s
            self._occupancy.observe(occ)
        tel.observe("serving.slot_occupancy", occ,
                    buckets=_OCCUPANCY_BUCKETS)
        freed: List[int] = []
        for i, s in occupied:
            emitted_n = int(steps_out[i]) - s.steps
            s.tokens.extend(int(t) for t in emitted[:emitted_n, i])
            s.steps = int(steps_out[i])
            s.carry = int(tok_out[i])
            s.done = bool(done_out[i])
            self._bump(tokens_generated=emitted_n)
            saw_eos = emitted_n > 0 and self.runtime.eos_id in s.tokens[-emitted_n:]
            if saw_eos or s.steps >= s.budget:
                freed.append(i)
        for i in freed:
            self._settle(i, self._slots[i])
        return True

    # ------------------------------------------------------------- settle

    def _settle(self, idx: int, slot: _Slot) -> None:
        """Emit the reply, record TTFT/TPOT, free the slot."""
        tel = get_telemetry()
        eos = self.runtime.eos_id
        toks = slot.tokens
        if eos in toks:
            toks = toks[:toks.index(eos)]
        toks = toks[:slot.budget]
        text = self.backend.tokenizer.decode(toks)
        now = time.monotonic()
        if slot.t_first is not None and len(toks) > 1:
            tpot = (now - slot.t_first) / (len(toks) - 1)
            self._tpot.observe(tpot)
            tel.observe("serving.tpot_seconds", tpot,
                        buckets=_TOKEN_BUCKETS)
        slot.req.succeed(
            text=text,
            label=normalise_label(text) if text.strip() else "Neutral",
            tokens=len(toks),
        )
        self._bump(completed=1)
        tel.count("serving.decode_completed")
        tel.observe("serving.request_seconds", now - slot.req.t_enqueue,
                    buckets=_LATENCY_BUCKETS)
        self._free([idx])

    def _free(self, indices: List[int], zero: bool = False) -> None:
        """Release slots for reuse.

        Normal completion is host-only: the next occupant's prefill
        overwrites every prompt row it will attend to, the decode step
        overwrites row ``R + t`` before attending to it, and everything
        else is masked to an exact-zero attention contribution — so the
        device zeroing is semantically redundant (the continuous-vs-
        static byte-identity tests run *with* slot reuse).  Failure
        paths pass ``zero=True`` to hard-zero a poisoned slot's rows via
        the ``slots.free`` program anyway: after a fault nothing about
        the slot's contents is trusted, including the invariants above.
        """
        import jax.numpy as jnp

        mask = np.zeros(self.plan.n_slots, bool)
        for i in indices:
            mask[i] = True
            self._slots[i] = None
        if zero:
            self.caches = self.runtime.free_slots(
                self.caches, jnp.asarray(mask)
            )

    # ----------------------------------------------------------- readouts

    def _publish_gauges(self) -> None:
        tel = get_telemetry()
        active = sum(
            1 for s in self._slots if s is not None and s.active
        )
        prefilling = sum(
            1 for s in self._slots if s is not None and s.next_chunk >= 0
        )
        with self._cond:
            backlog = len(self._queue) + prefilling
        tel.gauge("serving.decode.active_slots", active)
        tel.gauge("serving.decode.free_slots",
                  self.plan.n_slots - self._occupied())
        tel.gauge("serving.decode.prefill_backlog", backlog)

    def stats(self) -> Dict[str, Any]:
        """JSON-able snapshot for the ``stats`` control op, the manifest's
        ``serving.decode`` section, and the ``continuous`` bench suite."""
        with self._stats_lock:
            out: Dict[str, Any] = dict(self._stats)
            ttft = self._ttft.as_dict()
            tpot = self._tpot.as_dict()
            occ = self._occupancy.as_dict()
        with self._cond:
            backlog = len(self._queue)
        active = sum(1 for s in self._slots if s is not None and s.active)
        prefilling = sum(
            1 for s in self._slots if s is not None and s.next_chunk >= 0
        )
        decode_s = out.pop("decode_seconds")
        out.update(
            n_slots=self.plan.n_slots,
            prefill_chunk=self.plan.prefill_chunk,
            prompt_region=self.plan.prompt_region,
            max_new_tokens=self.plan.max_new,
            decode_span=self.plan.decode_span,
            active_slots=active,
            free_slots=self.plan.n_slots - self._occupied(),
            prefill_backlog=backlog + prefilling,
            decode_seconds=round(decode_s, 6),
            tokens_per_s=(
                round(out["tokens_generated"] / decode_s, 3)
                if decode_s > 0 else None
            ),
            ttft=ttft,
            tpot=tpot,
            slot_occupancy_hist=occ,
            compiled_variants=self.runtime.compiled_variants(),
            warmup=self._warmup_record,
        )
        return out
