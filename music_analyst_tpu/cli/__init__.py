"""Flag-compatible command-line surface (reference L3, SURVEY.md §1)."""
