"""``python -m music_analyst_tpu`` — the framework's CLI.

Four subcommands mirror the reference's four entry points (SURVEY.md §1 L3)
with the same flags plus TPU-era additions (``--device``, ``--batch-size``):

* ``analyze``   ≙ ``mpirun -np N bin/parallel_spotify dataset.csv``
* ``sentiment`` ≙ ``scripts/sentiment_classifier.py``
* ``wordcount-per-song`` ≙ ``scripts/word_count_per_song.py``
* ``split``     ≙ ``scripts/split_csv_columns.py``

TPU-era subcommands with no reference analogue: ``serve`` (resident
NDJSON inference server with dynamic batching, serving/), ``sweep``
(scaling sweeps), ``validate`` (weight certification), ``profile-diff``
(the perf-regression gate over run manifests / bench lines),
``telemetry-report`` (cross-run analytics over telemetry dirs + bench
captures), and ``trace-report`` (per-request waterfalls + critical-path
attribution over request_traces.jsonl).  Every run-scoped subcommand
takes ``--profile-dir`` to
capture device + span traces and ``--watchdog-timeout`` to arm the
hang-classifying heartbeat watchdog (observability/).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _int_list(text: str) -> List[int]:
    """argparse type for comma-separated positive ints (e.g. "32,64,128");
    tolerates stray blanks, reports bad input as a usage error rather than
    a traceback."""
    try:
        values = [int(x) for x in text.split(",") if x.strip()]
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected comma-separated integers, got {text!r}"
        )
    if not values:
        raise argparse.ArgumentTypeError("expected at least one integer")
    bad = [v for v in values if v < 1]
    if bad:
        raise argparse.ArgumentTypeError(
            f"expected positive integers, got {bad[0]}"
        )
    return values


def _buckets_arg(text: str):
    """``--length-buckets`` value: explicit comma-separated lengths, or
    ``auto`` to derive them from the first batch's length distribution."""
    if text.strip().lower() == "auto":
        return "auto"
    return _int_list(text)


def _chunk_songs_arg(text: str):
    """``--chunk-songs`` value: ``auto`` (size by corpus), ``0`` (off), or
    a positive songs-per-chunk count."""
    if text.strip().lower() == "auto":
        return "auto"
    try:
        value = int(text)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer >= 0 or 'auto', got {text!r}"
        )
    if value < 0:
        raise argparse.ArgumentTypeError(
            f"expected an integer >= 0 or 'auto', got {value}"
        )
    return value


def _add_corpus_cache_flags(p: argparse.ArgumentParser) -> None:
    """Persistent-ingest-cache + streaming flags (data/corpus_cache.py,
    ops/histogram.py streaming path), shared by analyze and sweep."""
    p.add_argument("--corpus-cache-dir", default=None,
                   help="Persistent corpus-cache directory (default "
                        "$MUSICAAL_CORPUS_CACHE or ~/.cache/musicaal_corpus)")
    p.add_argument("--no-corpus-cache", action="store_true",
                   help="Disable the persistent corpus cache (always "
                        "re-ingest)")
    p.add_argument("--chunk-songs", type=_chunk_songs_arg, default=None,
                   help="Songs per streamed device chunk for the word "
                        "histogram: 'auto' (default — stream only on "
                        "large corpora), 0 = whole-corpus put, or an "
                        "explicit count (bounds host+device memory)")


def _add_telemetry_flags(p: argparse.ArgumentParser) -> None:
    """Run-telemetry flags, shared by every subcommand (telemetry/)."""
    p.add_argument("--telemetry-dir", default=None,
                   help="Write telemetry.jsonl + run_manifest.json here "
                        "(default: the run's output dir)")
    p.add_argument("--no-telemetry", action="store_true",
                   help="Disable run telemetry entirely (no extra files)")
    p.add_argument("--profile-dir", default=None,
                   help="Capture a device profiler trace + span-level "
                        "Chrome trace (trace_spans.json) into this dir "
                        "(profiling/trace.py)")
    p.add_argument("--watchdog-timeout", default=None,
                   help="Heartbeat watchdog timeout in seconds: a stage/"
                        "compile/device scope silent this long dumps a "
                        "classified flight_record.json (default "
                        "$MUSICAAL_WATCHDOG_S, 0 = disabled)")
    p.add_argument("--inject-faults", default=None, metavar="SPEC",
                   help="Deterministic fault injection for chaos testing: "
                        "';'-separated 'site:mode[@trigger][seed=N]' rules, "
                        "e.g. 'ollama.request:error@2;h2d.transfer:"
                        "delay=0.5s@1%%seed=7' (default $MUSICAAL_FAULTS; "
                        "see resilience/faults.py for sites + grammar)")


def _add_analyze(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "analyze",
        help="parallel word-count + artist-count over the dataset",
    )
    p.add_argument("dataset", help="Path to the spotify_millsongdata.csv dataset")
    # Reference flags (src/parallel_spotify.c:756-767)
    p.add_argument("--word-limit", type=int, default=0,
                   help="Cap rows in word_counts.csv (0 = unlimited)")
    p.add_argument("--artist-limit", type=int, default=0,
                   help="Cap rows in top_artists.csv (0 = unlimited)")
    p.add_argument("--output-dir", default="output")
    # TPU-era additions
    p.add_argument("--limit", type=int, default=None,
                   help="Only process the first N songs")
    p.add_argument("--ingest", choices=("auto", "native", "python"), default="auto")
    p.add_argument("--count-mode", choices=("host-shard", "device-ids"),
                   default="host-shard",
                   help="Histogram layout: psum of host-ingested shards "
                        "(default) or scatter-add of device-resident ids")
    p.add_argument("--no-split", action="store_true",
                   help="Skip writing split_columns/ artifacts")
    p.add_argument("--trace-dir", default=None,
                   help="Capture an XLA/TPU profiler trace into this dir "
                        "(TensorBoard/Perfetto-viewable)")
    p.add_argument("--devices", type=int, default=None,
                   help="Use only the first N devices of the mesh")
    p.add_argument("--with-sentiment", action="store_true",
                   help="Joint pipeline: also classify sentiment in this run")
    p.add_argument("--model", default="mock",
                   help="Sentiment model for --with-sentiment")
    p.add_argument("--mock", action="store_true",
                   help="Keyword-kernel sentiment for --with-sentiment")
    p.add_argument("--batch-size", type=int, default=4096,
                   help="Sentiment batch size for --with-sentiment")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="Sentiment batches staged ahead of the device in "
                        "the tokenize→transfer pipeline (default 2, or "
                        "$MUSICAAL_PREFETCH_DEPTH; 0 = no overlap)")
    _add_corpus_cache_flags(p)
    _add_telemetry_flags(p)


def _add_sentiment(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("sentiment", help="batched sentiment classification")
    p.add_argument("dataset")
    # Reference flags (scripts/sentiment_classifier.py:128-136)
    p.add_argument("--model", default="llama3",
                   help="Model family: mock, distilbert[-*], llama[3*]")
    p.add_argument("--limit", type=int, default=None)
    p.add_argument("--output-dir", default="output")
    p.add_argument("--mock", action="store_true",
                   help="Keyword-kernel backend (no model weights needed)")
    # TPU-era additions
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--resume", action="store_true",
                   help="Continue from an interrupted run's "
                        "sentiment_details.csv")
    p.add_argument("--trace-dir", default=None,
                   help="Capture an XLA/TPU profiler trace into this dir")
    p.add_argument("--devices", type=int, default=None,
                   help="Shard model-backend batches over the first N "
                        "devices (dp); mesh-incapable backends "
                        "(--mock, ollama) ignore it")
    p.add_argument("--length-buckets", type=_buckets_arg, default=None,
                   help="Sequence-length buckets for the encoder "
                        "classifier: comma-separated lengths (e.g. "
                        "32,64,128) or 'auto' to derive them from the "
                        "corpus; short songs run at shorter sequence "
                        "lengths")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="Batches staged ahead of the device in the "
                        "tokenize→transfer pipeline (default 2, or "
                        "$MUSICAAL_PREFETCH_DEPTH; 0 = no overlap)")
    p.add_argument("--weight-quant", choices=("none", "int8", "int4"),
                   default="none",
                   help="Store model weights quantized on device "
                        "(int8 per-channel / int4 grouped); checkpoints "
                        "stream layer-by-layer through the quantized "
                        "cache ($MUSICAAL_WQ_CACHE)")
    _add_telemetry_flags(p)


def _add_wordcount_per_song(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "wordcount-per-song",
        help="serial per-song word counts (independent oracle)",
    )
    # Reference flags (scripts/word_count_per_song.py:52-81)
    p.add_argument("csv_path")
    p.add_argument("--output-dir", default="output/serial_word_counts")
    p.add_argument("--encoding", default="utf-8-sig")
    p.add_argument("--delimiter", default=None)
    p.add_argument("--workers", type=int, default=0)
    p.add_argument("--chunk-rows", type=int, default=512,
                   help="Rows per tokenize pool task (streaming "
                        "granularity; bounds in-flight memory)")
    _add_telemetry_flags(p)


def _add_split(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("split", help="split a CSV into one file per column")
    # Reference flags (scripts/split_csv_columns.py:73-114)
    p.add_argument("csv_path")
    p.add_argument("--output-dir", default=None)
    p.add_argument("--delimiter", default=None)
    p.add_argument("--quotechar", default='"')
    p.add_argument("--encoding", default="utf-8-sig")
    p.add_argument("--no-header", action="store_true")
    p.add_argument("--force", action="store_true")
    _add_telemetry_flags(p)


def _add_validate(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "validate",
        help="certify real weights: label agreement vs a transformers "
             "torch oracle on a dataset slice (engines/validate.py)",
    )
    p.add_argument("dataset")
    p.add_argument("--model", default="distilbert",
                   help="distilbert[-*] or llama[3*]; the checkpoint comes "
                        "from MUSICAAL_DISTILBERT_CKPT / MUSICAAL_LLAMA_CKPT")
    p.add_argument("--limit", type=int, default=64,
                   help="Rows in the validation slice (0 = whole dataset)")
    p.add_argument("--output-dir", default=None,
                   help="Also write weight_validation.json here")
    p.add_argument("--min-agreement", type=float, default=None,
                   help="Exit non-zero when agreement falls below this "
                        "fraction (CI gate)")
    p.add_argument("--weight-quant", choices=("none", "int8", "int4"),
                   default="none",
                   help="Validate the weight-quantized model against the "
                        "float torch oracle (quantization quality gate)")
    _add_telemetry_flags(p)


def _add_profile_diff(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "profile-diff",
        help="perf-regression gate: compare two run manifests / bench "
             "lines; exit 1 on regression (profiling/diff.py)",
    )
    p.add_argument("a", help="Baseline: run_manifest.json, a bench JSON "
                             "line file, or literal JSON")
    p.add_argument("b", help="Candidate, same formats")
    p.add_argument("--threshold", type=float, default=0.1,
                   help="Relative throughput drop that fails the gate "
                        "(default 0.10)")
    p.add_argument("--wall-threshold", type=float, default=0.25,
                   help="Relative wall-clock growth that fails the gate "
                        "for manifests (default 0.25)")


def _add_telemetry_report(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "telemetry-report",
        help="cross-run analytics: aggregate telemetry dirs / BENCH_r*.json "
             "captures / bench lines into a run-over-run report "
             "(observability/report.py); exit 1 when the newest run failed",
    )
    p.add_argument("sources", nargs="+",
                   help="Run sources, oldest first: telemetry run dirs, "
                        "BENCH_r*.json driver captures, bench-line JSON "
                        "files, or flight_record.json files")
    p.add_argument("--json", action="store_true",
                   help="Emit the aggregated report as one JSON object "
                        "instead of text")


def _add_trace_report(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "trace-report",
        help="per-request waterfalls: reconstruct cross-process traces "
             "from request_traces.jsonl and attribute each request's "
             "wire latency to its phases (observability/report.py); "
             "exit 1 when no complete waterfall was found",
    )
    p.add_argument("sources", nargs="+",
                   help="Trace sources: profile dirs holding "
                        "request_traces*.jsonl, or the .jsonl files "
                        "themselves")
    p.add_argument("--json", action="store_true",
                   help="Emit the reconstructed traces as one JSON object "
                        "instead of waterfall text")


def _add_serve(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "serve",
        help="resident inference server: newline-delimited JSON over a "
             "unix socket (or --stdio), dynamic batching + warm model "
             "residency (serving/)",
    )
    p.add_argument("--model", default="mock",
                   help="Model family: mock, distilbert[-*], llama[3*]")
    p.add_argument("--mock", action="store_true",
                   help="Keyword-kernel backend (no model weights needed)")
    p.add_argument("--weight-quant", choices=("none", "int8", "int4"),
                   default="none",
                   help="Serve the weight-quantized model (loads through "
                        "the persistent $MUSICAAL_WQ_CACHE)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="Unix socket path to listen on (loopback-only by "
                        "construction)")
    p.add_argument("--stdio", action="store_true",
                   help="Serve one NDJSON stream on stdin/stdout instead "
                        "of a socket (tests, pipelines)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="Flush a batch at this many requests (default "
                        f"$MUSICAAL_SERVE_MAX_BATCH or 32)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="Flush a partial batch once its oldest request "
                        "has waited this long (default "
                        "$MUSICAAL_SERVE_MAX_WAIT_MS or 5.0)")
    p.add_argument("--max-queue", type=int, default=None,
                   help="Admission queue bound; beyond it requests shed "
                        "with a structured queue_full error (default "
                        "$MUSICAAL_SERVE_MAX_QUEUE or 1024)")
    p.add_argument("--slots", type=int, default=None,
                   help="KV slots for the continuous-batching generate op "
                        "(power of two; 0 disables; default "
                        "$MUSICAAL_SERVE_SLOTS or 8; requires a "
                        "generative backend)")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="Prompt tokens written per chunked-prefill "
                        "dispatch for the generate op (default "
                        "$MUSICAAL_SERVE_PREFILL_CHUNK or 64)")
    p.add_argument("--max-new-tokens", type=int, default=16,
                   help="Largest per-request generation budget the decode "
                        "runtime is compiled for (generate op)")
    p.add_argument("--page-size", type=int, default=None,
                   help="Tokens per KV page for the paged prefix-shared "
                        "cache (power of two; 0 pins the monolithic "
                        "per-slot cache; default $MUSICAAL_SERVE_PAGE_SIZE "
                        "or 16)")
    p.add_argument("--kv-pages", type=int, default=None,
                   help="Physical KV pages in the device pool (>= slots; "
                        "0 sizes it to slots*pages_per_slot; default "
                        "$MUSICAAL_SERVE_KV_PAGES or 0)")
    p.add_argument("--kv-quant", choices=("none", "int8"), default=None,
                   help="KV-page quantization for the paged cache: int8 "
                        "stores pages as per-row symmetric int8 codes + "
                        "f32 scales (~1.9x less KV HBM per sequence), "
                        "dequantized inside the paged-attention kernel; "
                        "requires --page-size > 0 (default "
                        "$MUSICAAL_SERVE_KV_QUANT or none)")
    p.add_argument("--speculate-k", type=int, default=None,
                   help="Draft tokens per slot per speculative decode "
                        "dispatch (prompt-lookup self-drafting; the "
                        "verify program commits the longest accepted "
                        "prefix + 1 correction token, byte-identical to "
                        "plain decode; 0 disables; default "
                        "$MUSICAAL_SERVE_SPECULATE_K or 0)")
    p.add_argument("--replicas", type=int, default=None,
                   help="Worker server processes behind the replica "
                        "router (join-shortest-queue dispatch, "
                        "health-aware failover; 1 serves in-process; "
                        "default $MUSICAAL_SERVE_REPLICAS or 1)")
    p.add_argument("--tp", type=int, default=None,
                   help="Tensor-parallel width per worker: attention "
                        "heads + KV cache shard over a tp mesh axis "
                        "(must divide kv heads; default "
                        "$MUSICAAL_SERVE_TP or 1)")
    p.add_argument("--ttft-slo-ms", type=float, default=None,
                   help="Time-to-first-token target in ms: arms SLO-aware "
                        "preemption (a waiting higher-priority admit may "
                        "slot-steal) and deadline-aware shedding "
                        "(slo_unattainable); 0 disables (default "
                        "$MUSICAAL_SERVE_SLO_TTFT_MS or 0)")
    p.add_argument("--tpot-slo-ms", type=float, default=None,
                   help="Time-per-output-token target in ms: the decode "
                        "loop defers low-priority admits while the "
                        "per-token EWMA is over target; 0 disables "
                        "(default $MUSICAAL_SERVE_SLO_TPOT_MS or 0)")
    p.add_argument("--tenant-budget", type=float, default=None,
                   help="Per-tenant admission budget in requests/second "
                        "(token bucket, burst 2x); an over-budget tenant "
                        "sheds at its own bucket while others keep "
                        "admitting; 0 disables (default "
                        "$MUSICAAL_SERVE_TENANT_BUDGET or 0)")
    p.add_argument("--priority", type=int, default=None,
                   help="Default priority class for requests that don't "
                        "carry one on the wire (higher serves first; "
                        "default $MUSICAAL_SERVE_PRIORITY or 1)")
    p.add_argument("--journal-dir", default=None,
                   help="Durable request journal directory: admitted/"
                        "replied records are fsync'd there, unanswered "
                        "requests replay on restart, and re-sent ids "
                        "return the journaled reply instead of "
                        "recomputing (default $MUSICAAL_SERVE_JOURNAL; "
                        "unset = journaling off)")
    p.add_argument("--no-warmup", action="store_true",
                   help="Skip the startup warmup batches (first request "
                        "pays compile cost)")
    p.add_argument("--quiet", action="store_true",
                   help="Suppress stderr status lines")
    p.add_argument("--trace-sample", default=None, metavar="P",
                   help="Per-request distributed tracing head-sample "
                        "probability in [0, 1]; sampled (plus every shed/"
                        "preempted/requeued/SLO-missed) request flushes "
                        "its span waterfall to request_traces.jsonl under "
                        "--profile-dir (default $MUSICAAL_TRACE_SAMPLE "
                        "or 0; requires --profile-dir or "
                        "$MUSICAAL_TRACE_DIR)")
    p.add_argument("--metrics-interval-ms", default=None, metavar="MS",
                   help="Metrics plane sampling interval in ms: every "
                        "serving counter/gauge/histogram/rate snapshots "
                        "into a ring-buffer time series, flushes to "
                        "metrics.jsonl + a Prometheus exposition file "
                        "under --profile-dir, and feeds multi-window SLO "
                        "burn-rate alerts (default "
                        "$MUSICAAL_METRICS_INTERVAL_MS or 0 = off)")
    p.add_argument("--response-cache-dir", default=None,
                   help="Persistent response-cache directory: settled "
                        "replies are content-addressed (normalized text + "
                        "op + budget + backend fingerprint) and repeat "
                        "requests answer from cache before shedding or "
                        "tenant metering, byte-identical and without a "
                        "device dispatch (default $MUSICAAL_RESPONSE_CACHE "
                        "or ~/.cache/musicaal_responses)")
    p.add_argument("--no-response-cache", action="store_true",
                   help="Disable the response cache (every request "
                        "computes)")
    _add_telemetry_flags(p)


def _add_monitor(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "monitor",
        help="live fleet monitor: attach to a serving socket and render "
             "a refreshing per-replica table (req/s, tokens/s, "
             "occupancy, queue depth, p50/p99, active burn-rate alerts); "
             "jax-free (observability/monitor.py)",
    )
    p.add_argument("--socket", required=True, metavar="PATH",
                   help="Unix socket of a live serve front end (single "
                        "server or replica router)")
    p.add_argument("--once", action="store_true",
                   help="Render one snapshot and exit (0 = healthy "
                        "reply, 1 = draining, 2 = no usable reply)")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="Refresh period in seconds (default 2.0)")
    p.add_argument("--json", action="store_true",
                   help="Emit each snapshot as one JSON object instead "
                        "of the table")
    p.add_argument("--idle-bubble-gate", type=float, default=None,
                   metavar="FRAC",
                   help="With --once: also exit 1 when any engine's "
                        "ledger idle_bubble fraction exceeds FRAC "
                        "(0..1) — the goodput health gate")


def _add_sweep(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "sweep",
        help="scaling sweep over device counts (run_performance.sh analogue)",
    )
    p.add_argument("dataset")
    p.add_argument("--devices", type=_int_list, default=None,
                   help="Comma-separated device counts (default: 1,2,4,8 capped)")
    p.add_argument("--output-dir", default="output")
    p.add_argument("--ingest", choices=("auto", "native", "python"), default="auto")
    _add_corpus_cache_flags(p)
    _add_telemetry_flags(p)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="music_analyst_tpu",
        description="TPU-native Spotify lyrics analytics",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    _add_analyze(sub)
    _add_sentiment(sub)
    _add_wordcount_per_song(sub)
    _add_split(sub)
    _add_serve(sub)
    _add_sweep(sub)
    _add_validate(sub)
    _add_profile_diff(sub)
    _add_telemetry_report(sub)
    _add_trace_report(sub)
    _add_monitor(sub)
    args = parser.parse_args(argv)

    if args.command == "profile-diff":
        # Pure host-side comparison: no telemetry scope, no jax import.
        from music_analyst_tpu.profiling.diff import run_profile_diff

        return run_profile_diff(
            args.a, args.b,
            threshold=args.threshold,
            wall_threshold=args.wall_threshold,
        )

    if args.command == "telemetry-report":
        # Pure host-side aggregation — must work against a dead tunnel,
        # so like profile-diff it never configures telemetry or jax.
        from music_analyst_tpu.observability.report import (
            run_telemetry_report,
        )

        return run_telemetry_report(args.sources, json_output=args.json)

    if args.command == "trace-report":
        # Same posture: pure host-side reconstruction over trace files,
        # never configures telemetry or jax.
        from music_analyst_tpu.observability.report import run_trace_report

        return run_trace_report(args.sources, json_output=args.json)

    if args.command == "monitor":
        # A live monitor must attach while the device is busy (or the
        # tunnel dead): pure socket client, no telemetry scope, no jax.
        from music_analyst_tpu.observability.monitor import run_monitor

        return run_monitor(
            args.socket, once=args.once, interval_s=args.interval,
            json_output=args.json,
            idle_bubble_gate=args.idle_bubble_gate,
        )

    from music_analyst_tpu.telemetry import configure

    configure(
        enabled=not args.no_telemetry, directory=args.telemetry_dir
    )

    from music_analyst_tpu.observability import (
        install_flight_recorder,
        resolve_watchdog_timeout,
        start_watchdog,
    )

    # Every run-scoped subcommand flies with the recorder installed: an
    # unhandled exception or SIGTERM leaves flight_record.json behind.
    # The watchdog is opt-in (--watchdog-timeout / $MUSICAAL_WATCHDOG_S).
    install_flight_recorder()
    try:
        start_watchdog(resolve_watchdog_timeout(args.watchdog_timeout))
    except ValueError as exc:
        parser.error(str(exc))

    from music_analyst_tpu.resilience import (
        configure_faults,
        resolve_fault_spec,
    )

    # Fault injection is explicit chaos tooling: a malformed spec (flag
    # OR env) is a hard usage error, never a silent no-op.
    try:
        configure_faults(
            resolve_fault_spec(getattr(args, "inject_faults", None))
        )
    except ValueError as exc:
        parser.error(str(exc))

    from music_analyst_tpu.profiling.trace import profile_run

    with profile_run(getattr(args, "profile_dir", None)):
        return _dispatch(parser, args)


def _dispatch(parser: argparse.ArgumentParser,
              args: argparse.Namespace) -> int:

    if args.command == "validate":
        from music_analyst_tpu.engines.validate import run_validation

        report = run_validation(
            args.dataset,
            model=args.model,
            limit=args.limit,
            output_dir=args.output_dir,
            weight_quant=args.weight_quant,
        )
        if (args.min_agreement is not None
                and report["agreement"] < args.min_agreement):
            print(
                f"FAIL: agreement {report['agreement']} < "
                f"{args.min_agreement}",
                file=sys.stderr,
            )
            return 1
        return 0

    if args.command == "sweep":
        from music_analyst_tpu.engines.sweep import run_sweep

        summary = run_sweep(
            args.dataset,
            device_counts=args.devices,
            output_dir=args.output_dir,
            ingest_backend=args.ingest,
            quiet=False,
            corpus_cache_dir=args.corpus_cache_dir,
            use_corpus_cache=not args.no_corpus_cache,
            chunk_songs=args.chunk_songs,
        )
        for run in summary["runs"]:
            print(
                f"np={run['devices']}: {run['wall_seconds']}s "
                f"(speedup {run['speedup_vs_first']}x)"
            )
        return 0

    if args.command == "analyze":
        from music_analyst_tpu.parallel.mesh import data_parallel_mesh
        from music_analyst_tpu.profiling.trace import maybe_trace

        mesh = data_parallel_mesh(args.devices) if args.devices else None
        if args.with_sentiment:
            from music_analyst_tpu.engines.joint import run_joint

            with maybe_trace(args.trace_dir):
                run_joint(
                    args.dataset,
                    output_dir=args.output_dir,
                    model=args.model,
                    mock=args.mock,
                    word_limit=args.word_limit,
                    artist_limit=args.artist_limit,
                    limit=args.limit,
                    batch_size=args.batch_size,
                    mesh=mesh,
                    write_split=not args.no_split,
                    ingest_backend=args.ingest,
                    prefetch_depth=args.prefetch_depth,
                    corpus_cache_dir=args.corpus_cache_dir,
                    use_corpus_cache=not args.no_corpus_cache,
                    chunk_songs=args.chunk_songs,
                )
            return 0
        from music_analyst_tpu.engines.wordcount import run_analysis

        with maybe_trace(args.trace_dir):
            run_analysis(
                args.dataset,
                output_dir=args.output_dir,
                word_limit=args.word_limit,
                artist_limit=args.artist_limit,
                limit=args.limit,
                mesh=mesh,
                write_split=not args.no_split,
                ingest_backend=args.ingest,
                count_mode=args.count_mode,
                corpus_cache_dir=args.corpus_cache_dir,
                use_corpus_cache=not args.no_corpus_cache,
                chunk_songs=args.chunk_songs,
            )
        return 0

    if args.command == "sentiment":
        from music_analyst_tpu.engines.sentiment import run_sentiment
        from music_analyst_tpu.profiling.trace import maybe_trace

        # Fail as a usage error, not a mid-run traceback: buckets only
        # apply to the encoder classifier family (engines/sentiment.py
        # raises the same constraint later for programmatic callers).
        if args.length_buckets and (
            args.mock or not args.model.startswith("distilbert")
        ):
            parser.error(
                "--length-buckets requires --model distilbert[-*] "
                "(not --mock or decoder models)"
            )
        if args.weight_quant != "none" and (
            args.mock or not (args.model.startswith("distilbert")
                              or args.model.startswith("llama"))
        ):
            parser.error(
                "--weight-quant requires an on-device model family "
                "(distilbert[-*] or llama[3*])"
            )
        mesh = None
        if args.devices:
            from music_analyst_tpu.engines.sentiment import _mesh_capable

            # Don't initialize the device backend (tunnel round-trip on
            # axon) just to build a mesh the backend family can't take.
            if _mesh_capable(args.model, args.mock):
                from music_analyst_tpu.parallel.mesh import data_parallel_mesh

                mesh = data_parallel_mesh(args.devices)
        with maybe_trace(args.trace_dir):
            run_sentiment(
                args.dataset,
                model=args.model,
                mock=args.mock,
                limit=args.limit,
                output_dir=args.output_dir,
                batch_size=args.batch_size,
                resume=args.resume,
                mesh=mesh,
                length_buckets=args.length_buckets,
                prefetch_depth=args.prefetch_depth,
                weight_quant=args.weight_quant,
            )
        return 0

    if args.command == "serve":
        from music_analyst_tpu.serving.server import run_server

        if not args.stdio and not args.socket:
            parser.error("serve requires --socket PATH or --stdio")
        if args.weight_quant != "none" and (
            args.mock or not (args.model.startswith("distilbert")
                              or args.model.startswith("llama"))
        ):
            parser.error(
                "--weight-quant requires an on-device model family "
                "(distilbert[-*] or llama[3*])"
            )
        try:
            from music_analyst_tpu.serving.batcher import resolve_replicas

            common = dict(
                model=args.model,
                mock=args.mock,
                weight_quant=(
                    None if args.weight_quant == "none"
                    else args.weight_quant
                ),
                stdio=args.stdio,
                socket_path=args.socket,
                max_batch=args.max_batch,
                max_wait_ms=args.max_wait_ms,
                max_queue=args.max_queue,
                warmup=not args.no_warmup,
                quiet=args.quiet,
                slots=args.slots,
                prefill_chunk=args.prefill_chunk,
                max_new_tokens=args.max_new_tokens,
                page_size=args.page_size,
                kv_pages=args.kv_pages,
                kv_quant=args.kv_quant,
                speculate_k=args.speculate_k,
                tp=args.tp,
                ttft_slo_ms=args.ttft_slo_ms,
                tpot_slo_ms=args.tpot_slo_ms,
                tenant_budget=args.tenant_budget,
                priority=args.priority,
                journal_dir=args.journal_dir,
                trace_sample=args.trace_sample,
                trace_dir=args.profile_dir,
                metrics_interval_ms=args.metrics_interval_ms,
                response_cache_dir=args.response_cache_dir,
                use_response_cache=not args.no_response_cache,
            )
            if resolve_replicas(args.replicas) > 1:
                from music_analyst_tpu.serving.router import run_router

                return run_router(replicas=args.replicas, **common)
            return run_server(**common)
        except ValueError as exc:
            parser.error(str(exc))

    if args.command == "wordcount-per-song":
        from music_analyst_tpu.engines.persong import run_per_song_wordcount

        run_per_song_wordcount(
            args.csv_path,
            output_dir=args.output_dir,
            encoding=args.encoding,
            delimiter=args.delimiter,
            workers=args.workers,
            chunk_rows=args.chunk_rows,
        )
        return 0

    if args.command == "split":
        from music_analyst_tpu.data.splitter import split_csv_columns
        from music_analyst_tpu.telemetry import get_telemetry

        # The splitter has no engine scope of its own; sink only where
        # --telemetry-dir points (None ⇒ memory-only), never into the
        # split output dir — its listing is a compared artifact.
        with get_telemetry().run_scope("split", None):
            out_dir, names = split_csv_columns(
                args.csv_path,
                output_dir=args.output_dir,
                delimiter=args.delimiter,
                quotechar=args.quotechar,
                encoding=args.encoding,
                no_header=args.no_header,
                force=args.force,
            )
        print(f"Wrote {len(names)} column file(s) to {out_dir}:")
        for name in names:
            print(f"  {out_dir / name}")
        return 0

    return 1


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except Exception as exc:  # top-level error reporting, like the reference
        print(f"Error: {exc}", file=sys.stderr)
        raise
