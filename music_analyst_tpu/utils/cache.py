"""Persistent XLA compilation cache.

No reference analogue: the reference recompiles nothing (ahead-of-time C
binary) but also re-does its column-split preprocessing on every run
(``src/parallel_spotify.c:821``); here the expensive per-run artifact is
the XLA program, and it persists.

First-compile latency (~1-2 s per program on v5e, more for big models)
would otherwise be paid by every fresh process; with the persistent cache
a cold CLI invocation reuses programs compiled by any earlier run.
Combined with the power-of-two shape bucketing in ``ops/histogram.py``,
repeat analyses skip compilation entirely.
"""

from __future__ import annotations

import os

_enabled = False


def enable_persistent_compilation_cache(path: str | None = None) -> None:
    global _enabled
    if _enabled:
        return
    import jax

    cache_dir = path or os.environ.get(
        "MUSICAAL_XLA_CACHE", os.path.expanduser("~/.cache/musicaal_xla")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        _enabled = True
    except Exception:
        # Cache is an optimization only; never fail a run over it.  But
        # leave _enabled False: a transient failure (unwritable dir, full
        # disk) must stay retryable on the next call, not silently pin
        # the process to cold compiles — and the failure is observable.
        try:
            from music_analyst_tpu.telemetry import get_telemetry

            get_telemetry().count("xla_cache.enable_failed")
        except Exception:
            pass
