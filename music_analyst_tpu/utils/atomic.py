"""Atomic artifact writes: stage to a tmp file, publish with one rename.

The engines' output files (``word_counts.csv``, ``top_artists.csv``,
``performance_metrics.json``, ``sentiment_totals.json``) are contracts —
resume logic and the differential tests trust whatever is on disk.  A
crash mid-``write()`` used to leave a torn file under the final name;
with this helper the final name either holds the previous complete
artifact or the new complete artifact, never a prefix.  Same pattern the
corpus/wq caches already use for directory entries (stage under
``<name>.tmp-<pid>-<uuid>``, publish with one ``os.replace``).

``os.replace`` (not ``rename``) so an existing artifact from a previous
run is overwritten in one step on every platform.

Atomicity alone is only crash-consistent against *process* death: after
a machine crash the rename may be on disk while the data blocks are not,
publishing a complete-looking file full of zeros.  ``durable=True`` adds
the two fsyncs the rename trick needs to be an actual write barrier —
the staged file before the rename (data reaches the platter before the
name does) and the parent directory after it (the rename itself reaches
the platter).  The request journal (``serving/journal.py``) sets it;
bulk artifact writers keep the fast default, and
``$MUSICAAL_ATOMIC_FSYNC=1`` upgrades every atomic write for paranoid
deployments (``=0`` forces it off for tests that hammer tiny files).
"""

from __future__ import annotations

import contextlib
import os
import uuid
from typing import IO, Iterator, Optional


def _fsync_wanted(durable: Optional[bool]) -> bool:
    """Explicit ``durable`` wins; else ``$MUSICAAL_ATOMIC_FSYNC`` (1/0);
    else off — the historical behavior, cheap for bulk artifacts."""
    env = os.environ.get("MUSICAAL_ATOMIC_FSYNC", "").strip()
    if durable is not None:
        return bool(durable)
    return env in ("1", "true", "yes")


def fsync_dir(directory: str) -> None:
    """fsync a directory so a rename/create inside it is on disk.

    Best-effort on platforms whose directories can't be opened for
    fsync; the journal's replay tolerates the resulting (tiny) window.
    """
    try:
        dir_fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dir_fd)
    except OSError:
        pass
    finally:
        os.close(dir_fd)


@contextlib.contextmanager
def atomic_write(
    path: str,
    mode: str = "w",
    encoding: Optional[str] = "utf-8",
    newline: Optional[str] = None,
    durable: Optional[bool] = None,
) -> Iterator[IO]:
    """Open a staging file that replaces ``path`` only on a clean exit.

    On any exception the staging file is removed and ``path`` is left
    untouched.  Binary modes pass ``encoding=None``.  ``durable=True``
    fsyncs the staged file before the rename and the parent directory
    after it (see module docstring); ``None`` defers to
    ``$MUSICAAL_ATOMIC_FSYNC``.
    """
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(
        directory,
        f"{os.path.basename(path)}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}",
    )
    fsync = _fsync_wanted(durable)
    fh = open(tmp, mode, encoding=encoding, newline=newline)
    try:
        yield fh
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
        fh.close()
        os.replace(tmp, path)
        if fsync:
            fsync_dir(directory)
    except BaseException:
        fh.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
