"""Atomic artifact writes: stage to a tmp file, publish with one rename.

The engines' output files (``word_counts.csv``, ``top_artists.csv``,
``performance_metrics.json``, ``sentiment_totals.json``) are contracts —
resume logic and the differential tests trust whatever is on disk.  A
crash mid-``write()`` used to leave a torn file under the final name;
with this helper the final name either holds the previous complete
artifact or the new complete artifact, never a prefix.  Same pattern the
corpus/wq caches already use for directory entries (stage under
``<name>.tmp-<pid>-<uuid>``, publish with one ``os.replace``).

``os.replace`` (not ``rename``) so an existing artifact from a previous
run is overwritten in one step on every platform.
"""

from __future__ import annotations

import contextlib
import os
import uuid
from typing import IO, Iterator, Optional


@contextlib.contextmanager
def atomic_write(
    path: str,
    mode: str = "w",
    encoding: Optional[str] = "utf-8",
    newline: Optional[str] = None,
) -> Iterator[IO]:
    """Open a staging file that replaces ``path`` only on a clean exit.

    On any exception the staging file is removed and ``path`` is left
    untouched.  Binary modes pass ``encoding=None``.
    """
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(
        directory,
        f"{os.path.basename(path)}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}",
    )
    fh = open(tmp, mode, encoding=encoding, newline=newline)
    try:
        yield fh
        fh.flush()
        fh.close()
        os.replace(tmp, path)
    except BaseException:
        fh.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
