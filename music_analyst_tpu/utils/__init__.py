"""Shared utilities: label contract, config defaults, small helpers."""

from music_analyst_tpu.utils.labels import SUPPORTED_LABELS, normalise_label
