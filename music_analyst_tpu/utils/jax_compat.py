"""Version shims for jax API moves.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` in jax 0.5, and its replication-check kwarg
was renamed ``check_rep`` → ``check_vma``.  ``jax.lax.pcast`` arrived
with the varying-manual-axes type system; under the older ``check_rep``
system there is nothing to cast, so it degrades to identity.  The
kernels here are written against the new names; this shim keeps them
running on a 0.4.x jax.
"""

from __future__ import annotations

import jax

try:
    from jax import shard_map  # jax >= 0.5
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _experimental

    def shard_map(f, /, **kwargs):  # type: ignore[misc]
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental(f, **kwargs)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:  # pragma: no cover - version-dependent

    def pcast(x, axes=None, to=None):
        return x


__all__ = ["shard_map", "pcast"]
