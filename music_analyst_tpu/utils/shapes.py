"""Shared jit-shape bucketing policy.

Everything dispatched to the device rounds its dynamic sizes up to a
bounded set of compiled shapes (XLA compiles per shape; unbounded shape
churn defeats the compilation cache).  The rounding rule lives here once —
histogram rows, keyword-kernel byte buckets, encoder row counts, and
decoder prompt widths all share it.
"""

from __future__ import annotations


def round_pow2(n: int, floor: int) -> int:
    """Round ``n`` up to a power of two (≥ ``floor``): stable jit shapes,
    ≤ 2× padding, O(log) distinct compiled programs."""
    size = floor
    while size < n:
        size <<= 1
    return size
