"""The three-label sentiment contract shared by every classifier backend.

The reference's label set and normalization rules
(``scripts/sentiment_classifier.py:36,102-108``) are part of its public API:
every output artifact speaks ``Positive | Neutral | Negative``.  All three
backends here (keyword kernel, encoder classifier, decoder LM) funnel
through this module so the contract is enforced in exactly one place.
"""

from __future__ import annotations

SUPPORTED_LABELS = ("Positive", "Neutral", "Negative")

# Stable int encoding used on device: scores/argmax indices map through this.
LABEL_TO_ID = {label: i for i, label in enumerate(SUPPORTED_LABELS)}
ID_TO_LABEL = dict(enumerate(SUPPORTED_LABELS))


def normalise_label(output: str) -> str:
    """First whitespace token, title-cased, whitelisted — else ``Neutral``.

    Matches the reference normalizer (``scripts/sentiment_classifier.py:
    102-108``) except for one deliberate fix: the reference crashes with
    ``IndexError`` on an empty model response (``"".split()[0]``); here an
    empty response normalizes to ``Neutral`` (SURVEY.md §5 contract #5).
    """
    parts = output.split()
    if not parts:
        return "Neutral"
    cleaned = parts[0].strip().title()
    if cleaned not in SUPPORTED_LABELS:
        return "Neutral"
    return cleaned


def score_to_label(score: int | float) -> str:
    """Sign-of-score labeling used by the keyword heuristic.

    Reference ``scripts/sentiment_classifier.py:78-83``.
    """
    if score > 0:
        return "Positive"
    if score < 0:
        return "Negative"
    return "Neutral"
