"""Corpus ingest: dataset file → device-ready dense arrays.

The host half of the word/artist-count pipeline.  Produces the exact same
aggregates the reference's per-rank loops feed into hash tables
(``src/parallel_spotify.c:918-998``), but as dense id arrays ready to be
sharded over a mesh:

* word ids: every >=3-byte token of every lyric, C-tokenizer semantics;
* artist ids: one id per record with a non-empty artist, ``-1`` otherwise
  (empty-artist records still count toward the song total — SURVEY.md §5
  contract #3);
* vocabularies mapping ids back to strings for the host-side sort/export.

Backends: ``python`` (reference-exact oracle, this module) and ``native``
(multithreaded C++, ``data/native.py``); ``auto`` prefers native.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import numpy as np

from music_analyst_tpu.data.csv_io import iter_dataset_fields
from music_analyst_tpu.data.tokenizer import tokenize_ascii
from music_analyst_tpu.data.vocab import Vocab
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy

# Transient read failures (tunnel-mounted corpus, injected ingest.read
# faults) get re-attempted; the whole ingest is idempotent, so the retry
# wraps the full backend dispatch rather than just the open().
_INGEST_RETRY = RetryPolicy(base_s=0.05, cap_s=1.0)


@dataclasses.dataclass
class IngestResult:
    """Dense host-side corpus representation."""

    word_vocab: Vocab
    word_ids: np.ndarray       # int32 [total_tokens]
    word_offsets: np.ndarray   # int64 [songs+1] — song s owns ids[off[s]:off[s+1]]
    artist_vocab: Vocab
    artist_ids: np.ndarray     # int32 [songs], -1 for empty artist
    song_count: int
    # Optional captured records (``capture_records=True``): cleaned
    # artist/song/text bytes concatenated in record order; ``record_offsets``
    # holds 3*songs+1 cumulative field ends.  Kept as one arena + offsets —
    # NOT per-record Python strings — so a 1M-song capture costs one blob,
    # and rows decode lazily per batch.
    records_blob: Optional[bytes] = None
    record_offsets: Optional[np.ndarray] = None

    @property
    def token_count(self) -> int:
        return int(self.word_ids.shape[0])

    def tokens_per_song(self) -> np.ndarray:
        return np.diff(self.word_offsets)

    @property
    def has_records(self) -> bool:
        return self.records_blob is not None

    def record(self, i: int) -> Tuple[str, str, str]:
        """Decoded ``(artist, song, text)`` for song ``i``."""
        if not self.has_records:
            raise ValueError(
                "records were not captured; ingest with capture_records=True"
            )
        off = self.record_offsets
        start = int(off[3 * i])
        a_end, s_end, t_end = (int(off[3 * i + f + 1]) for f in range(3))
        blob = self.records_blob
        return (
            blob[start:a_end].decode("utf-8", errors="replace"),
            blob[a_end:s_end].decode("utf-8", errors="replace"),
            blob[s_end:t_end].decode("utf-8", errors="replace"),
        )

    def iter_records(self) -> Iterator[Tuple[str, str, str]]:
        """Lazily decode every captured ``(artist, song, text)`` row."""
        if not self.has_records:
            raise ValueError(
                "records were not captured; ingest with capture_records=True"
            )
        for i in range(self.song_count):
            yield self.record(i)


def ingest_python(
    data: bytes,
    limit: Optional[int] = None,
    capture_records: bool = False,
) -> IngestResult:
    """Pure-Python reference-exact ingest (oracle for the native path)."""
    word_vocab = Vocab()
    artist_vocab = Vocab()
    word_add = word_vocab.add
    ids: List[int] = []
    offsets: List[int] = [0]
    artist_ids: List[int] = []
    blob = bytearray() if capture_records else None
    rec_offsets: List[int] = [0] if capture_records else []
    for parsed, (artist_raw, song_raw, text_raw) in enumerate(
        iter_dataset_fields(data)
    ):
        if limit is not None and parsed >= limit:
            break
        ids.extend(word_add(tok) for tok in tokenize_ascii(text_raw))
        offsets.append(len(ids))
        if artist_raw:
            artist = artist_raw.decode("utf-8", errors="replace")
            artist_ids.append(artist_vocab.add(artist))
        else:
            artist_ids.append(-1)
        if capture_records:
            for field in (artist_raw, song_raw, text_raw):
                blob.extend(field)
                rec_offsets.append(len(blob))
    return IngestResult(
        word_vocab=word_vocab,
        word_ids=np.asarray(ids, dtype=np.int32),
        word_offsets=np.asarray(offsets, dtype=np.int64),
        artist_vocab=artist_vocab,
        artist_ids=np.asarray(artist_ids, dtype=np.int32),
        song_count=len(artist_ids),
        records_blob=bytes(blob) if capture_records else None,
        record_offsets=(
            np.asarray(rec_offsets, dtype=np.int64)
            if capture_records
            else None
        ),
    )


def ingest_dataset(
    path: str,
    limit: Optional[int] = None,
    backend: str = "auto",
    num_threads: int = 0,
    capture_records: bool = False,
    cache_dir: Optional[str] = None,
) -> IngestResult:
    """Ingest a dataset CSV with the requested backend.

    ``capture_records=True`` additionally retains every cleaned
    ``(artist, song, text)`` row in an arena (see ``IngestResult``) so the
    joint pipeline can feed sentiment from the same single parse.

    ``cache_dir`` (already resolved — see
    ``data/corpus_cache.resolve_cache_dir``) enables the persistent corpus
    cache: a hit skips the parse entirely and maps the id arrays back
    read-only; a miss ingests then stores.  The key includes the backend
    actually used, so a ``python``-oracle request can never be satisfied
    by a native-written entry.
    """
    if backend not in ("auto", "python", "native"):
        raise ValueError(f"unknown ingest backend: {backend}")

    def _ingest_once() -> IngestResult:
        fault_point("ingest.read", path=path, backend=backend)
        if backend in ("auto", "native"):
            from music_analyst_tpu.data import native

            if native.available():
                return native.ingest_native(
                    path,
                    limit=limit,
                    num_threads=num_threads,
                    capture_records=capture_records,
                    cache_dir=cache_dir,
                )
            if backend == "native":
                raise RuntimeError(
                    "native ingest requested but the C++ library is "
                    f"unavailable ({native.unavailable_reason()})"
                )
        if cache_dir:
            from music_analyst_tpu.data import corpus_cache

            cached = corpus_cache.load(
                cache_dir, path, limit, capture_records, "python"
            )
            if cached is not None:
                return cached
        with open(path, "rb") as fh:
            data = fh.read()
        result = ingest_python(
            data, limit=limit, capture_records=capture_records
        )
        if cache_dir:
            corpus_cache.store(
                cache_dir, path, limit, capture_records, "python", result
            )
        return result

    return _INGEST_RETRY.call(_ingest_once, site="ingest.read")
