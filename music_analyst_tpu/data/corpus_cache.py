"""Persistent on-disk corpus cache: content-addressed ``IngestResult``.

The reference re-does its preprocessing on every run
(``src/parallel_spotify.c:821``, SURVEY.md §3.1) and this framework used
to share the flaw for its own expensive host artifact: every
``analyze``/``sweep``/``joint`` invocation re-parsed and re-tokenized the
whole CSV even though the wordcount path is host-ingest-bound
(``ops/histogram.py`` design note).  ``utils/cache.py`` already persists
the other per-run cost — the XLA program; this module persists the ingest.

Design:

* **Content-addressed key** — (schema version, backend, file size,
  BLAKE2b content hash, limit, capture flag).  Renames and mtime churn
  don't invalidate; any byte change does.
* **Zero-copy load** — the dense arrays are stored as ``.npy`` and come
  back via ``np.load(..., mmap_mode="r")``: a warm hit maps the id
  arrays instead of re-materializing them, so repeat analyses are
  ingest-free AND allocation-free until a consumer slices.
* **Length-prefixed vocab blobs** — concatenated UTF-8 token bytes plus
  an int32 length per token (the native wire format,
  ``data/native.py``): artist names may legally contain newlines, so a
  delimiter format would corrupt the id mapping.
* **Atomic writes** — entries are staged in a tmp dir and published with
  one ``os.rename``; concurrent writers race benignly (first rename
  wins, losers discard).
* **Corruption-tolerant** — any load failure (truncated ``.npy``, stale
  schema, meta mismatch) counts a ``corpus_cache.corrupt`` telemetry
  event, best-effort deletes the entry, and falls back to a fresh
  ingest.  The cache can never fail a run.

Resolution: explicit ``cache_dir`` argument (``--corpus-cache-dir``)
wins, then ``$MUSICAAL_CORPUS_CACHE`` (a directory, or ``0``/``off`` to
disable), then ``~/.cache/musicaal_corpus``.  ``--no-corpus-cache`` /
``use_cache=False`` opts out.  Hit/miss/bytes-saved counters land in the
run manifest (``telemetry/introspect.py`` adds a ``corpus_cache``
section).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy

# Publish is one rename; transient filesystem errors (and injected
# corpus_cache.publish faults) get a couple of fast re-attempts before the
# store is abandoned.  Short sleeps: the caller is blocking an ingest.
_PUBLISH_RETRY = RetryPolicy(base_s=0.02, cap_s=0.2)

SCHEMA_VERSION = 1

_META_NAME = "meta.json"
_HASH_CHUNK = 1 << 22  # 4 MiB reads: streaming hash, bounded memory

# Process-lifetime stats (mirrored into telemetry counters as they
# happen): the manifest's ``corpus_cache`` section and the bench suites
# read these.
_STATS_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    "hits": 0,
    "misses": 0,
    "stores": 0,
    "corrupt": 0,
    "bytes_saved": 0,
}


def _bump(name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[name] += n
    try:
        from music_analyst_tpu.telemetry import get_telemetry

        get_telemetry().count(f"corpus_cache.{name}", n)
    except Exception:
        pass


def cache_stats() -> Dict[str, int]:
    """Snapshot of this process's hit/miss/store/corrupt/bytes-saved."""
    with _STATS_LOCK:
        return dict(_STATS)


def resolve_cache_dir(
    cache_dir: Optional[str] = None, use_cache: Optional[bool] = None
) -> Optional[str]:
    """The directory to cache under, or ``None`` when caching is off.

    ``use_cache=False`` (the ``--no-corpus-cache`` flag) always wins;
    then an explicit ``cache_dir`` (``--corpus-cache-dir``), then
    ``$MUSICAAL_CORPUS_CACHE`` (``0``/``off``/``false`` disables), then
    the user-level default next to the XLA cache.
    """
    if use_cache is False:
        return None
    if cache_dir:
        return cache_dir
    env = os.environ.get("MUSICAAL_CORPUS_CACHE", "").strip()
    if env.lower() in ("0", "off", "false", "no"):
        return None
    if env:
        return env
    return os.path.expanduser("~/.cache/musicaal_corpus")


def _content_hash(path: str) -> str:
    digest = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as fh:
        while True:
            block = fh.read(_HASH_CHUNK)
            if not block:
                break
            digest.update(block)
    return digest.hexdigest()


def corpus_key(
    path: str,
    limit: Optional[int],
    capture_records: bool,
    backend: str,
) -> str:
    """Content-addressed entry name.  Hashing the file is the warm-path
    cost floor (~GB/s) — orders of magnitude under re-parsing it."""
    size = os.path.getsize(path)
    return (
        f"v{SCHEMA_VERSION}-{backend}-{size}-{_content_hash(path)}"
        f"-limit{'all' if limit is None else int(limit)}"
        f"-rec{int(bool(capture_records))}"
    )


def _vocab_paths(entry: str, kind: str) -> tuple:
    return (
        os.path.join(entry, f"{kind}_vocab.bin"),
        os.path.join(entry, f"{kind}_vocab_lens.npy"),
    )


def _write_vocab(entry: str, kind: str, tokens: List[str]) -> int:
    blob_path, lens_path = _vocab_paths(entry, kind)
    encoded = [t.encode("utf-8", errors="surrogatepass") for t in tokens]
    lens = np.asarray([len(e) for e in encoded], dtype=np.int32)
    with open(blob_path, "wb") as fh:
        for e in encoded:
            fh.write(e)
    np.save(lens_path, lens)
    return int(lens.sum()) if len(encoded) else 0


def _read_vocab(entry: str, kind: str, expected: int) -> List[str]:
    blob_path, lens_path = _vocab_paths(entry, kind)
    lens = np.load(lens_path)
    if lens.shape[0] != expected:
        raise ValueError(
            f"{kind} vocab length mismatch: {lens.shape[0]} != {expected}"
        )
    with open(blob_path, "rb") as fh:
        blob = fh.read()
    if len(blob) != int(lens.sum() if lens.size else 0):
        raise ValueError(f"{kind} vocab blob truncated")
    tokens: List[str] = []
    pos = 0
    for n in lens.tolist():
        tokens.append(blob[pos : pos + n].decode("utf-8", "surrogatepass"))
        pos += n
    return tokens


def store(
    cache_dir: str,
    path: str,
    limit: Optional[int],
    capture_records: bool,
    backend: str,
    result: Any,
) -> bool:
    """Persist ``result`` (an ``IngestResult``) atomically; never raises.

    Staged under ``<key>.tmp-<pid>-<uuid>`` then published with one
    ``rename``; a concurrent writer that won the race just costs this
    writer its discarded tmp dir.
    """
    try:
        key = corpus_key(path, limit, capture_records, backend)
        final = os.path.join(cache_dir, key)
        if os.path.isdir(final):
            return True
        os.makedirs(cache_dir, exist_ok=True)
        tmp = os.path.join(
            cache_dir, f"{key}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        os.makedirs(tmp)
        try:
            np.save(os.path.join(tmp, "word_ids.npy"),
                    np.ascontiguousarray(result.word_ids, dtype=np.int32))
            np.save(os.path.join(tmp, "word_offsets.npy"),
                    np.ascontiguousarray(result.word_offsets, dtype=np.int64))
            np.save(os.path.join(tmp, "artist_ids.npy"),
                    np.ascontiguousarray(result.artist_ids, dtype=np.int32))
            _write_vocab(tmp, "word", result.word_vocab.tokens)
            _write_vocab(tmp, "artist", result.artist_vocab.tokens)
            if capture_records and result.has_records:
                with open(os.path.join(tmp, "records.bin"), "wb") as fh:
                    fh.write(result.records_blob)
                np.save(os.path.join(tmp, "record_offsets.npy"),
                        np.ascontiguousarray(result.record_offsets,
                                             dtype=np.int64))
            meta = {
                "schema": SCHEMA_VERSION,
                "backend": backend,
                "file_size": os.path.getsize(path),
                "limit": limit,
                "capture_records": bool(capture_records),
                "song_count": int(result.song_count),
                "token_count": int(result.token_count),
                "word_vocab_size": len(result.word_vocab),
                "artist_vocab_size": len(result.artist_vocab),
                "source_path": os.path.abspath(path),
            }
            with open(os.path.join(tmp, _META_NAME), "w",
                      encoding="utf-8") as fh:
                json.dump(meta, fh)
            def _publish() -> None:
                fault_point("corpus_cache.publish", key=key)
                os.rename(tmp, final)

            try:
                _PUBLISH_RETRY.call(
                    _publish, site="corpus_cache.publish"
                )
            except OSError:
                # Lost the publish race — the winner's entry is equivalent
                # (content-addressed), so dropping ours is correct.
                shutil.rmtree(tmp, ignore_errors=True)
                return os.path.isdir(final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _bump("stores")
        return True
    except Exception:
        # Cache is an optimization only; never fail an ingest over it.
        return False


def load(
    cache_dir: str,
    path: str,
    limit: Optional[int],
    capture_records: bool,
    backend: str,
) -> Optional[Any]:
    """Return a cached ``IngestResult`` or ``None`` (miss/corruption).

    Id arrays come back memory-mapped read-only (zero-copy); a corrupt
    entry is deleted and treated as a miss so the caller re-ingests.
    """
    from music_analyst_tpu.data.ingest import IngestResult
    from music_analyst_tpu.data.vocab import Vocab

    try:
        key = corpus_key(path, limit, capture_records, backend)
    except OSError:
        return None
    entry = os.path.join(cache_dir, key)
    if not os.path.isdir(entry):
        _bump("misses")
        return None
    try:
        with open(os.path.join(entry, _META_NAME), encoding="utf-8") as fh:
            meta = json.load(fh)
        if meta.get("schema") != SCHEMA_VERSION:
            raise ValueError(
                f"stale schema {meta.get('schema')} != {SCHEMA_VERSION}"
            )
        songs = int(meta["song_count"])
        tokens = int(meta["token_count"])
        word_ids = np.load(os.path.join(entry, "word_ids.npy"), mmap_mode="r")
        word_offsets = np.load(
            os.path.join(entry, "word_offsets.npy"), mmap_mode="r"
        )
        artist_ids = np.load(
            os.path.join(entry, "artist_ids.npy"), mmap_mode="r"
        )
        if (word_ids.shape[0] != tokens
                or word_offsets.shape[0] != songs + 1
                or artist_ids.shape[0] != songs
                or (tokens and int(word_offsets[-1]) != tokens)):
            raise ValueError("id array shapes disagree with meta")
        word_vocab = Vocab(
            _read_vocab(entry, "word", int(meta["word_vocab_size"]))
        )
        artist_vocab = Vocab(
            _read_vocab(entry, "artist", int(meta["artist_vocab_size"]))
        )
        records_blob = None
        record_offsets = None
        if capture_records:
            if not meta.get("capture_records"):
                raise ValueError("entry lacks captured records")
            with open(os.path.join(entry, "records.bin"), "rb") as fh:
                records_blob = fh.read()
            record_offsets = np.load(
                os.path.join(entry, "record_offsets.npy"), mmap_mode="r"
            )
            if record_offsets.shape[0] != 3 * songs + 1 or (
                songs and int(record_offsets[-1]) != len(records_blob)
            ):
                raise ValueError("record arena disagrees with meta")
        result = IngestResult(
            word_vocab=word_vocab,
            word_ids=word_ids,
            word_offsets=word_offsets,
            artist_vocab=artist_vocab,
            artist_ids=artist_ids,
            song_count=songs,
            records_blob=records_blob,
            record_offsets=record_offsets,
        )
    except Exception:
        _bump("corrupt")
        _bump("misses")
        shutil.rmtree(entry, ignore_errors=True)
        return None
    _bump("hits")
    try:
        _bump("bytes_saved", os.path.getsize(path))
    except OSError:
        pass
    return result
