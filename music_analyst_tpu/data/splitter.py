"""CSV column splitting: the dataset preprocessor and the generic tool.

Two distinct splitters exist in the reference and both are reproduced:

* the in-process dataset splitter the analysis binary runs on rank 0
  (``src/parallel_spotify.c:640-721``): writes
  ``split_columns/<artist>.csv`` + ``<text>.csv``, one record per line with
  the original quoting preserved, header label as first line;
* the standalone generic splitter (``scripts/split_csv_columns.py``): one
  file per column of any CSV, named after the sanitized header, with
  collision suffixes, ``--no-header`` / ``--force`` support.
"""

from __future__ import annotations

import csv
import os
import re
from pathlib import Path
from typing import List, Optional, Tuple

from music_analyst_tpu.data.csv_io import (
    iter_csv_records_exact,
    parse_record_exact,
)


def sanitize_header_name(name: str) -> str:
    """Header → filename base, C-binary semantics.

    Reference ``src/parallel_spotify.c:510-543``: drop CR/LF, map other
    whitespace and non ``[A-Za-z0-9-._]`` ASCII chars to ``_``; empty result
    falls back to ``"col"``.  (Non-ASCII bytes are "not alnum" to the C
    locale, so every byte of a multi-byte char becomes ``_``.)
    """
    out = []
    for byte in name.encode("utf-8", errors="surrogateescape"):
        ch = chr(byte)
        if ch in "\r\n":
            continue
        if ch in " \t\v\f":
            out.append("_")
        elif ch.isascii() and (ch.isalnum() or ch in "-._"):
            out.append(ch)
        else:
            out.append("_")
    return "".join(out) or "col"


def sanitize_filename(name: str, max_len: int = 80) -> str:
    """Header → filename base, generic-tool semantics.

    Reference ``scripts/split_csv_columns.py:25-29``: newlines → spaces,
    non ``[\\w\\-. ]`` → ``_`` (Unicode word chars allowed), whitespace runs
    → ``_``, truncated to ``max_len``, fallback ``"col"``.
    """
    name = (name or "").replace("\n", " ").replace("\r", " ").strip()
    name = re.sub(r"[^\w\-. ]+", "_", name, flags=re.UNICODE)
    name = re.sub(r"\s+", "_", name)
    return (name or "col")[:max_len]


def split_dataset_columns(
    dataset_path: str,
    split_dir: str,
    artist_base_name: str,
    text_base_name: str,
    artist_header_label: str,
    text_header_label: str,
    backend: str = "auto",
) -> Tuple[str, str]:
    """Write ``<split_dir>/<artist>.csv`` and ``<text>.csv``.

    Matches the reference splitter (``src/parallel_spotify.c:640-721``):
    header label (or ``Artists``/``Texts`` fallback) on the first line, then
    one record per data row with outer quotes preserved verbatim; records
    with fewer than three unquoted commas are skipped.  Uses the C++ fast
    path when available (byte-identical; tested differentially).
    """
    os.makedirs(split_dir, exist_ok=True)
    artist_path = os.path.join(split_dir, artist_base_name + ".csv")
    text_path = os.path.join(split_dir, text_base_name + ".csv")
    if backend in ("auto", "native"):
        from music_analyst_tpu.data import native

        if native.available():
            native.split_columns_native(
                dataset_path, artist_path, text_path,
                artist_header_label or "Artists",
                text_header_label or "Texts",
            )
            return artist_path, text_path
        if backend == "native":
            raise RuntimeError("native splitter requested but unavailable")
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    records = iter_csv_records_exact(data)
    next(records, None)  # header row
    with open(artist_path, "wb") as artist_fp, open(text_path, "wb") as text_fp:
        artist_fp.write((artist_header_label or "Artists").encode("utf-8") + b"\n")
        text_fp.write((text_header_label or "Texts").encode("utf-8") + b"\n")
        for record in records:
            if not record.strip(b"\r\n"):
                continue
            parsed = parse_record_exact(
                record, preserve_artist_quotes=True, preserve_text_quotes=True
            )
            if parsed is None:
                continue
            artist_raw, text_raw = parsed
            artist_fp.write(artist_raw + b"\n")
            text_fp.write(text_raw + b"\n")
    return artist_path, text_path


def read_header_labels(dataset_path: str) -> Tuple[str, str]:
    """Artist/text header labels from the dataset's first record.

    Mirrors the rank-0 preamble (``src/parallel_spotify.c:788-819``): parse
    the header record with quotes stripped; raises ``ValueError`` when the
    header can't be parsed (the reference aborts).
    """
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    header = next(iter_csv_records_exact(data), None)
    if header is None:
        raise ValueError("Dataset does not contain a header row")
    parsed = parse_record_exact(header)
    if parsed is None:
        raise ValueError("Unable to parse dataset header")
    artist_label, text_label = parsed
    return (
        artist_label.decode("utf-8", errors="replace"),
        text_label.decode("utf-8", errors="replace"),
    )


def split_csv_columns(
    csv_path: str,
    output_dir: Optional[str] = None,
    delimiter: Optional[str] = None,
    quotechar: str = '"',
    encoding: str = "utf-8-sig",
    no_header: bool = False,
    force: bool = False,
) -> Tuple[Path, List[str]]:
    """Generic one-file-per-column splitter.

    Behavioral clone of ``scripts/split_csv_columns.py:117-206``: sniffed
    delimiter (64 KiB sample, fallback ``,``), sanitized header filenames
    with ``_2, _3…`` collision suffixes, header row re-emitted into each
    column file unless ``no_header``.
    """
    in_path = Path(csv_path)
    if not in_path.exists():
        raise FileNotFoundError(str(in_path))
    base_out = (
        Path(output_dir)
        if output_dir
        else in_path.with_suffix("").parent / f"{in_path.stem}_columns"
    )
    base_out.mkdir(parents=True, exist_ok=True)

    with open(in_path, "r", encoding=encoding, newline="") as fh:
        if delimiter:
            fmt = dict(
                delimiter=delimiter,
                quotechar=quotechar,
                doublequote=True,
                skipinitialspace=False,
                lineterminator="\n",
                quoting=csv.QUOTE_MINIMAL,
            )
        else:
            pos = fh.tell()
            sample = fh.read(65536)
            fh.seek(pos)
            try:
                dialect = csv.Sniffer().sniff(sample)
                fmt = dict(
                    delimiter=dialect.delimiter,
                    quotechar=quotechar or '"',
                    doublequote=True,
                    skipinitialspace=dialect.skipinitialspace,
                    lineterminator="\n",
                    quoting=csv.QUOTE_MINIMAL,
                )
            except csv.Error:
                fmt = dict(
                    delimiter=",",
                    quotechar=quotechar or '"',
                    doublequote=True,
                    skipinitialspace=False,
                    lineterminator="\n",
                    quoting=csv.QUOTE_MINIMAL,
                )
        reader = csv.reader(fh, **fmt)
        try:
            first_row = next(reader)
        except StopIteration:
            raise ValueError("empty CSV")

        if no_header:
            headers = [f"col{i + 1}" for i in range(len(first_row))]
            first_data_row: Optional[List[str]] = first_row
        else:
            headers = [
                (h if h is not None and str(h).strip() else f"col{i + 1}")
                for i, h in enumerate(first_row)
            ]
            first_data_row = None

        num_cols = len(headers)
        seen: set = set()
        filenames: List[str] = []
        for i, h in enumerate(headers, start=1):
            name = sanitize_filename(str(h)) or f"col{i}"
            candidate = f"{name}.csv"
            k = 2
            while candidate.lower() in seen or (
                (base_out / candidate).exists() and not force
            ):
                candidate = f"{name}_{k}.csv"
                k += 1
            seen.add(candidate.lower())
            filenames.append(candidate)

        files = []
        writers = []
        try:
            for i in range(num_cols):
                fh_out = open(base_out / filenames[i], "w", encoding=encoding, newline="")
                writer = csv.writer(fh_out, **fmt)
                if not no_header:
                    writer.writerow([headers[i]])
                files.append(fh_out)
                writers.append(writer)
            if first_data_row is not None:
                for i in range(num_cols):
                    writers[i].writerow(
                        [first_data_row[i] if i < len(first_data_row) else ""]
                    )
            for row in reader:
                for i in range(num_cols):
                    writers[i].writerow([row[i] if i < len(row) else ""])
        finally:
            for fh_out in files:
                try:
                    fh_out.close()
                except Exception:
                    pass
    return base_out, filenames
