"""CSV column splitting: the dataset preprocessor and the generic tool.

Two distinct splitters exist in the reference and both are reproduced:

* the in-process dataset splitter the analysis binary runs on rank 0
  (``src/parallel_spotify.c:640-721``): writes
  ``split_columns/<artist>.csv`` + ``<text>.csv``, one record per line with
  the original quoting preserved, header label as first line;
* the standalone generic splitter (``scripts/split_csv_columns.py``): one
  file per column of any CSV, named after the sanitized header, with
  collision suffixes, ``--no-header`` / ``--force`` support.
"""

from __future__ import annotations

import csv
import os
import re
from contextlib import ExitStack
from dataclasses import dataclass
from itertools import chain, count
from pathlib import Path
from typing import Iterator, List, Optional, Sequence, Tuple

from music_analyst_tpu.data.csv_io import (
    iter_csv_records_exact,
    parse_record_exact,
)


def sanitize_header_name(name: str) -> str:
    """Header → filename base, C-binary semantics.

    Reference ``src/parallel_spotify.c:510-543``: drop CR/LF, map other
    whitespace and non ``[A-Za-z0-9-._]`` ASCII chars to ``_``; empty result
    falls back to ``"col"``.  (Non-ASCII bytes are "not alnum" to the C
    locale, so every byte of a multi-byte char becomes ``_``.)
    """
    out = []
    for byte in name.encode("utf-8", errors="surrogateescape"):
        ch = chr(byte)
        if ch in "\r\n":
            continue
        if ch in " \t\v\f":
            out.append("_")
        elif ch.isascii() and (ch.isalnum() or ch in "-._"):
            out.append(ch)
        else:
            out.append("_")
    return "".join(out) or "col"


def sanitize_filename(name: str, max_len: int = 80) -> str:
    """Header → filename base, generic-tool semantics.

    Reference ``scripts/split_csv_columns.py:25-29``: newlines → spaces,
    non ``[\\w\\-. ]`` → ``_`` (Unicode word chars allowed), whitespace runs
    → ``_``, truncated to ``max_len``, fallback ``"col"``.
    """
    name = (name or "").replace("\n", " ").replace("\r", " ").strip()
    name = re.sub(r"[^\w\-. ]+", "_", name, flags=re.UNICODE)
    name = re.sub(r"\s+", "_", name)
    return (name or "col")[:max_len]


def split_dataset_columns(
    dataset_path: str,
    split_dir: str,
    artist_base_name: str,
    text_base_name: str,
    artist_header_label: str,
    text_header_label: str,
    backend: str = "auto",
) -> Tuple[str, str]:
    """Write ``<split_dir>/<artist>.csv`` and ``<text>.csv``.

    Matches the reference splitter (``src/parallel_spotify.c:640-721``):
    header label (or ``Artists``/``Texts`` fallback) on the first line, then
    one record per data row with outer quotes preserved verbatim; records
    with fewer than three unquoted commas are skipped.  Uses the C++ fast
    path when available (byte-identical; tested differentially).
    """
    os.makedirs(split_dir, exist_ok=True)
    artist_path = os.path.join(split_dir, artist_base_name + ".csv")
    text_path = os.path.join(split_dir, text_base_name + ".csv")
    if backend in ("auto", "native"):
        from music_analyst_tpu.data import native

        if native.available():
            native.split_columns_native(
                dataset_path, artist_path, text_path,
                artist_header_label or "Artists",
                text_header_label or "Texts",
            )
            return artist_path, text_path
        if backend == "native":
            raise RuntimeError("native splitter requested but unavailable")
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    records = iter_csv_records_exact(data)
    next(records, None)  # header row
    with open(artist_path, "wb") as artist_fp, open(text_path, "wb") as text_fp:
        artist_fp.write((artist_header_label or "Artists").encode("utf-8") + b"\n")
        text_fp.write((text_header_label or "Texts").encode("utf-8") + b"\n")
        for record in records:
            if not record.strip(b"\r\n"):
                continue
            parsed = parse_record_exact(
                record, preserve_artist_quotes=True, preserve_text_quotes=True
            )
            if parsed is None:
                continue
            artist_raw, text_raw = parsed
            artist_fp.write(artist_raw + b"\n")
            text_fp.write(text_raw + b"\n")
    return artist_path, text_path


def read_header_labels(dataset_path: str) -> Tuple[str, str]:
    """Artist/text header labels from the dataset's first record.

    Mirrors the rank-0 preamble (``src/parallel_spotify.c:788-819``): parse
    the header record with quotes stripped; raises ``ValueError`` when the
    header can't be parsed (the reference aborts).
    """
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    header = next(iter_csv_records_exact(data), None)
    if header is None:
        raise ValueError("Dataset does not contain a header row")
    parsed = parse_record_exact(header)
    if parsed is None:
        raise ValueError("Unable to parse dataset header")
    artist_label, text_label = parsed
    return (
        artist_label.decode("utf-8", errors="replace"),
        text_label.decode("utf-8", errors="replace"),
    )


@dataclass(frozen=True)
class _ColumnCsvFormat:
    """Dialect parameters shared by the generic splitter's reader and its
    column writers (same-format round-trip is what keeps unquoted cells
    unquoted and ``\\n`` line endings stable)."""

    delimiter: str = ","
    quotechar: str = '"'
    skipinitialspace: bool = False

    def dialect_kwargs(self) -> dict:
        return dict(
            delimiter=self.delimiter,
            quotechar=self.quotechar,
            doublequote=True,
            skipinitialspace=self.skipinitialspace,
            lineterminator="\n",
            quoting=csv.QUOTE_MINIMAL,
        )


def _resolve_format(
    fh, delimiter: Optional[str], quotechar: str
) -> _ColumnCsvFormat:
    """Explicit delimiter wins; otherwise sniff a 64 KiB sample and fall
    back to commas (reference tool semantics, SURVEY.md §2.2 P9)."""
    quote = quotechar or '"'
    if delimiter:
        return _ColumnCsvFormat(delimiter, quote)
    mark = fh.tell()
    sample = fh.read(65536)
    fh.seek(mark)
    try:
        sniffed = csv.Sniffer().sniff(sample)
    except csv.Error:
        return _ColumnCsvFormat(",", quote)
    return _ColumnCsvFormat(
        sniffed.delimiter, quote, sniffed.skipinitialspace
    )


def _allocate_column_filenames(
    headers: Sequence[str], out_dir: Path, force: bool
) -> List[str]:
    """``<sanitized>.csv`` per column; duplicates (case-insensitive) and
    pre-existing files (unless ``force``) get ``_2, _3…`` suffixes."""
    taken: set = set()
    names: List[str] = []
    for position, header in enumerate(headers, start=1):
        base = sanitize_filename(str(header)) or f"col{position}"
        for suffix in count(1):
            name = f"{base}.csv" if suffix == 1 else f"{base}_{suffix}.csv"
            blocked = name.lower() in taken or (
                (out_dir / name).exists() and not force
            )
            if not blocked:
                break
        taken.add(name.lower())
        names.append(name)
    return names


def split_csv_columns(
    csv_path: str,
    output_dir: Optional[str] = None,
    delimiter: Optional[str] = None,
    quotechar: str = '"',
    encoding: str = "utf-8-sig",
    no_header: bool = False,
    force: bool = False,
) -> Tuple[Path, List[str]]:
    """Generic one-file-per-column splitter.

    Capability parity with ``scripts/split_csv_columns.py`` (artifact
    bytes pinned by ``tests/test_reference_scripts_differential.py``):
    sanitized header filenames with collision suffixes, header row
    re-emitted into each column file unless ``no_header``, short rows
    padded with empty cells, surplus cells dropped.
    """
    in_path = Path(csv_path)
    if not in_path.exists():
        raise FileNotFoundError(str(in_path))
    out_dir = (
        Path(output_dir)
        if output_dir
        else in_path.parent / f"{in_path.stem}_columns"
    )
    out_dir.mkdir(parents=True, exist_ok=True)

    with open(in_path, "r", encoding=encoding, newline="") as fh:
        fmt = _resolve_format(fh, delimiter, quotechar).dialect_kwargs()
        rows: Iterator[List[str]] = csv.reader(fh, **fmt)
        first = next(rows, None)
        if first is None:
            raise ValueError(f"{in_path} is empty")
        if no_header:
            headers = [f"col{i + 1}" for i in range(len(first))]
            rows = chain([first], rows)  # first row is data, not labels
        else:
            headers = [
                str(cell) if str(cell).strip() else f"col{i + 1}"
                for i, cell in enumerate(first)
            ]
        names = _allocate_column_filenames(headers, out_dir, force)

        with ExitStack() as stack:
            sinks = []
            for header, name in zip(headers, names):
                sink_fh = stack.enter_context(
                    open(out_dir / name, "w", encoding=encoding, newline="")
                )
                sink = csv.writer(sink_fh, **fmt)
                if not no_header:
                    sink.writerow([header])
                sinks.append(sink)
            for row in rows:
                for i, sink in enumerate(sinks):
                    sink.writerow([row[i] if i < len(row) else ""])
    return out_dir, names
