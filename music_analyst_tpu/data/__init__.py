"""Host-side data plane: CSV ingest, tokenization, vocabulary.

The reference's L1 data plane (SURVEY.md §1) is a C CSV record
reader/splitter plus a byte-wise tokenizer inside an MPI binary
(``/root/reference/src/parallel_spotify.c:258-304,549-633,350-394``). Here the
data plane is a standalone host library: pure-Python reference
implementations (exact semantics, used for parity tests and small inputs) and
a multithreaded C++ fast path (``native/``) that feeds device buffers.
"""

from music_analyst_tpu.data.tokenizer import tokenize_ascii, tokenize_latin1
from music_analyst_tpu.data.vocab import Vocab
