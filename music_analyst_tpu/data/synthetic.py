"""Synthetic Spotify-like dataset generator.

The real ``spotify_millsongdata.csv`` is stripped from the reference repo
(``.MISSING_LARGE_BLOBS``), so benchmarks and stress tests synthesize a
dataset with the same shape: columns ``artist,song,link,text``, lyrics of
a few hundred words with newlines, quotes, punctuation, apostrophes and the
sentiment keywords at realistic rates.
"""

from __future__ import annotations

import csv
import io
from typing import Optional

import numpy as np

_WORDS = (
    "love heart night time baby life world dream feel know way day eyes "
    "light fire rain soul mind home road song dance sweet blue sun moon "
    "star sky hand face kiss tear smile cry pain joy happy lonely sad "
    "tears sunshine wanna gonna ain't don't can't i'm you're it's never "
    "always together forever yesterday tomorrow remember forget believe "
    "break fall rise run walk stand hold touch whisper scream silence "
    "música coração noite amor céu"
).split()

_ARTIST_FIRST = (
    "The Midnight Electric Golden Silver Crimson Velvet Neon Lunar Solar "
    "Wild Broken Silent Lost Royal"
).split()
_ARTIST_SECOND = (
    "Echoes Rivers Wolves Hearts Shadows Lights Dreamers Strangers "
    "Horizons Sparrows Tides O'Brien Sons, Daughters"
).split()


def generate_dataset(
    path: str,
    num_songs: int = 10_000,
    seed: int = 0,
    mean_words: int = 180,
    num_artists: Optional[int] = None,
) -> None:
    """Write a synthetic dataset CSV with ``num_songs`` rows."""
    rng = np.random.default_rng(seed)
    if num_artists is None:
        num_artists = max(1, num_songs // 25)
    artists = [
        f"{rng.choice(_ARTIST_FIRST)} {rng.choice(_ARTIST_SECOND)} {i}"
        for i in range(num_artists)
    ]
    words = np.array(_WORDS)
    with open(path, "w", newline="", encoding="utf-8") as fh:
        writer = csv.writer(fh)
        writer.writerow(["artist", "song", "link", "text"])
        for i in range(num_songs):
            artist = artists[int(rng.integers(0, num_artists))]
            n_words = max(5, int(rng.normal(mean_words, mean_words // 3)))
            lyric_words = rng.choice(words, size=n_words)
            # newline every ~8 words, like real lyric rows
            parts = []
            for j in range(0, n_words, 8):
                parts.append(" ".join(lyric_words[j : j + 8]))
            text = "  \n".join(parts)
            if i % 97 == 0:
                text = f'She said "{text[:40]}" and left'
            writer.writerow(
                [artist, f"Song {i}", f"/x/{i}.html", text]
            )


def generate_dataset_bytes(num_songs: int = 1000, seed: int = 0) -> bytes:
    buf = io.StringIO()
    rng = np.random.default_rng(seed)
    writer = csv.writer(buf)
    writer.writerow(["artist", "song", "link", "text"])
    words = np.array(_WORDS)
    for i in range(num_songs):
        n_words = max(5, int(rng.normal(120, 40)))
        text = " ".join(rng.choice(words, size=n_words))
        writer.writerow([f"Artist {i % 37}", f"Song {i}", f"/x/{i}", text])
    return buf.getvalue().encode("utf-8")
