"""CSV ingest and export with the reference's exact field semantics.

Three access paths:

* :func:`iter_songs` — fast ``csv.DictReader`` path over the
  ``artist,song,link,text`` dataset, mirroring the sentiment pipeline's
  reader (reference ``scripts/sentiment_classifier.py:111-118``).
* the *exact* byte-level record reader / field extractor replicating the C
  binary's parser (reference ``src/parallel_spotify.c:549-633`` record
  reader, ``:258-304`` line parser, ``:215-255`` field duplication).  Used
  by parity tests and as the oracle for the native C++ ingest.
* :func:`write_count_csv` — the count-table CSV writer: rows sorted count
  descending, ties byte-wise ascending, keys always quoted with ``""``
  doubling (reference ``src/parallel_spotify.c:178-188,307-344``).
"""

from __future__ import annotations

import csv
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

# C-locale isspace() byte set (reference trims fields with isspace,
# src/parallel_spotify.c:191-208).
C_WHITESPACE = b" \t\n\r\x0b\x0c"

_QUOTE = 0x22  # '"'
_COMMA = 0x2C
_NL = 0x0A
_CR = 0x0D


def iter_songs(
    path: str,
    limit: Optional[int] = None,
    encoding: str = "utf-8",
) -> Iterator[Tuple[str, str, str]]:
    """Yield ``(artist, song, text)`` rows like the reference sentiment reader.

    Mirrors ``scripts/sentiment_classifier.py:111-118``: ``csv.DictReader``
    over the named columns, optional row limit applied by row index.  One
    deliberate robustness fix: rows shorter than the header give ``None``
    values from ``DictReader`` and the reference would crash on
    ``None.strip()`` — here missing values coerce to ``""``.
    """
    with open(path, newline="", encoding=encoding) as fh:
        reader = csv.DictReader(fh)
        for index, row in enumerate(reader):
            if limit is not None and index >= limit:
                break
            yield (
                row.get("artist") or "",
                row.get("song") or "",
                row.get("text") or "",
            )


def sniff_delimiter(sample: str, fallback: str = ",") -> str:
    """Delimiter of a CSV sample via ``csv.Sniffer``.

    Used by the per-song tool (reference
    ``scripts/word_count_per_song.py:42-49`` sniffs a 64 KiB sample, comma
    fallback).  The generic splitter needs the full dialect, not just the
    delimiter — see ``data/splitter.py:_resolve_format``.
    """
    try:
        return csv.Sniffer().sniff(sample).delimiter
    except csv.Error:
        return fallback


def iter_csv_records_exact(data: bytes) -> Iterator[bytes]:
    """Split a CSV byte stream into records, quotes-aware.

    Exact re-implementation of the reference's record reader
    (``src/parallel_spotify.c:549-633``): a record ends at an unquoted
    newline; ``""`` inside a quoted field is kept verbatim; a lone ``\\r``
    or ``\\r\\n`` both terminate a record (the terminator bytes are included
    in the yielded record, as in the reference).
    """
    i = 0
    n = len(data)
    while i < n:
        start = i
        in_quotes = False
        while i < n:
            ch = data[i]
            i += 1
            if ch == _QUOTE:
                if not in_quotes:
                    in_quotes = True
                elif i < n and data[i] == _QUOTE:
                    i += 1  # escaped quote stays inside the field
                else:
                    in_quotes = False
            elif (ch == _NL or ch == _CR) and not in_quotes:
                if ch == _CR and i < n and data[i] == _NL:
                    i += 1
                break
        yield data[start:i]


def clean_field(raw: bytes, preserve_outer_quotes: bool = False) -> bytes:
    """Normalize one CSV field exactly like the reference's field duplicator.

    Reference ``src/parallel_spotify.c:215-255``: trim C whitespace; if the
    trimmed field is wrapped in quotes, either keep it verbatim
    (``preserve_outer_quotes``) or strip the quotes and collapse ``""`` to
    ``"``; then trim again.
    """
    stripped = raw.strip(C_WHITESPACE)
    quoted = (
        len(stripped) >= 2
        and stripped[:1] == b'"'
        and stripped[-1:] == b'"'
    )
    if preserve_outer_quotes and quoted:
        out = stripped
    else:
        inner = stripped[1:-1] if quoted else stripped
        out = inner.replace(b'""', b'"')
    return out.strip(C_WHITESPACE)


def _split_record_fields(
    record: bytes,
) -> Optional[Tuple[bytes, bytes, bytes, bytes]]:
    """Split one record at its first three unquoted commas.

    Returns raw ``(field0, field1, field2, rest)`` or ``None`` for records
    with fewer than three unquoted commas (the reference rejects them,
    ``src/parallel_spotify.c:258-304``).
    """
    line = record.rstrip(b"\r\n")
    fields: List[bytes] = []
    in_quotes = False
    start = 0
    i = 0
    n = len(line)
    while i < n:
        ch = line[i]
        if ch == _QUOTE:
            if in_quotes and i + 1 < n and line[i + 1] == _QUOTE:
                i += 1
            else:
                in_quotes = not in_quotes
        elif ch == _COMMA and not in_quotes:
            fields.append(line[start:i])
            start = i + 1
            if len(fields) == 3:
                break
        i += 1
    if len(fields) < 3:
        return None
    return fields[0], fields[1], fields[2], line[start:]


def parse_record_exact(
    record: bytes,
    preserve_artist_quotes: bool = False,
    preserve_text_quotes: bool = False,
) -> Optional[Tuple[bytes, bytes]]:
    """Extract ``(artist, text)`` from one record, reference semantics.

    Reference ``src/parallel_spotify.c:258-304``: split on unquoted commas;
    field 0 is the artist; the *text* is everything after the third unquoted
    comma (untouched — it may itself contain unquoted commas).  Records with
    fewer than three unquoted commas are rejected (``None``).
    """
    split = _split_record_fields(record)
    if split is None:
        return None
    field0, _, _, rest = split
    return (
        clean_field(field0, preserve_artist_quotes),
        clean_field(rest, preserve_text_quotes),
    )


def parse_record_fields(
    record: bytes,
) -> Optional[Tuple[bytes, bytes, bytes]]:
    """Extract cleaned ``(artist, song, text)`` from one record.

    Same splitting/cleaning semantics as :func:`parse_record_exact`, plus
    the *song* column (field 1) — the fused joint pipeline classifies
    sentiment from the very records the histogram pass parsed, and its
    details CSV needs the song title.
    """
    split = _split_record_fields(record)
    if split is None:
        return None
    field0, field1, _, rest = split
    return clean_field(field0), clean_field(field1), clean_field(rest)


def _iter_data_records(data: bytes) -> Iterator[bytes]:
    """Every non-blank data record (header skipped) — the reference's
    record-skip semantics (``src/parallel_spotify.c:690-714``), shared by
    the two dataset iterators below so they can never drift apart."""
    records = iter_csv_records_exact(data)
    next(records, None)  # header
    for record in records:
        if record.strip(b"\r\n"):
            yield record


def iter_dataset_exact(data: bytes) -> Iterator[Tuple[bytes, bytes]]:
    """Yield ``(artist, text)`` for every parseable data record."""
    for record in _iter_data_records(data):
        parsed = parse_record_exact(record)
        if parsed is not None:
            yield parsed


def iter_dataset_fields(data: bytes) -> Iterator[Tuple[bytes, bytes, bytes]]:
    """Yield cleaned ``(artist, song, text)`` for every parseable record."""
    for record in _iter_data_records(data):
        parsed = parse_record_fields(record)
        if parsed is not None:
            yield parsed


def sort_count_entries(
    entries: Iterable[Tuple[str, int]],
) -> List[Tuple[str, int]]:
    """Sort count-descending, ties byte-wise ascending (strcmp order).

    Reference comparator ``src/parallel_spotify.c:178-188``: larger counts
    first, ties broken by ``strcmp`` — reproduced here by comparing the
    UTF-8 bytes of the key (unsigned lexicographic, same as strcmp on the
    reference's raw bytes).
    """
    return sorted(entries, key=lambda kv: (-kv[1], kv[0].encode("utf-8")))


def format_count_row(key: str, value: int) -> str:
    """One output row: key always quoted, inner quotes doubled.

    Reference ``src/parallel_spotify.c:307-319``.
    """
    return '"%s",%d\n' % (key.replace('"', '""'), value)


def write_count_csv(
    path: str,
    key_header: str,
    entries: Sequence[Tuple[str, int]],
    limit: int = 0,
) -> None:
    """Write a sorted count table (reference ``write_table_csv``, :325-344).

    ``limit`` <= 0 means unlimited, matching the reference's default flag
    values (``src/parallel_spotify.c:32-33``).
    """
    from music_analyst_tpu.utils.atomic import atomic_write

    ordered = sort_count_entries(entries)
    if limit > 0:
        ordered = ordered[:limit]
    # Atomic publish: the byte-identity contracts (word_counts.csv vs the
    # reference binary, cold-vs-warm cache, chaos runs) compare whole
    # files — a torn half-write under the final name must be impossible.
    with atomic_write(path, newline="") as fh:
        fh.write("%s,count\n" % key_header)
        for key, value in ordered:
            fh.write(format_count_row(key, value))
