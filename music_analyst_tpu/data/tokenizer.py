"""Reference-exact lyric tokenizers.

The reference has two divergent tokenizers (SURVEY.md §2.2 P7 vs §2.1 C6):

* the C path (``/root/reference/src/parallel_spotify.c:350-394``): byte-wise,
  token chars are ASCII alphanumerics (lowercased) plus apostrophe, anything
  else is a separator (non-ASCII UTF-8 bytes break tokens), tokens counted
  only when >= 3 **bytes** long.  This is the parity target for
  ``word_counts.csv``.
* the serial Python tool (``/root/reference/scripts/word_count_per_song.py:
  27-39``): regex ``[0-9A-Za-zÀ-ÖØ-öø-ÿ']+`` (Latin-1 accented letters are
  token chars), Unicode lowercase, >= 3 **characters**, tokens made only of
  apostrophes rejected.

Both are reimplemented here from their observed behavior; the C semantics are
also implemented in C++ (``native/ingest.cpp``) for the production ingest
path — this module is the oracle the native path is tested against.
"""

from __future__ import annotations

import re
from typing import Iterator, List

# Token chars of the C tokenizer: C-locale isalnum() bytes plus apostrophe
# (reference src/parallel_spotify.c:359).  Operating on ``bytes`` makes every
# non-ASCII UTF-8 byte a separator, exactly like the reference's byte loop.
_ASCII_TOKEN_RE = re.compile(rb"[0-9A-Za-z']+")

# Reference scripts/word_count_per_song.py:27 — note the explicit Latin-1
# accent ranges; this is NOT the same token-character set as the C path.
LATIN1_TOKEN_RE = re.compile(r"[0-9A-Za-zÀ-ÖØ-öø-ÿ']+", re.UNICODE)

MIN_TOKEN_LEN = 3


def tokenize_ascii(text: str | bytes) -> List[str]:
    """Tokenize with the C binary's exact semantics.

    Accepts ``str`` (encoded to UTF-8 first, as the reference reads raw file
    bytes) or ``bytes``.  Returns lowercase ASCII tokens of length >= 3
    bytes.  Apostrophes count toward length and are preserved (a token may
    even be all-apostrophes, e.g. ``'''`` — the reference counts it,
    src/parallel_spotify.c:378-381).
    """
    if isinstance(text, str):
        data = text.encode("utf-8", errors="surrogateescape")
    else:
        data = text
    out: List[str] = []
    append = out.append
    for match in _ASCII_TOKEN_RE.finditer(data):
        tok = match.group()
        if len(tok) >= MIN_TOKEN_LEN:
            # bytes.lower() lowercases exactly the ASCII A-Z range, matching
            # per-byte tolower() in the C locale.
            append(tok.lower().decode("ascii"))
    return out


def tokenize_latin1(text: str) -> Iterator[str]:
    """Tokenize with the serial Python tool's exact semantics.

    Yields lowercase tokens of >= 3 characters; tokens containing no
    alphanumeric character (i.e. all apostrophes) are rejected
    (reference scripts/word_count_per_song.py:30-39).
    """
    for match in LATIN1_TOKEN_RE.finditer(text):
        token = match.group().lower()
        if len(token) < MIN_TOKEN_LEN:
            continue
        if not any(ch.isalnum() for ch in token):
            continue
        yield token
