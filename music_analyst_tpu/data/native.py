"""ctypes bindings for the native C++ ingest library.

The reference's hot path is native C (``src/parallel_spotify.c``); this
framework keeps the host-side hot path native too — a multithreaded C++
scanner/tokenizer (``native/ingest.cpp``) that byte-partitions the dataset
across threads with record-exact boundary handling and merges per-thread
vocabularies.  Python only sees dense numpy arrays.

The library is built on demand with ``make -C native`` (plain g++, no
external deps).  Every entry point degrades gracefully: if the library is
missing and cannot be built, callers fall back to the pure-Python ingest.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libmusicaal.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None


def _try_build() -> None:
    subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-s"],
        check=True,
        capture_output=True,
        timeout=300,
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            # make is dependency-driven: a no-op when the .so is current,
            # a rebuild when ingest.cpp is newer (stale .so would otherwise
            # surface as missing symbols below).  A failed make still
            # falls through to loading a pre-existing library.
            try:
                _try_build()
            except Exception:
                if not os.path.exists(_LIB_PATH):
                    raise
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
        except Exception as exc:  # missing toolchain, build failure,
            # stale .so lacking a symbol (AttributeError from _bind), ...
            _load_error = str(exc)
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    """Declare every exported symbol's signature (raises if one is absent)."""
    lib.man_ingest.restype = ctypes.c_void_p
    lib.man_ingest.argtypes = [ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int]
    lib.man_ingest_v2.restype = ctypes.c_void_p
    lib.man_ingest_v2.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
    ]
    lib.man_records_bytes.restype = ctypes.c_longlong
    lib.man_records_bytes.argtypes = [ctypes.c_void_p]
    lib.man_copy_records.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.man_error.restype = ctypes.c_char_p
    lib.man_error.argtypes = [ctypes.c_void_p]
    lib.man_song_count.restype = ctypes.c_longlong
    lib.man_song_count.argtypes = [ctypes.c_void_p]
    lib.man_token_count.restype = ctypes.c_longlong
    lib.man_token_count.argtypes = [ctypes.c_void_p]
    lib.man_word_vocab_size.restype = ctypes.c_int
    lib.man_word_vocab_size.argtypes = [ctypes.c_void_p]
    lib.man_artist_vocab_size.restype = ctypes.c_int
    lib.man_artist_vocab_size.argtypes = [ctypes.c_void_p]
    lib.man_word_vocab_bytes.restype = ctypes.c_longlong
    lib.man_word_vocab_bytes.argtypes = [ctypes.c_void_p]
    lib.man_artist_vocab_bytes.restype = ctypes.c_longlong
    lib.man_artist_vocab_bytes.argtypes = [ctypes.c_void_p]
    lib.man_copy_word_ids.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.man_copy_word_offsets.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.man_copy_artist_ids.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    # Vocab wire format is length-prefixed (concatenated UTF-8 bytes +
    # an int32 length per token) — artist names may legally contain
    # newlines, so a delimiter-based format would corrupt the mapping.
    lib.man_copy_word_vocab.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.man_copy_artist_vocab.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.man_free.argtypes = [ctypes.c_void_p]
    lib.man_split_columns.restype = ctypes.c_int
    lib.man_split_columns.argtypes = [
        ctypes.c_char_p,  # dataset path
        ctypes.c_char_p,  # artist out path
        ctypes.c_char_p,  # text out path
        ctypes.c_char_p,  # artist header label
        ctypes.c_char_p,  # text header label
        ctypes.c_int,     # num_threads
    ]
    lib.man_record_ranges.restype = ctypes.c_longlong
    lib.man_record_ranges.argtypes = [
        ctypes.c_char_p,  # dataset path
        ctypes.c_int,     # n_procs
        ctypes.c_int,     # p
        ctypes.c_int,     # num_threads
        ctypes.c_void_p,  # out int64[3]: header_end, begin, end
    ]
    lib.man_hash_tokenize_batch.argtypes = [
        ctypes.c_char_p,      # blob
        ctypes.c_void_p,      # offsets int64[n+1]
        ctypes.c_longlong,    # n_rows
        ctypes.c_int,         # max_len
        ctypes.c_int,         # vocab_size
        ctypes.c_int,         # cls_id
        ctypes.c_int,         # sep_id
        ctypes.c_int,         # pad_id
        ctypes.c_int,         # reserved
        ctypes.c_int,         # num_threads
        ctypes.c_void_p,      # out ids
        ctypes.c_void_p,      # out lens
    ]
    lib.man_wp_create.restype = ctypes.c_void_p
    lib.man_wp_create.argtypes = [
        ctypes.c_char_p,      # vocab blob (newline-separated entries)
        ctypes.c_longlong,    # blob bytes
        ctypes.c_int,         # max_word_chars
        ctypes.c_void_p,      # char class table uint8[N]
        ctypes.c_int,         # N (table codepoint bound)
        ctypes.c_char_p,      # replacement blob
        ctypes.c_void_p,      # replacement offsets int32[N+1]
    ]
    lib.man_wp_destroy.argtypes = [ctypes.c_void_p]
    lib.man_wp_encode_batch.argtypes = [
        ctypes.c_void_p,      # vocab handle
        ctypes.c_char_p,      # blob
        ctypes.c_void_p,      # offsets int64[n+1]
        ctypes.c_longlong,    # n_rows
        ctypes.c_int,         # max_len
        ctypes.c_int,         # num_threads
        ctypes.c_void_p,      # out ids
        ctypes.c_void_p,      # out lens
        ctypes.c_void_p,      # handled uint8[n]
    ]


def available() -> bool:
    return _load() is not None


def unavailable_reason() -> str:
    _load()
    return _load_error or "unknown"


def hash_tokenize_batch(
    texts,
    max_len: int,
    vocab_size: int,
    cls_id: int,
    sep_id: int,
    pad_id: int,
    reserved: int,
    num_threads: int = 0,
):
    """C++ batch hash tokenization (spec: models/tokenization.py)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    encoded = [t.encode("utf-8", errors="replace") for t in texts]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    blob = b"".join(encoded)
    n = len(encoded)
    out = np.empty((n, max_len), dtype=np.int32)
    lens = np.empty(n, dtype=np.int32)
    lib.man_hash_tokenize_batch(
        blob,
        offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_longlong(n),
        max_len,
        vocab_size,
        cls_id,
        sep_id,
        pad_id,
        reserved,
        num_threads,
        out.ctypes.data_as(ctypes.c_void_p),
        lens.ctypes.data_as(ctypes.c_void_p),
    )
    return out, lens


def wp_create(
    vocab_path: str, char_table, max_word_chars: int = 100
) -> Optional[int]:
    """Build a native WordPiece vocab handle; None when unavailable or the
    vocab lacks [CLS]/[SEP] (the Python tokenizer raises on those).

    ``char_table`` is ``(classes, repl_blob, offsets)`` from
    ``models/tokenization.py:_wp_char_table`` — the Python-owned Unicode
    semantics the kernel executes.
    """
    lib = _load()
    if lib is None:
        return None
    with open(vocab_path, "rb") as fh:
        blob = fh.read()
    classes, repl_blob, offsets = char_table
    classes = np.ascontiguousarray(classes, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    handle = lib.man_wp_create(
        blob,
        ctypes.c_longlong(len(blob)),
        max_word_chars,
        classes.ctypes.data_as(ctypes.c_void_p),
        int(classes.size),
        repl_blob,
        offsets.ctypes.data_as(ctypes.c_void_p),
    )
    return handle or None


def wp_destroy(handle: int) -> None:
    lib = _load()
    if lib is not None and handle:
        lib.man_wp_destroy(ctypes.c_void_p(handle))


def wp_encode_batch(handle: int, texts, max_len: int, num_threads: int = 0):
    """C++ Latin-fast-path WordPiece; returns ``(ids, lens, handled)``.

    Rows with ``handled == 0`` — a codepoint past the char table
    (≥ U+0370: Greek/Cyrillic/CJK/emoji), invalid UTF-8, or a degenerate
    ``max_len`` — must be re-encoded by the Python tokenizer.  Accented
    Latin rows ARE handled natively (the table covers < U+0370)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    # surrogatepass, NOT replace: a lone surrogate must reach the kernel
    # as the invalid UTF-8 it is, so the row is flagged unhandled and the
    # Python fallback (which drops it as a C*-category char) keeps the
    # identical-output contract; "replace" would tokenize a synthetic '?'.
    encoded = [t.encode("utf-8", errors="surrogatepass") for t in texts]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    blob = b"".join(encoded)
    n = len(encoded)
    out = np.empty((n, max_len), dtype=np.int32)
    lens = np.empty(n, dtype=np.int32)
    handled = np.empty(n, dtype=np.uint8)
    lib.man_wp_encode_batch(
        ctypes.c_void_p(handle),
        blob,
        offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_longlong(n),
        max_len,
        num_threads,
        out.ctypes.data_as(ctypes.c_void_p),
        lens.ctypes.data_as(ctypes.c_void_p),
        handled.ctypes.data_as(ctypes.c_void_p),
    )
    return out, lens, handled


def split_columns_native(
    dataset_path: str,
    artist_path: str,
    text_path: str,
    artist_header: str,
    text_header: str,
    num_threads: int = 0,
) -> bool:
    """C++ column split; returns False when the library is unavailable."""
    lib = _load()
    if lib is None:
        return False
    rc = lib.man_split_columns(
        dataset_path.encode("utf-8"),
        artist_path.encode("utf-8"),
        text_path.encode("utf-8"),
        artist_header.encode("utf-8"),
        text_header.encode("utf-8"),
        num_threads,
    )
    if rc != 1:
        raise RuntimeError(f"native column split failed for {dataset_path}")
    return True


def record_range(
    path: str, n_procs: int, p: int, num_threads: int = 0
) -> tuple:
    """Process ``p``'s record-exact slice of the dataset's data records.

    Returns ``(header_end, begin, end, n_records)`` byte offsets: the
    header record is ``[0, header_end)`` and the slice ``[begin, end)``.
    Runs the C++ parallel boundary scan — memory-bandwidth work instead of
    the per-byte Python parse it replaces.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    out = (ctypes.c_longlong * 3)()
    n = lib.man_record_ranges(
        path.encode("utf-8"), ctypes.c_int(n_procs), ctypes.c_int(p),
        ctypes.c_int(num_threads), out,
    )
    if n < 0:
        raise RuntimeError(f"native record scan failed to read {path!r}")
    return int(out[0]), int(out[1]), int(out[2]), int(n)


def ingest_native(
    path: str,
    limit: Optional[int] = None,
    num_threads: int = 0,
    capture_records: bool = False,
    cache_dir: Optional[str] = None,
):
    """Run the C++ ingest and wrap the results as an :class:`IngestResult`.

    ``cache_dir`` plugs this backend into the persistent corpus cache
    (``data/corpus_cache.py``): a hit returns memory-mapped arrays without
    touching the C++ parser, a miss parses then stores under the
    ``native``-keyed entry.
    """
    from music_analyst_tpu.data.ingest import IngestResult
    from music_analyst_tpu.data.vocab import Vocab

    from music_analyst_tpu.telemetry import get_telemetry

    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    if cache_dir:
        from music_analyst_tpu.data import corpus_cache

        cached = corpus_cache.load(
            cache_dir, path, limit, capture_records, "native"
        )
        if cached is not None:
            return cached
    tel = get_telemetry()
    try:
        file_bytes = os.path.getsize(path)
    except OSError:
        file_bytes = 0
    # The span times the C++ parse only — the native boundary the run log
    # wants isolated; the numpy copy-out below is host-side glue.
    with tel.span("native_ingest", bytes=file_bytes):
        handle = lib.man_ingest_v2(
            path.encode("utf-8"),
            ctypes.c_longlong(-1 if limit is None else limit),
            ctypes.c_int(num_threads),
            ctypes.c_int(1 if capture_records else 0),
        )
    if not handle:
        raise RuntimeError("native ingest failed to allocate")
    try:
        err = lib.man_error(handle)
        if err:
            raise RuntimeError(f"native ingest: {err.decode()}")
        songs = lib.man_song_count(handle)
        tokens = lib.man_token_count(handle)
        tel.count("native_bytes_parsed", file_bytes)
        tel.count("native_songs_parsed", int(songs))
        tel.count("native_tokens_parsed", int(tokens))
        word_ids = np.empty(tokens, dtype=np.int32)
        word_offsets = np.empty(songs + 1, dtype=np.int64)
        artist_ids = np.empty(songs, dtype=np.int32)
        if tokens:
            lib.man_copy_word_ids(handle, word_ids.ctypes.data_as(ctypes.c_void_p))
        lib.man_copy_word_offsets(handle, word_offsets.ctypes.data_as(ctypes.c_void_p))
        if songs:
            lib.man_copy_artist_ids(handle, artist_ids.ctypes.data_as(ctypes.c_void_p))
        def _read_vocab(count: int, total_bytes: int, copy_fn) -> list:
            if count == 0:
                return []
            buf = ctypes.create_string_buffer(max(1, total_bytes))
            lens = np.empty(count, dtype=np.int32)
            copy_fn(handle, buf, lens.ctypes.data_as(ctypes.c_void_p))
            blob = buf.raw[:total_bytes]
            tokens = []
            pos = 0
            for n in lens.tolist():
                tokens.append(blob[pos : pos + n].decode("utf-8", errors="replace"))
                pos += n
            return tokens

        word_tokens = _read_vocab(
            lib.man_word_vocab_size(handle),
            lib.man_word_vocab_bytes(handle),
            lib.man_copy_word_vocab,
        )
        artist_tokens = _read_vocab(
            lib.man_artist_vocab_size(handle),
            lib.man_artist_vocab_bytes(handle),
            lib.man_copy_artist_vocab,
        )
        records_blob = None
        record_offsets = None
        if capture_records:
            n_bytes = lib.man_records_bytes(handle)
            buf = ctypes.create_string_buffer(max(1, n_bytes))
            record_offsets = np.empty(3 * songs + 1, dtype=np.int64)
            lib.man_copy_records(
                handle, buf, record_offsets.ctypes.data_as(ctypes.c_void_p)
            )
            records_blob = buf.raw[:n_bytes]
        result = IngestResult(
            word_vocab=Vocab(word_tokens),
            word_ids=word_ids,
            word_offsets=word_offsets,
            artist_vocab=Vocab(artist_tokens),
            artist_ids=artist_ids,
            song_count=int(songs),
            records_blob=records_blob,
            record_offsets=record_offsets,
        )
        if cache_dir:
            corpus_cache.store(
                cache_dir, path, limit, capture_records, "native", result
            )
        return result
    finally:
        lib.man_free(handle)
