"""String→id vocabulary: the bridge between host strings and device ints.

The key structural translation from the reference (SURVEY.md §2.4): the MPI
build ships string-keyed hash tables between ranks
(``src/parallel_spotify.c:396-432``); on TPU the idiomatic design keeps
strings on the host, assigns dense int32 ids here, and reduces dense count
vectors on device with one ``psum``.  This class is that host-side id
assignment.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np


class Vocab:
    """Insertion-ordered string→int32 id map."""

    __slots__ = ("_index", "_tokens")

    def __init__(self, tokens: Iterable[str] = ()) -> None:
        self._index: Dict[str, int] = {}
        self._tokens: List[str] = []
        for tok in tokens:
            self.add(tok)

    def add(self, token: str) -> int:
        idx = self._index.get(token)
        if idx is None:
            idx = len(self._tokens)
            self._index[token] = idx
            self._tokens.append(token)
        return idx

    def get(self, token: str, default: int = -1) -> int:
        return self._index.get(token, default)

    def __len__(self) -> int:
        return len(self._tokens)

    def __contains__(self, token: str) -> bool:
        return token in self._index

    @property
    def tokens(self) -> List[str]:
        return self._tokens

    def token(self, idx: int) -> str:
        return self._tokens[idx]

    def counts_to_entries(self, counts: np.ndarray) -> List[Tuple[str, int]]:
        """Pair each vocab string with its dense count; drop zero counts."""
        out: List[Tuple[str, int]] = []
        for idx, value in enumerate(np.asarray(counts).tolist()):
            if value:
                out.append((self._tokens[idx], int(value)))
        return out


def encode_corpus(
    token_lists: Iterable[Sequence[str]],
    vocab: Vocab | None = None,
) -> Tuple[Vocab, np.ndarray, np.ndarray]:
    """Flatten per-song token lists into device-ready dense arrays.

    Returns ``(vocab, flat_ids int32[N], offsets int64[S+1])`` where song
    ``s`` owns ``flat_ids[offsets[s]:offsets[s+1]]``.  This is the host→HBM
    handoff format shared with the native C++ ingest.
    """
    if vocab is None:
        vocab = Vocab()
    ids: List[int] = []
    offsets: List[int] = [0]
    add = vocab.add
    for toks in token_lists:
        ids.extend(add(t) for t in toks)
        offsets.append(len(ids))
    return vocab, np.asarray(ids, dtype=np.int32), np.asarray(offsets, dtype=np.int64)
