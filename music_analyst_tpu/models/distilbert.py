"""DistilBERT-sst2-style encoder classifier (BASELINE.json config[2]).

The batched on-device replacement for the reference's per-song HTTP loop
(``scripts/sentiment_classifier.py:85-100``): a 6-layer post-LN transformer
encoder with learned positions and a CLS head, matching the
``distilbert-base-uncased-finetuned-sst-2-english`` architecture so real
checkpoints drop in when available (``load_hf_torch_checkpoint``), while
random init keeps the pipeline, sharding, and benchmarks runnable in this
zero-egress environment.

Label contract: sst2 is 2-class (negative/positive).  The mapping onto the
reference's 3-label API (SURVEY.md §7 step 5 — "documented mapping") is
confidence-thresholded: ``max softmax prob < neutral_threshold`` →
``Neutral``, else argmax → ``Positive``/``Negative``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from music_analyst_tpu.engines.sentiment import ClassifierBackend
from music_analyst_tpu.models.layers import (
    GeluMLP,
    MultiHeadAttention,
    padding_mask,
    segment_mask,
)
from music_analyst_tpu.models.tokenization import resolve_bert_tokenizer


# HF DistilBERT hardcodes nn.LayerNorm(eps=1e-12) (flax defaults to
# 1e-6); match it exactly so real checkpoints reproduce the reference
# forward — the oracle tests share this constant.
LN_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class DistilBertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    hidden_dim: int = 3072
    max_positions: int = 512
    n_classes: int = 2
    dtype: str = "bfloat16"
    # "flash" = Pallas blocked attention (padding-mask path); max_len must
    # divide the kernel block size.
    attn_impl: str = "dense"
    # "int8" = dynamic-quant projections/MLP on the MXU int8 path
    # (ops/quant.py; ~2.1x bf16 matmul throughput per the roofline suite).
    # Inference-only; small logit perturbation bounded by tests/test_quant.py.
    quant: str = "none"
    # "int8"/"int4" = stored weight-quantized projection/MLP kernels
    # (QuantizedParam leaves; ops/quant.py).  Embeddings, norms, and the
    # classifier heads stay float.  Mutually exclusive with `quant`.
    weight_quant: str = "none"

    def __post_init__(self):
        if self.weight_quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"weight_quant must be none/int8/int4, got "
                f"{self.weight_quant!r}"
            )
        if self.weight_quant != "none" and self.quant != "none":
            raise ValueError(
                "weight_quant and dynamic quant are mutually exclusive — "
                "the stored-weight path already runs the int8 MXU matmul"
            )

    @classmethod
    def tiny(cls) -> "DistilBertConfig":
        return cls(vocab_size=1024, dim=64, n_layers=2, n_heads=4,
                   hidden_dim=128, max_positions=128)


class TransformerBlock(nn.Module):
    """Post-LN block: x → LN(x + attn(x)) → LN(· + mlp(·))."""

    config: DistilBertConfig

    @nn.compact
    def __call__(self, x, mask, lengths=None, segment_ids=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        attn_out = MultiHeadAttention(
            n_heads=cfg.n_heads, dtype=dtype, attn_impl=cfg.attn_impl,
            use_bias=True,  # HF DistilBERT q/k/v/out projections have biases
            quant=cfg.quant, weight_quant=cfg.weight_quant,
            name="attention",
        )(x, mask=None if cfg.attn_impl == "flash" else mask,
          lengths=lengths,
          segment_ids=segment_ids if cfg.attn_impl == "flash" else None)
        x = nn.LayerNorm(
            name="sa_layer_norm", dtype=dtype, epsilon=LN_EPS
        )(x + attn_out)
        mlp_out = GeluMLP(cfg.hidden_dim, dtype=dtype, quant=cfg.quant,
                          weight_quant=cfg.weight_quant, name="ffn")(x)
        return nn.LayerNorm(
            name="output_layer_norm", dtype=dtype, epsilon=LN_EPS
        )(x + mlp_out)


class DistilBertEncoder(nn.Module):
    config: DistilBertConfig

    @nn.compact
    def __call__(self, token_ids, lengths, positions=None, segment_ids=None):
        """Encode ``[B, S]`` ids.

        Flat mode (``positions``/``segment_ids`` omitted): positions are
        ``0..S-1`` and masking is key-padding from ``lengths`` — the
        original single-lyric-per-row contract.

        Packed mode (SURVEY §7 "packed batching"): rows carry several
        lyrics back to back.  ``segment_ids`` ``[B, S]`` labels each token
        with its lyric (0 = padding) and attention is restricted to
        same-segment pairs, so lyrics sharing a row can never see each
        other; ``positions`` ``[B, S]`` restart at every segment boundary
        so each lyric receives exactly the position embeddings it would
        have gotten in its own row.
        """
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        if positions is None:
            positions = jnp.arange(token_ids.shape[1])[None, :]
        tok = nn.Embed(cfg.vocab_size, cfg.dim, dtype=dtype,
                       name="word_embeddings")(token_ids)
        pos = nn.Embed(cfg.max_positions, cfg.dim, dtype=dtype,
                       name="position_embeddings")(positions)
        x = nn.LayerNorm(
            name="embed_layer_norm", dtype=dtype, epsilon=LN_EPS
        )(tok + pos)
        if segment_ids is not None:
            # Block-diagonal: token pairs attend iff same segment.  The
            # dense impl gets a mask array; the flash kernel takes the
            # segment ids natively (ops/flash_attention.py segment mode).
            # Padding (segment 0) forms its own group, so a fully padded
            # tail (or row) either softmaxes over uniform masked logits
            # (dense — finite fill keeps it NaN-free) or outputs zeros
            # (flash guarded denominator); it is never gathered by the
            # head either way.
            mask = (
                None if cfg.attn_impl == "flash"
                else segment_mask(segment_ids)
            )
        else:
            mask = padding_mask(lengths, token_ids.shape[1])
        # CONTRACT: with cfg.attn_impl == "flash", attention masking is
        # derived from `lengths` + optional `segment_ids` (key padding +
        # block-diagonal); the mask array is only consumed by the dense
        # impl.
        for i in range(cfg.n_layers):
            x = TransformerBlock(cfg, name=f"layer_{i}")(
                x, mask, lengths, segment_ids=segment_ids
            )
        return x


class DistilBertForSentiment(nn.Module):
    """Encoder + CLS head → class logits.

    Flat mode returns ``[B, n_classes]`` from each row's position-0 CLS.
    Packed mode (``cls_index`` ``[B, K]`` = the CLS offset of each of up
    to K lyrics per row) returns ``[B, K, n_classes]`` — the head runs on
    every segment's own CLS vector; unused slots (index clamped into the
    row) produce garbage logits the caller masks out.
    """

    config: DistilBertConfig

    @nn.compact
    def __call__(self, token_ids, lengths, positions=None, segment_ids=None,
                 cls_index=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = DistilBertEncoder(cfg, name="encoder")(
            token_ids, lengths, positions=positions, segment_ids=segment_ids
        )
        if cls_index is None:
            cls = x[:, 0]  # [CLS]
        else:
            cls = jnp.take_along_axis(
                x, cls_index[:, :, None].astype(jnp.int32), axis=1
            )                                               # [B, K, D]
        h = nn.Dense(cfg.dim, dtype=dtype, name="pre_classifier")(cls)
        h = nn.relu(h)
        return nn.Dense(cfg.n_classes, dtype=jnp.float32, name="classifier")(h)


def iter_hf_param_units(params, path: str, mmap: bool = False):
    """Stream an HF DistilBERT torch ``state_dict`` as layer-sized units.

    Yields ``(unit_name, [("/"-joined tree path, np.ndarray), …])`` —
    embeddings, then one unit per transformer layer, then the classifier
    head — in the layout ``load_quantized_params`` consumes, so the
    quantize-on-load path holds at most one unit of float tensors at a
    time.  Kernel matrices transpose (torch Linear stores ``[out, in]``),
    attention projections (weights AND biases) reshape to the
    ``[dim, heads, head_dim]`` head layout.  Every checkpoint tensor must
    be consumed — leftover keys raise at the end of the stream, so a
    checkpoint with unexpected structure can never silently half-load.
    ``params`` supplies shapes only; ``ShapeDtypeStruct`` trees work.
    """
    import torch

    try:
        sd = torch.load(path, map_location="cpu", weights_only=True,
                        mmap=mmap)
    except (RuntimeError, ValueError, TypeError):
        # Non-zipfile (legacy) serialization or older torch: mmap
        # unsupported — fall back to an eager read.
        sd = torch.load(path, map_location="cpu", weights_only=True)
    enc_shapes = params["encoder"]
    cfg_heads = enc_shapes["layer_0"]["attention"]["q_proj"]["kernel"].shape[1]
    dim = enc_shapes["word_embeddings"]["embedding"].shape[1]
    head_dim = dim // cfg_heads
    consumed = set()

    def t(name):
        consumed.add(name)
        return np.asarray(sd[name].numpy())

    yield "embeddings", [
        ("encoder/word_embeddings/embedding",
         t("distilbert.embeddings.word_embeddings.weight")),
        ("encoder/position_embeddings/embedding",
         t("distilbert.embeddings.position_embeddings.weight")),
        ("encoder/embed_layer_norm/scale",
         t("distilbert.embeddings.LayerNorm.weight")),
        ("encoder/embed_layer_norm/bias",
         t("distilbert.embeddings.LayerNorm.bias")),
    ]
    n_layers = sum(1 for k in enc_shapes if k.startswith("layer_"))
    for i in range(n_layers):
        hf = f"distilbert.transformer.layer.{i}"
        p = f"encoder/layer_{i}"
        leaves = []
        for ours, theirs in (("q_proj", "q_lin"), ("k_proj", "k_lin"),
                             ("v_proj", "v_lin")):
            w = t(f"{hf}.attention.{theirs}.weight").T  # [in, out]
            leaves.append((f"{p}/attention/{ours}/kernel",
                           w.reshape(dim, cfg_heads, head_dim)))
            leaves.append((f"{p}/attention/{ours}/bias",
                           t(f"{hf}.attention.{theirs}.bias").reshape(
                               cfg_heads, head_dim)))
        leaves.append((f"{p}/attention/o_proj/kernel",
                       t(f"{hf}.attention.out_lin.weight").T.reshape(
                           cfg_heads, head_dim, dim)))
        leaves.append((f"{p}/attention/o_proj/bias",
                       t(f"{hf}.attention.out_lin.bias")))
        leaves.append((f"{p}/sa_layer_norm/scale",
                       t(f"{hf}.sa_layer_norm.weight")))
        leaves.append((f"{p}/sa_layer_norm/bias",
                       t(f"{hf}.sa_layer_norm.bias")))
        leaves.append((f"{p}/ffn/lin1/kernel", t(f"{hf}.ffn.lin1.weight").T))
        leaves.append((f"{p}/ffn/lin1/bias", t(f"{hf}.ffn.lin1.bias")))
        leaves.append((f"{p}/ffn/lin2/kernel", t(f"{hf}.ffn.lin2.weight").T))
        leaves.append((f"{p}/ffn/lin2/bias", t(f"{hf}.ffn.lin2.bias")))
        leaves.append((f"{p}/output_layer_norm/scale",
                       t(f"{hf}.output_layer_norm.weight")))
        leaves.append((f"{p}/output_layer_norm/bias",
                       t(f"{hf}.output_layer_norm.bias")))
        yield f"layer_{i}", leaves
    yield "head", [
        ("pre_classifier/kernel", t("pre_classifier.weight").T),
        ("pre_classifier/bias", t("pre_classifier.bias")),
        ("classifier/kernel", t("classifier.weight").T),
        ("classifier/bias", t("classifier.bias")),
    ]
    # Non-parameter buffers some transformers versions serialize.
    ignorable = {k for k in sd if k.endswith("position_ids")}
    leftovers = set(sd) - consumed - ignorable
    if leftovers:
        raise ValueError(
            "checkpoint keys not consumed by the DistilBERT mapping: "
            + ", ".join(sorted(leftovers)[:8])
        )


def load_hf_torch_checkpoint(params, path: str):
    """Map an HF DistilBERT torch ``state_dict`` onto the Flax params.

    Eager wrapper over ``iter_hf_param_units`` — see it for the mapping
    contract (transposes, head-layout reshapes, consumed-keys check).
    """
    new = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    for _, leaves in iter_hf_param_units(params, path):
        for tree_path, arr in leaves:
            parts = tree_path.split("/")
            node = new
            for part in parts[:-1]:
                node = node[part]
            node[parts[-1]] = arr
    return new


def derive_length_buckets(
    lengths,
    max_len: int,
    min_share: float = 0.05,
    floor: int = 16,
) -> Tuple[int, ...]:
    """Pick power-of-two sequence buckets from an observed length sample.

    Data-driven default for the SURVEY §7 "ragged lyrics" lever: each kept
    bucket must absorb at least ``min_share`` of the sampled rows — a bucket
    costs one compiled program per batch shape, and one holding few rows
    saves negligible FLOPs.  Rows skipped by a dropped bucket roll upward
    into the next candidate.  Returns ``()`` when the sample is dominated
    by full-length rows (real lyric corpora mostly are at ``max_len`` 128):
    the flat path is then already optimal, and auto mode stays flat.
    """
    lengths = np.asarray(lengths)
    out = []
    if lengths.size:
        prev = 0
        b = floor
        while b < max_len:
            share = float(((lengths > prev) & (lengths <= b)).mean())
            if share >= min_share:
                out.append(b)
                prev = b
            b <<= 1
    return tuple(out)


def pack_segments(
    lengths, capacity: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Best-fit-decreasing bin packing of per-lyric token lengths.

    The SURVEY §7 "packed batching" lever: several short lyrics share one
    ``capacity``-wide row instead of each padding its own row out (the
    reference pads nothing because it classifies one song per blocking
    HTTP call, ``scripts/sentiment_classifier.py:144-154``; a batched
    device path pays for padding in real FLOPs).  Best-fit over the open
    rows' remaining capacities (binary search per lyric, ~11/9·OPT worst
    case) keeps host cost at O(n log n) for 8k-row batches.

    Returns ``(bin_of, slot_of, starts, row_len)``: input ``i`` becomes
    segment ``slot_of[i]`` of packed row ``bin_of[i]``; ``starts[p, k]``
    is the token offset of each row's ``k``-th segment (``capacity``
    sentinel for unused slots — never a valid offset); ``row_len[p]`` is
    the occupied prefix of each row.
    """
    import bisect

    lengths = np.asarray(lengths, dtype=np.int64)
    if lengths.size and (lengths <= 0).any():
        # A zero-length segment would collide with the sentinel (or with a
        # neighbor's offset) and gather another lyric's CLS as its own.
        # Unreachable via the classifier (every tokenizer emits ≥ 2 ids,
        # CLS+SEP), but the helper is public — enforce the precondition.
        raise ValueError("pack_segments requires every length > 0")
    if lengths.size and int(lengths.max()) > capacity:
        raise ValueError(
            f"segment length {int(lengths.max())} exceeds capacity "
            f"{capacity}"
        )
    n = int(lengths.size)
    bin_of = np.zeros(n, np.int64)
    slot_of = np.zeros(n, np.int64)
    rems: list = []       # open-row remaining capacities, ascending
    rem_bin: list = []    # parallel row ids
    rows: list = []       # input indices per row, placement order
    for i in np.argsort(-lengths, kind="stable"):
        need = int(lengths[i])
        j = bisect.bisect_left(rems, need)
        if j == len(rems):
            rem, b = capacity, len(rows)
            rows.append([])
        else:
            rem, b = rems.pop(j), rem_bin.pop(j)
        bin_of[i] = b
        slot_of[i] = len(rows[b])
        rows[b].append(int(i))
        rem -= need
        j = bisect.bisect_left(rems, rem)
        rems.insert(j, rem)
        rem_bin.insert(j, b)
    n_rows = len(rows)
    n_slots = max((len(r) for r in rows), default=0)
    starts = np.full((n_rows, n_slots), capacity, np.int64)
    row_len = np.zeros(n_rows, np.int64)
    for b, members in enumerate(rows):
        offset = 0
        for k, i in enumerate(members):
            starts[b, k] = offset
            offset += int(lengths[i])
        row_len[b] = offset
    return bin_of, slot_of, starts, row_len


class DistilBertClassifier(ClassifierBackend):
    """Batched data-parallel sentiment backend.

    ``neutral_threshold`` (default 0.6) is the 2→3-label calibration knob:
    the sst2 head is binary, so its max softmax prob is ≥ 0.5 by
    construction, and the band [0.5, threshold) — a logit margin under
    ``ln(threshold/(1-threshold))``, ≈0.405 at 0.6 — is mapped to
    ``Neutral``.  This mirrors the reference's behavior of bucketing every
    non-committal model answer into Neutral (``utils/labels.py`` /
    ``scripts/sentiment_classifier.py:101-107``): 0.6 keeps near-equipoise
    lyrics out of Positive/Negative while letting any clear sst2 verdict
    through.  It is a deployment knob, not a learned constant — the tested
    contract (``tests/test_models.py``) is monotonicity: threshold 0.5
    never yields Neutral on non-empty text, threshold 1.0 always does.
    """

    name = "distilbert"

    # sst2 head order in the HF checkpoint: [NEGATIVE, POSITIVE]
    _CLASS_LABELS = ("Negative", "Positive")

    def __init__(
        self,
        config: Optional[DistilBertConfig] = None,
        checkpoint_path: Optional[str] = None,
        max_len: int = 128,
        neutral_threshold: float = 0.6,
        mesh=None,
        seed: int = 0,
        vocab_path: Optional[str] = None,
        length_buckets: Optional[Sequence[int]] = None,
        packed: bool = False,
        wq_cache_dir: Optional[str] = None,
    ) -> None:
        self.config = config or DistilBertConfig()
        self.max_len = max_len
        self.neutral_threshold = neutral_threshold
        self.packed = bool(packed)
        if self.packed and length_buckets:
            # Packing already right-sizes padding within full-width rows;
            # composing the two would bucket *rows of several lyrics* by
            # the wrong lengths.  One lever at a time.  (Flash attention
            # DOES compose: the kernel takes segment ids natively.)
            raise ValueError(
                "packed=True cannot be combined with length_buckets"
            )
        # "auto" defers to the first submitted batch's length distribution
        # (resolved via derive_length_buckets); a sequence is validated now.
        if isinstance(length_buckets, str):
            if length_buckets != "auto":
                # Catch the CLI syntax leaking into the API: tuple("32,64")
                # would otherwise iterate characters and raise nonsense.
                raise ValueError(
                    "length_buckets must be 'auto' or a sequence of ints, "
                    f"got the string {length_buckets!r}"
                )
            self.length_buckets = "auto"
        else:
            self.length_buckets = self._check_buckets(length_buckets, max_len)
        self.tokenizer = resolve_bert_tokenizer(
            vocab_path, vocab_size=self.config.vocab_size
        )
        self.model = DistilBertForSentiment(self.config)
        dummy = (
            jnp.zeros((1, max_len), jnp.int32),
            jnp.ones((1,), jnp.int32),
        )
        wq = self.config.weight_quant
        if checkpoint_path and wq != "none":
            # Streaming quantize-on-load: the float tree is never
            # materialized — only per-unit shapes via eval_shape, then the
            # layer-by-layer quantize→H2D pipeline (engines/checkpoint.py).
            from music_analyst_tpu.engines import wq_cache
            from music_analyst_tpu.engines.checkpoint import (
                load_quantized_params,
            )
            from music_analyst_tpu.ops.quant import WQ_DEFAULT_GROUP

            params_shape = jax.eval_shape(
                self.model.init, jax.random.key(seed), *dummy
            )["params"]
            cache_dir = wq_cache.resolve_cache_dir(wq_cache_dir)
            cache_key = (
                wq_cache.wq_key(checkpoint_path, "distilbert", wq,
                                WQ_DEFAULT_GROUP)
                if cache_dir else None
            )
            self.params = load_quantized_params(
                params_shape,
                lambda: iter_hf_param_units(
                    params_shape, checkpoint_path, mmap=True
                ),
                wq,
                group_size=WQ_DEFAULT_GROUP,
                mesh=mesh,
                cache_dir=cache_dir,
                cache_key=cache_key,
            )
            self.pretrained = True
        else:
            self.params = self.model.init(
                jax.random.key(seed), *dummy
            )["params"]
            self.pretrained = False
            if checkpoint_path:
                self.params = load_hf_torch_checkpoint(
                    self.params, checkpoint_path
                )
                self.pretrained = True
            if wq != "none":
                from music_analyst_tpu.ops.quant import (
                    WQ_DEFAULT_GROUP,
                    quantize_tree,
                )

                self.params = quantize_tree(self.params, wq, WQ_DEFAULT_GROUP)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from music_analyst_tpu.parallel.sharding import shard_params

            # Megatron-style TP rules; axes absent from the mesh prune to
            # replication, so the same call serves dp-only and dp×tp.
            self.params = shard_params(self.params, mesh)
            self._data_sharding = NamedSharding(mesh, P("dp"))
        else:
            self._data_sharding = None
        self.mesh = mesh

        from music_analyst_tpu.profiling.compile import profiled_jit
        from music_analyst_tpu.runtime.wire import forward_donation_kwargs

        def _forward(params, token_ids, lengths):
            # ids/lengths may arrive int16 (see _wire_dtype/_index_dtype)
            # — widen on device.
            logits = self.model.apply(
                {"params": params},
                token_ids.astype(jnp.int32),
                lengths.astype(jnp.int32),
            )
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.argmax(logits, axis=-1), jnp.max(probs, axis=-1)

        # Steady-state forwards donate their input batch: the H2D staging
        # buffer is dead the moment the widened copy exists, so XLA may
        # reuse its space for temporaries instead of pinning ~depth+1
        # staged batches live across the step (no-op on the CPU test mesh,
        # see forward_donation_kwargs).
        self._forward = profiled_jit(
            _forward, name="distilbert_forward",
            **forward_donation_kwargs(1, 2),
        )

        def _forward_packed(params, token_ids, starts, row_len):
            """Packed rows: expand the compact per-segment wire format
            (``starts`` [P,K] with ``S`` sentinel + ``row_len`` [P]) into
            segment ids / restarted positions ON DEVICE — the host ships
            ~2 extra bytes per segment instead of 2 extra arrays of S
            bytes per row across the ~10 MB/s tunnel."""
            seq = token_ids.shape[1]
            ids = token_ids.astype(jnp.int32)
            st = starts.astype(jnp.int32)                    # [P, K]
            s_axis = jnp.arange(seq, dtype=jnp.int32)
            started = st[:, :, None] <= s_axis[None, None, :]  # [P, K, S]
            # Segment id = number of starts at or before s (starts[0] is
            # always 0, sentinel starts never fire) → 1..K; padding tail
            # (s ≥ row_len) and all-pad rows drop to segment 0, which
            # never equals a real segment in the block-diagonal mask.
            seg = started.sum(axis=1, dtype=jnp.int32)         # [P, S]
            valid = s_axis[None, :] < row_len[:, None].astype(jnp.int32)
            seg = jnp.where(valid, seg, 0)
            last_start = jnp.max(
                jnp.where(started, st[:, :, None], -1), axis=1
            )                                                  # [P, S]
            positions = s_axis[None, :] - jnp.maximum(last_start, 0)
            logits = self.model.apply(
                {"params": params},
                ids,
                row_len.astype(jnp.int32),
                positions=positions,
                segment_ids=seg,
                cls_index=jnp.minimum(st, seq - 1),
            )                                                  # [P, K, C]
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.argmax(logits, axis=-1), jnp.max(probs, axis=-1)

        self._forward_packed = profiled_jit(
            _forward_packed, name="distilbert_forward_packed",
            **forward_donation_kwargs(1, 2, 3),
        )
        # Host→device transfer rides a ~10 MB/s tunnel in this environment
        # (roofline suite); token ids are the payload, and every BERT-sized
        # vocab fits int16, halving the bytes on the wire.  Lossless: the
        # cast back to int32 happens on device inside the jit.
        # Sized from the TOKENIZER's id range, not the model config: a
        # supplied vocab.txt (MUSICAAL_BERT_VOCAB) can be larger than the
        # config vocab, and an int16 wire would silently wrap its ids.
        wire_vocab = max(self.config.vocab_size, self.tokenizer.vocab_size)
        self._wire_dtype = np.int16 if wire_vocab <= (1 << 15) else np.int32
        # Packed-row segment starts / row lengths are positions in
        # [0, max_len] (max_len itself is the empty-slot sentinel), so the
        # same wire-narrowing applies — conditioned on max_len, not the
        # vocab: a long-context config must not wrap its offsets.
        self._index_dtype = np.int16 if max_len < (1 << 15) else np.int32

    @classmethod
    def from_pretrained_or_random(cls, model: str, **kwargs):
        """Resolve ``--model distilbert[...]`` to a backend instance.

        Checkpoint lookup: explicit kwarg, else ``$MUSICAAL_DISTILBERT_CKPT``.
        Without a checkpoint the model runs with random weights (documented:
        throughput/sharding are exercised; accuracy needs real weights).
        """
        ckpt = kwargs.pop("checkpoint_path", None) or os.environ.get(
            "MUSICAAL_DISTILBERT_CKPT"
        )
        config = kwargs.pop("config", None)
        # Suffixes compose in any order (distilbert-tiny-int8-packed ==
        # distilbert-tiny-packed-int8): strip to fixpoint.
        quant, tiny = "none", False
        stripped = True
        while stripped:
            stripped = True
            if model.endswith("-packed"):
                model = model[: -len("-packed")]
                kwargs.setdefault("packed", True)
            elif model.endswith("-int8"):
                model, quant = model[: -len("-int8")], "int8"
            elif model.endswith("-tiny"):
                model, tiny = model[: -len("-tiny")], True
            else:
                stripped = False
        if tiny:
            config = config or DistilBertConfig.tiny()
        if quant != "none":
            config = dataclasses.replace(
                config or DistilBertConfig(), quant=quant
            )
        weight_quant = kwargs.pop("weight_quant", "none") or "none"
        if weight_quant != "none":
            config = dataclasses.replace(
                config or DistilBertConfig(), weight_quant=weight_quant
            )
        return cls(config=config, checkpoint_path=ckpt, **kwargs)

    @staticmethod
    def _check_buckets(
        buckets: Optional[Sequence[int]], max_len: int
    ) -> Optional[Tuple[int, ...]]:
        """Validate ascending sequence-length buckets; ``max_len`` is always
        the (implicit) last bucket so every row has a home."""
        if not buckets:
            return None
        out = sorted(set(int(b) for b in buckets) | {max_len})
        if out[0] < 8:
            raise ValueError(f"length bucket {out[0]} is below the floor of 8")
        if out[-1] > max_len:
            raise ValueError(
                f"length bucket {out[-1]} exceeds max_len={max_len}"
            )
        return tuple(out)

    @staticmethod
    def _round_rows(n: int) -> int:
        """Next power of two (≥16): bounds the number of compiled batch
        shapes per bucket while keeping row padding ≤ 2×."""
        from music_analyst_tpu.utils.shapes import round_pow2

        return round_pow2(n, 16)

    def _pad_batch(self, batch: np.ndarray, lengths: np.ndarray):
        """Pad the row count so the batch splits evenly over the dp axis."""
        if self.mesh is None:
            return batch, lengths, batch.shape[0]
        shards = self.mesh.shape.get("dp", 1)
        n = batch.shape[0]
        padded = -(-n // shards) * shards
        if padded != n:
            batch = np.pad(batch, ((0, padded - n), (0, 0)))
            lengths = np.pad(lengths, (0, padded - n), constant_values=1)
        return batch, lengths, n

    def _record_mesh_collectives(self, rows: int, seq: int) -> None:
        """Analytic per-step collective bytes for the sharded forward.

        Under tensor parallelism every encoder block ends its attention
        and MLP halves with a ``psum`` of the [rows/dp, seq, dim] bf16
        activations over the tp axis (Megatron pattern — 2 all-reduces
        per layer); the dp result gather moves each shard's class/
        confidence rows (~8 B/row) back together.  Pure estimate: no
        device counters exist behind the axon tunnel.
        """
        if self.mesh is None:
            return
        from music_analyst_tpu.profiling.collectives import record_collective

        dp = self.mesh.shape.get("dp", 1)
        tp = self.mesh.shape.get("tp", 1)
        if tp > 1:
            act_bytes = (rows // max(dp, 1)) * seq * self.config.dim * 2
            record_collective(
                "sentiment.tp_allreduce", "psum",
                payload_bytes=act_bytes, n_devices=tp, axis="tp",
                count=2 * self.config.n_layers,
            )
        if dp > 1:
            record_collective(
                "sentiment.result_gather", "all_gather",
                payload_bytes=(rows // dp) * 8, n_devices=dp, axis="dp",
            )

    def _plan_flat(self, token_ids: np.ndarray, lengths: np.ndarray):
        """Host-side plan for one full-width forward: pad for the dp axis
        and cast to wire dtypes.  ``(gather, n, arrays)`` — no device."""
        from music_analyst_tpu.runtime.wire import narrow_lengths

        token_ids, lengths, n = self._pad_batch(token_ids, lengths)
        token_ids = np.asarray(token_ids, dtype=self._wire_dtype)
        lengths = narrow_lengths(lengths, self.max_len)
        return None, n, (token_ids, lengths)

    def _plan_packed(self, token_ids: np.ndarray, lengths: np.ndarray):
        """Host-side plan for packed rows: bin-pack lyrics into shared
        rows, cast the compact wire format.  Row and slot counts round to
        powers of two (shapes stay bounded); the plan carries the
        ``(bin_of, slot_of)`` gather map back to :meth:`collect`."""
        from music_analyst_tpu.runtime.wire import narrow_lengths
        from music_analyst_tpu.utils.shapes import round_pow2

        n = token_ids.shape[0]
        if n == 0:
            return []
        bin_of, slot_of, starts, row_len = pack_segments(lengths, self.max_len)
        n_rows, n_slots = starts.shape
        rows_padded = self._round_rows(n_rows)
        if self.mesh is not None:
            shards = self.mesh.shape.get("dp", 1)
            rows_padded = -(-rows_padded // shards) * shards
        slots_padded = round_pow2(max(n_slots, 1), 4)
        ids = np.zeros((rows_padded, self.max_len), token_ids.dtype)
        st = np.full((rows_padded, slots_padded), self.max_len, np.int64)
        st[:n_rows, :n_slots] = starts
        rl = np.zeros((rows_padded,), np.int64)
        rl[:n_rows] = row_len
        for i in range(n):
            offset = starts[bin_of[i], slot_of[i]]
            ids[bin_of[i], offset : offset + lengths[i]] = token_ids[
                i, : lengths[i]
            ]
        ids = np.asarray(ids, dtype=self._wire_dtype)
        st = narrow_lengths(st, self.max_len)
        rl = narrow_lengths(rl, self.max_len)
        return [((bin_of, slot_of), n, (ids, st, rl))]

    def prepare(self, texts: Sequence[str]):
        """Host phase: tokenize and plan the batch (no device work).

        With ``length_buckets`` set, rows group by token length and each
        group runs at the smallest sufficient sequence length (seq-32 rows
        cost ~1/4 the encoder FLOPs of seq-128 rows) — the SURVEY §7
        "ragged lyrics" lever.  With ``packed=True``, short lyrics instead
        share full-width rows behind a block-diagonal attention mask
        (:func:`pack_segments`) — same FLOP saving, but concentrated into
        fewer, fuller rows.  Row counts round up to powers of two so the
        compiled-shape set stays bounded; original order is restored in
        :meth:`collect`.

        Returns ``(texts, [(gather, n, host_arrays)...])`` — every array
        already padded and cast to its wire dtype, ready for
        :meth:`transfer`.
        """
        token_ids, lengths = self.tokenizer.encode_batch(texts, self.max_len)
        if self.packed:
            return texts, self._plan_packed(token_ids, lengths)
        if self.length_buckets == "auto" and lengths.size:
            # First non-empty batch is the sample: at production batch
            # sizes (4-8k rows) its length distribution is the corpus's.
            # (An empty batch leaves "auto" pending rather than silently
            # resolving to the flat path forever.)
            self.length_buckets = self._check_buckets(
                derive_length_buckets(lengths, self.max_len), self.max_len
            )
        if self.length_buckets == "auto":
            return texts, []
        if self.length_buckets is None:
            return texts, [self._plan_flat(token_ids, lengths)]
        parts = []
        remaining = np.arange(token_ids.shape[0])
        for bucket in self.length_buckets:
            in_bucket = lengths[remaining] <= bucket
            rows = remaining[in_bucket]
            remaining = remaining[~in_bucket]
            if rows.size == 0:
                continue
            padded_rows = self._round_rows(rows.size)
            ids_b = np.zeros((padded_rows, bucket), token_ids.dtype)
            len_b = np.ones((padded_rows,), lengths.dtype)
            ids_b[: rows.size] = token_ids[rows, :bucket]
            len_b[: rows.size] = lengths[rows]
            _, _, arrays = self._plan_flat(ids_b, len_b)
            parts.append((rows, rows.size, arrays))
        return texts, parts

    def transfer(self, prepared):
        """H2D phase: place every planned wire array on device.

        Runs in the pipeline's transfer stage so batch i+1 crosses the
        ~10 MB/s tunnel while batch i computes.  Bytes shipped (and saved
        vs an int32 wire) land in the ``pipeline.h2d_bytes*`` counters.
        """
        from music_analyst_tpu.runtime.wire import count_h2d_bytes

        texts, parts = prepared
        placed = []
        for gather, n, arrays in parts:
            count_h2d_bytes(arrays)
            arrays = tuple(
                jax.device_put(a, self._data_sharding) for a in arrays
            )
            placed.append((gather, n, arrays))
        return texts, placed

    def launch(self, transferred):
        """Dispatch phase: launch the jitted forwards (JAX async dispatch
        — returns handles, never blocks on results)."""
        texts, parts = transferred
        launched = []
        for gather, n, arrays in parts:
            if len(arrays) == 2:
                token_ids, lengths = arrays
                self._record_mesh_collectives(*token_ids.shape)
                classes, confidence = self._forward(
                    self.params, token_ids, lengths
                )
            else:
                ids, st, rl = arrays
                self._record_mesh_collectives(ids.shape[0], self.max_len)
                classes, confidence = self._forward_packed(
                    self.params, ids, st, rl
                )
            launched.append((gather, classes, confidence, n))
        return texts, launched

    def submit(self, texts: Sequence[str]):
        """Tokenize + dispatch without blocking: the staged hooks composed
        for direct submit/collect callers."""
        return self.launch(self.transfer(self.prepare(texts)))

    def collect(self, handle) -> List[str]:
        texts, parts = handle
        # Sentinel init + coverage check: every row must be written by
        # exactly one bucket part, or labels would silently be garbage.
        classes = np.full((len(texts),), -1, np.int64)
        confidence = np.empty((len(texts),), np.float64)
        for rows, part_classes, part_confidence, n in parts:
            if isinstance(rows, tuple):
                # Packed part: device results are [rows, slots]; gather
                # input i's segment via its (bin, slot) coordinates.
                bin_of, slot_of = rows
                classes[:n] = np.asarray(part_classes)[bin_of, slot_of]
                confidence[:n] = np.asarray(part_confidence)[bin_of, slot_of]
                continue
            if rows is None:
                rows = np.arange(len(texts))
            classes[rows] = np.asarray(part_classes)[:n]
            confidence[rows] = np.asarray(part_confidence)[:n]
        uncovered = np.flatnonzero(classes < 0)
        if uncovered.size:
            raise AssertionError(
                f"{uncovered.size} row(s) not covered by any length bucket "
                f"(first: {uncovered[0]})"
            )
        labels: List[str] = []
        for text, cls_id, conf in zip(texts, classes, confidence):
            if not text.strip():
                labels.append("Neutral")  # reference empty-lyric rule
            elif conf < self.neutral_threshold:
                labels.append("Neutral")
            else:
                labels.append(self._CLASS_LABELS[int(cls_id)])
        return labels

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        return self.collect(self.submit(texts))
