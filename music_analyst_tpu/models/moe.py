"""Mixture-of-experts feed-forward with expert-parallel sharding.

No reference analogue (SURVEY.md §2.4 marks EP absent); present because the
framework treats every parallelism axis as first-class.  The expert weight
stacks carry a leading ``E`` axis sharded over the ``ep`` mesh axis
(``parallel/sharding.py``); the hidden axis additionally shards over ``tp``.

Dispatch is *dense* in this round: every expert computes every token and a
top-k-masked router combine zeroes the unused results.  That is exact (same
math as sparse dispatch), keeps shapes static, and shards cleanly; the
sort/scatter token-dropping dispatch is a later optimization, not a
semantics change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoESwiGLU(nn.Module):
    """Top-k routed mixture of SwiGLU experts."""

    n_experts: int
    hidden_dim: int
    top_k: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        features = x.shape[-1]
        E, H = self.n_experts, self.hidden_dim
        k = min(self.top_k, E)
        init = nn.initializers.lecun_normal()
        gate_w = self.param("gate_experts", init, (E, features, H))
        up_w = self.param("up_experts", init, (E, features, H))
        down_w = self.param("down_experts", init, (E, H, features))

        router_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32, name="router"
        )(x)                                                   # [B,S,E]
        top_vals, top_idx = jax.lax.top_k(router_logits, k)
        top_weights = jax.nn.softmax(top_vals, axis=-1)        # [B,S,k]
        # scatter the top-k weights back to a dense [B,S,E] combine matrix
        combine = jnp.sum(
            jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
            * top_weights[..., None],
            axis=-2,
        )

        xc = x.astype(self.dtype)
        gate = jnp.einsum("bsd,edh->besh", xc, gate_w.astype(self.dtype))
        up = jnp.einsum("bsd,edh->besh", xc, up_w.astype(self.dtype))
        expert_out = jnp.einsum(
            "besh,ehd->besd", nn.silu(gate) * up, down_w.astype(self.dtype)
        )                                                      # [B,E,S,D]
        out = jnp.einsum(
            "bse,besd->bsd", combine.astype(self.dtype), expert_out
        )
        return out.astype(x.dtype)

    @staticmethod
    def load_balancing_loss(router_logits: jax.Array, top_idx: jax.Array,
                            n_experts: int) -> jax.Array:
        """Switch-style auxiliary loss (mean prob × mean dispatch per expert)."""
        probs = jax.nn.softmax(router_logits, axis=-1)
        mean_prob = probs.mean(axis=(0, 1))
        dispatch = jax.nn.one_hot(top_idx[..., 0], n_experts).mean(axis=(0, 1))
        return n_experts * jnp.sum(mean_prob * dispatch)
