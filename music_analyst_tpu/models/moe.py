"""Mixture-of-experts feed-forward with expert-parallel sharding.

No reference analogue (SURVEY.md §2.4 marks EP absent); present because the
framework treats every parallelism axis as first-class.  The expert weight
stacks carry a leading ``E`` axis sharded over the ``ep`` mesh axis
(``parallel/sharding.py``); the hidden axis additionally shards over ``tp``.

Dispatch is *sparse* (token-choice top-k with a capacity bound): each
token's top-k expert assignments scatter into a static ``[E, capacity]``
buffer (position = running count within the expert, computed by one
cumsum), the expert SwiGLUs run over the buffer, and results gather back
weighted by the router.  FLOPs are ``k × capacity_factor`` per token
instead of the dense path's ``E×``; shapes stay static so the whole thing
jits and shards.  Assignments beyond an expert's capacity are dropped —
the standard Switch/GShard trade; ``capacity_factor >= n_experts`` is
lossless and reproduces the dense path exactly, which is how the
differential test pins the implementation (``tests/test_moe.py``).

``dispatch="dense"`` keeps the exact all-experts compute as the oracle.

Sharding semantics under an ``ep`` mesh axis: the expert einsums — where
~all FLOPs live — partition over ``E`` (weights carry the sharded axis);
the routing/scatter/gather bookkeeping computes on replicated token
activations (O(T·(k+D)) elementwise work, no matmuls) and XLA slices the
buffer per shard at the einsum boundary.  An explicit all-to-all token
exchange only pays off once tokens themselves are ep-sharded across
hosts — the multi-host regime ``parallel/distributed.py`` owns.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn


def moe_capacity(tokens: int, top_k: int, n_experts: int,
                 capacity_factor: float) -> int:
    """Buffer slots per expert: ``ceil(ceil(T*k/E) * capacity_factor)``.

    The outer ceil matters at decode-scale token counts: truncation would
    silently erase the headroom (ceil(8/4)*1.25 = 2.5 must give 3 slots,
    not 2 — 2 is capacity_factor 1.0 in disguise).
    """
    fair_share = -(-tokens * top_k // n_experts)
    return max(1, math.ceil(fair_share * capacity_factor))


class MoESwiGLU(nn.Module):
    """Top-k routed mixture of SwiGLU experts."""

    n_experts: int
    hidden_dim: int
    top_k: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    # "sparse": capacity-bounded scatter/gather dispatch (production);
    # "dense": every expert computes every token, router mask combines
    # (exact; the differential oracle).
    dispatch: str = "sparse"
    # Buffer slots per expert = ceil(T*k/E) * capacity_factor.  1.25 keeps
    # drops rare under mild router imbalance; >= n_experts is lossless.
    capacity_factor: float = 1.25
    # "int8" routes the expert einsums — where ~all MoE FLOPs live —
    # through the dynamic per-expert int8 matmul
    # (``ops/quant.py:quant_batched_matmul``); the router stays f32 (a
    # [D,E] sliver of the FLOPs, and top-k index flips under quantization
    # would change *routing*, not just precision).  Same contract as the
    # dense layers' quant flag: inference-only, default OFF.
    quant: str = "none"

    def _expert_mm(self, x: jax.Array, w: jax.Array) -> jax.Array:
        """Batched per-expert matmul ``[E,C,K] @ [E,K,N]`` in self.dtype
        or via the int8 MXU path."""
        if self.quant == "int8":
            from music_analyst_tpu.ops.quant import quant_batched_matmul

            return quant_batched_matmul(x, w).astype(self.dtype)
        return jnp.einsum("eck,ekn->ecn", x, w.astype(self.dtype))

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.dispatch not in ("sparse", "dense"):
            raise ValueError(f"unknown MoE dispatch {self.dispatch!r}")
        features = x.shape[-1]
        E, H = self.n_experts, self.hidden_dim
        k = min(self.top_k, E)
        init = nn.initializers.lecun_normal()
        gate_w = self.param("gate_experts", init, (E, features, H))
        up_w = self.param("up_experts", init, (E, features, H))
        down_w = self.param("down_experts", init, (E, H, features))

        router_logits = nn.Dense(
            E, use_bias=False, dtype=jnp.float32, name="router"
        )(x)                                                   # [B,S,E]
        top_vals, top_idx = jax.lax.top_k(router_logits, k)
        top_weights = jax.nn.softmax(top_vals, axis=-1)        # [B,S,k]

        if self.dispatch == "dense":
            return self._dense(
                x, gate_w, up_w, down_w, top_idx, top_weights
            )
        return self._sparse(x, gate_w, up_w, down_w, top_idx, top_weights)

    def _dense(self, x, gate_w, up_w, down_w, top_idx, top_weights):
        E = self.n_experts
        # scatter the top-k weights back to a dense [B,S,E] combine matrix
        combine = jnp.sum(
            jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
            * top_weights[..., None],
            axis=-2,
        )
        if self.quant == "int8":
            # Same batched-matmul layout as the sparse path so both
            # dispatches quantize identically: broadcast the tokens to
            # every expert ([E,T,D] — the dense oracle already pays E×
            # FLOPs, the copy is not the cost driver).
            B, S, D = x.shape
            T = B * S
            xb = jnp.broadcast_to(
                x.reshape(T, D).astype(self.dtype), (E, T, D)
            )
            gate = self._expert_mm(xb, gate_w)
            up = self._expert_mm(xb, up_w)
            out = self._expert_mm(nn.silu(gate) * up, down_w)  # [E,T,D]
            out = jnp.einsum(
                "te,etd->td",
                combine.reshape(T, E).astype(jnp.float32),
                out.astype(jnp.float32),
            ).reshape(B, S, D)
            return out.astype(x.dtype)
        xc = x.astype(self.dtype)
        gate = jnp.einsum("bsd,edh->besh", xc, gate_w.astype(self.dtype))
        up = jnp.einsum("bsd,edh->besh", xc, up_w.astype(self.dtype))
        expert_out = jnp.einsum(
            "besh,ehd->besd", nn.silu(gate) * up, down_w.astype(self.dtype)
        )                                                      # [B,E,S,D]
        out = jnp.einsum(
            "bse,besd->bsd", combine.astype(self.dtype), expert_out
        )
        return out.astype(x.dtype)

    def _sparse(self, x, gate_w, up_w, down_w, top_idx, top_weights):
        B, S, D = x.shape
        E, k = self.n_experts, top_idx.shape[-1]
        T = B * S
        A = T * k  # assignments: token t's choices at flat ids t*k .. t*k+k-1
        capacity = moe_capacity(T, k, E, self.capacity_factor)

        xt = x.reshape(T, D).astype(self.dtype)
        flat_expert = top_idx.reshape(A)
        flat_weight = top_weights.reshape(A)
        flat_token = jnp.arange(A) // k

        # Position of each assignment within its expert: cumulative count
        # of earlier same-expert assignments (one cumsum over the one-hot).
        one_hot_e = jax.nn.one_hot(flat_expert, E, dtype=jnp.int32)  # [A,E]
        pos = jnp.sum(
            (jnp.cumsum(one_hot_e, axis=0) - 1) * one_hot_e, axis=-1
        )                                                            # [A]
        keep = pos < capacity
        # Dropped assignments target row `capacity`, one past the buffer:
        # scatter mode="drop" discards them; gathers clamp but are masked.
        safe_pos = jnp.where(keep, pos, capacity)

        buf = jnp.zeros((E, capacity, D), self.dtype)
        buf = buf.at[flat_expert, safe_pos].set(
            xt[flat_token], mode="drop"
        )

        gate = self._expert_mm(buf, gate_w)
        up = self._expert_mm(buf, up_w)
        out_buf = self._expert_mm(nn.silu(gate) * up, down_w)  # [E,C,D]

        gathered = out_buf[flat_expert, jnp.minimum(safe_pos, capacity - 1)]
        contrib = gathered.astype(jnp.float32) * (
            flat_weight * keep.astype(jnp.float32)
        )[:, None]
        out = (
            jnp.zeros((T, D), jnp.float32)
            .at[flat_token]
            .add(contrib)
            .reshape(B, S, D)
        )
        return out.astype(x.dtype)

    @staticmethod
    def load_balancing_loss(router_logits: jax.Array, top_idx: jax.Array,
                            n_experts: int) -> jax.Array:
        """Switch-style auxiliary loss (mean prob × mean dispatch per expert)."""
        probs = jax.nn.softmax(router_logits, axis=-1)
        mean_prob = probs.mean(axis=(0, 1))
        dispatch = jax.nn.one_hot(top_idx[..., 0], n_experts).mean(axis=(0, 1))
        return n_experts * jnp.sum(mean_prob * dispatch)
