"""The ``--mock`` classifier backend: keyword kernel on device.

Reference behavior being reproduced (``scripts/sentiment_classifier.py:
57-83``): strip the lyric; empty → Neutral; otherwise substring-score the
ten keywords and label by sign.  The scoring itself runs batched on device
(``ops/keyword_sentiment.py``); this wrapper owns batching policy and the
empty-lyric short-circuit.
"""

from __future__ import annotations

from typing import List, Sequence


from music_analyst_tpu.engines.sentiment import ClassifierBackend
from music_analyst_tpu.ops.keyword_sentiment import score_texts
from music_analyst_tpu.utils.labels import score_to_label


class MockKeywordClassifier(ClassifierBackend):
    name = "mock"
    # Reference mock records latency 0.0 for every song
    # (scripts/sentiment_classifier.py:83).
    reports_latency = False

    def __init__(self, window_bytes: int = 4096) -> None:
        self.window_bytes = window_bytes

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        scores = score_texts(texts, length=self.window_bytes)
        # Empty (post-strip) lyrics score 0 → Neutral, identical to the
        # reference's explicit short-circuit (classify(), :60-61).
        return [score_to_label(int(s)) for s in scores]
