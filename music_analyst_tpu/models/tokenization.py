"""Subword tokenizers for the neural sentiment backends.

Replaces nothing in the reference: its LLM path sends raw text to an
Ollama server which tokenizes remotely (``scripts/sentiment_classifier.py:
85-100``); on-device models need explicit tokenizers.

This environment is zero-egress, so pretrained tokenizer assets may be
absent.  Three tiers, best available wins:

* a real WordPiece vocab (``vocab.txt``) or HF tokenizer directory supplied
  via path/env — exact DistilBERT tokenization;
* :class:`HashWordTokenizer` — deterministic hash of whitespace/punct-split
  words into the id space.  Calibration-free: architecture benchmarks and
  sharding tests don't depend on which subword each word maps to;
* :class:`ByteTokenizer` — raw UTF-8 bytes + specials, used by the decoder
  LM family offline.
"""

from __future__ import annotations

import os
import unicodedata
from typing import List, Optional, Sequence, Tuple

import numpy as np

_CJK_RANGES = (
    (0x4E00, 0x9FFF), (0x3400, 0x4DBF), (0x20000, 0x2A6DF),
    (0x2A700, 0x2B73F), (0x2B740, 0x2B81F), (0x2B820, 0x2CEAF),
    (0xF900, 0xFAFF), (0x2F800, 0x2FA1F),
)


def _is_bert_punctuation(ch: str) -> bool:
    """BERT treats the ASCII symbol ranges as punctuation in addition to
    the Unicode P* categories (so ``$``, ``+``, `` ` `` split too)."""
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def bert_basic_tokenize(text: str) -> List[str]:
    """HF ``BertTokenizer``'s BasicTokenizer (``do_lower_case=True``),
    reimplemented exactly.

    Clean control chars (every C* category, like HF's ``_is_control``),
    isolate CJK ideographs, whitespace-split, lowercase + strip accents
    (NFD, drop combining marks), then split punctuation into single-char
    tokens.  The real-weights path depends on byte-exact agreement with
    the checkpoint's tokenizer — ``tests/test_wordpiece_differential.py``
    pins this function against ``transformers.BertTokenizer`` directly.
    """
    chars: List[str] = []
    for ch in text:
        cp = ord(ch)
        cat = unicodedata.category(ch)
        if ch in " \t\n\r" or cat == "Zs":
            chars.append(" ")
        elif cp == 0 or cp == 0xFFFD or cat.startswith("C"):
            continue
        elif any(lo <= cp <= hi for lo, hi in _CJK_RANGES):
            chars.extend((" ", ch, " "))
        else:
            chars.append(ch)
    tokens: List[str] = []
    for token in "".join(chars).split():
        token = token.lower()
        token = unicodedata.normalize("NFD", token)
        token = "".join(
            c for c in token if unicodedata.category(c) != "Mn"
        )
        current: List[str] = []
        for c in token:
            if _is_bert_punctuation(c):
                if current:
                    tokens.append("".join(current))
                    current = []
                tokens.append(c)
            else:
                current.append(c)
        if current:
            tokens.append("".join(current))
    return tokens


class HashWordTokenizer:
    """Deterministic word→id hashing into a fixed vocab space.

    Tokenization spec (deliberately byte-level so the native C++ fast path
    in ``native/ingest.cpp`` is exactly equivalent):

    * ASCII A-Z lowercases; words are runs of ``[a-z0-9']`` bytes;
    * ASCII whitespace separates; any other character — including each
      multi-byte UTF-8 character — is its own single-character token;
    * a word's id is ``reserved + FNV-1a(word bytes) % (vocab - reserved)``.
    """

    def __init__(
        self,
        vocab_size: int = 30522,
        cls_id: int = 101,
        sep_id: int = 102,
        pad_id: int = 0,
        reserved: int = 1000,
    ) -> None:
        if vocab_size < 16:
            raise ValueError("vocab_size too small for special tokens")
        self.vocab_size = vocab_size
        # keep specials + reserved range inside small vocabs
        self.cls_id = min(cls_id, vocab_size - 2)
        self.sep_id = min(sep_id, vocab_size - 1)
        self.pad_id = pad_id
        self.reserved = min(reserved, vocab_size // 2)

    def _hash_id(self, data: bytes) -> int:
        h = 2166136261
        for ch in data:
            h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
        return self.reserved + (h % (self.vocab_size - self.reserved))

    def _token_ids(self, text: str, max_tokens: int) -> List[int]:
        data = text.encode("utf-8", errors="replace")
        ids: List[int] = []
        i, n = 0, len(data)
        word_start = -1
        while i < n and len(ids) < max_tokens:
            b = data[i]
            if 65 <= b <= 90:
                b += 32  # ASCII lowercase
            is_word = (97 <= b <= 122) or (48 <= b <= 57) or b == 0x27
            if is_word:
                if word_start < 0:
                    word_start = i
                i += 1
                continue
            if word_start >= 0:
                ids.append(self._hash_id(data[word_start:i].lower()))
                word_start = -1
                if len(ids) >= max_tokens:
                    break
            if b in (0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C):
                i += 1
                continue
            # single character token (UTF-8 multi-byte steps as one char)
            char_len = 1
            if b >= 0xF0:
                char_len = 4
            elif b >= 0xE0:
                char_len = 3
            elif b >= 0xC0:
                char_len = 2
            ids.append(self._hash_id(data[i : i + char_len]))
            i += char_len
        if word_start >= 0 and len(ids) < max_tokens:
            ids.append(self._hash_id(data[word_start:i].lower()))
        return ids

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        ids = [self.cls_id] + self._token_ids(text, max_len - 2) + [self.sep_id]
        length = len(ids)
        out = np.full(max_len, self.pad_id, dtype=np.int32)
        out[:length] = ids
        return out, length

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths


class NativeHashTokenizer(HashWordTokenizer):
    """C++-accelerated batch encoding with identical output."""

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        from music_analyst_tpu.data import native

        if not native.available():
            return super().encode_batch(texts, max_len)
        return native.hash_tokenize_batch(
            texts,
            max_len,
            vocab_size=self.vocab_size,
            cls_id=self.cls_id,
            sep_id=self.sep_id,
            pad_id=self.pad_id,
            reserved=self.reserved,
        )


class WordPieceTokenizer:
    """Greedy longest-match-first WordPiece over a provided ``vocab.txt``.

    Matches the BERT algorithm: basic whitespace+punctuation split,
    lowercase, then greedy subword segmentation with ``##`` continuations;
    unknown words map to ``[UNK]``.
    """

    SPECIAL_TOKENS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")

    def __init__(self, vocab_path: str, max_word_chars: int = 100) -> None:
        import re

        with open(vocab_path, encoding="utf-8") as fh:
            self.vocab = {line.rstrip("\n"): i for i, line in enumerate(fh)}
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.cls_id = self.vocab["[CLS]"]
        self.sep_id = self.vocab["[SEP]"]
        self.unk_id = self.vocab.get("[UNK]", 100)
        self.max_word_chars = max_word_chars
        self.vocab_size = len(self.vocab)
        # HF passes never_split=all_special_tokens to its basic tokenizer:
        # a literal "[MASK]" in the text stays one token (case-sensitive,
        # anywhere in the string), it is not lowercased or punct-split.
        self._specials = frozenset(
            t for t in self.SPECIAL_TOKENS if t in self.vocab
        )
        self._special_re = (
            re.compile("(" + "|".join(map(re.escape, self._specials)) + ")")
            if self._specials else None
        )

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > self.max_word_chars:
            return [self.unk_id]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        ids: List[int] = [self.cls_id]
        chunks = (
            self._special_re.split(text) if self._special_re else [text]
        )
        for chunk in chunks:
            if len(ids) >= max_len - 1:
                break
            if chunk in self._specials:
                ids.append(self.vocab[chunk])
                continue
            for word in bert_basic_tokenize(chunk):
                ids.extend(self._wordpiece(word))
                if len(ids) >= max_len - 1:
                    break
        ids = ids[: max_len - 1] + [self.sep_id]
        out = np.full(max_len, self.pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out, len(ids)

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths


class ByteTokenizer:
    """UTF-8 bytes + specials: the offline tokenizer for the decoder LM."""

    PAD, BOS, EOS = 256, 257, 258

    def __init__(self, vocab_size: int = 512) -> None:
        assert vocab_size >= 259
        self.vocab_size = vocab_size
        self.pad_id = self.PAD
        self.bos_id = self.BOS
        self.eos_id = self.EOS

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        data = text.encode("utf-8")[: max_len - 1]
        ids = [self.BOS] + list(data)
        out = np.full(max_len, self.PAD, dtype=np.int32)
        out[: len(ids)] = ids
        return out, len(ids)

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.PAD, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizerAdapter:
    """Wrap a local HF tokenizer (e.g. Llama-3 BPE) behind the same
    ``encode``/``encode_batch``/``decode`` surface the offline tokenizers
    expose.  ``local_files_only`` — this environment has zero egress."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self.tok)
        eos = self.tok.eos_token_id
        pad = self.tok.pad_token_id
        self.eos_id = eos if eos is not None else 0
        self.pad_id = pad if pad is not None else self.eos_id
        self.bos_id = self.tok.bos_token_id  # may be None (no-BOS styles)

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        ids = self.tok.encode(text, truncation=True, max_length=max_len)
        out = np.full(max_len, self.pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out, len(ids)

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        # One batched call: fast tokenizers parallelize across texts here;
        # a per-text Python loop forfeits that on every 4k-song batch.
        # Padding happens in numpy so tokenizers without a pad token work.
        ids_list = self.tok(
            list(texts), truncation=True, max_length=max_len
        )["input_ids"]
        batch = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, ids in enumerate(ids_list):
            batch[i, : len(ids)] = ids
            lengths[i] = len(ids)
        return batch, lengths

    def decode(self, ids: Sequence[int]) -> str:
        return self.tok.decode(
            [int(i) for i in ids if int(i) != self.pad_id],
            skip_special_tokens=True,
        )


def resolve_llama_tokenizer(
    vocab_size: int, path: Optional[str] = None
):
    """Best-available decoder tokenizer.

    A local HF tokenizer directory (``$MUSICAAL_LLAMA_TOKENIZER``) gives
    exact Llama-3 BPE for real checkpoints; otherwise the byte tokenizer
    keeps everything runnable offline.
    """
    path = path or os.environ.get("MUSICAAL_LLAMA_TOKENIZER")
    if path and os.path.exists(path):
        return HFTokenizerAdapter(path)
    return ByteTokenizer(vocab_size)


# Codepoints below this bound are classified/normalized by a table the
# Python side builds from unicodedata and hands to the native kernel:
# ASCII + Latin-1 Supplement + Latin Extended-A/B + IPA + combining
# diacriticals — i.e. every Western-language lyric.  Greek and beyond
# (0x370+) fall back to the Python path per row: lowercasing there can be
# context-dependent (final sigma), which a per-char table can't express.
_WP_TABLE_MAX = 0x370


def _wp_char_table():
    """``(classes, repl_blob, offsets)`` for the native WordPiece kernel.

    ``classes[cp]``: 0=drop (C* controls), 1=whitespace, 2=punctuation,
    3=word char.  ``repl`` is the per-char normalization BERT applies
    inside a token — lowercase, NFD, strip combining marks — as UTF-8
    bytes (empty for a bare combining mark, multi-byte where the
    lowercased base keeps a non-ASCII char like ``ø``).  Derived from the
    same unicodedata calls ``bert_basic_tokenize`` makes, so the native
    path can't drift from the Python semantics.
    """
    classes = np.zeros(_WP_TABLE_MAX, np.uint8)
    repls = []
    for cp in range(_WP_TABLE_MAX):
        ch = chr(cp)
        cat = unicodedata.category(ch)
        if ch in " \t\n\r" or cat == "Zs":
            classes[cp] = 1
            repls.append(b"")
        elif cp == 0 or cat.startswith("C"):
            classes[cp] = 0
            repls.append(b"")
        elif _is_bert_punctuation(ch):
            classes[cp] = 2
            repls.append(ch.encode("utf-8"))
        else:
            classes[cp] = 3
            norm = "".join(
                c for c in unicodedata.normalize("NFD", ch.lower())
                if unicodedata.category(c) != "Mn"
            )
            repls.append(norm.encode("utf-8"))
    offsets = np.zeros(_WP_TABLE_MAX + 1, np.int32)
    np.cumsum([len(r) for r in repls], out=offsets[1:])
    return classes, b"".join(repls), offsets


class NativeWordPieceTokenizer(WordPieceTokenizer):
    """C++-accelerated batch WordPiece with identical output.

    Latin-script rows (every Western-language lyric, accents included)
    encode in the threaded native kernel
    (``native/ingest.cpp:man_wp_encode_batch``) driven by the
    :func:`_wp_char_table` classification; rows the kernel flags
    (codepoints ≥ U+0370 or invalid UTF-8) re-encode through the Python
    path, which owns the full-Unicode BasicTokenizer semantics.  Python
    WordPiece runs ~10x slower than the DistilBERT device forward, so
    without this the real-weights path is tokenizer-bound.
    """

    def __init__(self, vocab_path: str, max_word_chars: int = 100) -> None:
        super().__init__(vocab_path, max_word_chars)
        from music_analyst_tpu.data import native

        self._native = native
        self._handle = (
            native.wp_create(vocab_path, _wp_char_table(), max_word_chars)
            if native.available() else None
        )

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        if self._handle is None:
            return super().encode_batch(texts, max_len)
        batch, lengths, handled = self._native.wp_encode_batch(
            self._handle, texts, max_len
        )
        for i in np.flatnonzero(handled == 0):
            row, n = self.encode(texts[i], max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths

    def __del__(self):
        try:
            handle = getattr(self, "_handle", None)
            if handle:
                self._native.wp_destroy(handle)
        except Exception:
            # Interpreter teardown may have cleared module globals the
            # destroy path needs; leaking at exit beats a stderr
            # "Exception ignored" traceback in every process.
            pass


def resolve_bert_tokenizer(
    vocab_path: Optional[str] = None, vocab_size: int = 30522
):
    """Best-available encoder tokenizer (WordPiece if a vocab is supplied)."""
    path = vocab_path or os.environ.get("MUSICAAL_BERT_VOCAB")
    if path and os.path.exists(path):
        return NativeWordPieceTokenizer(path)
    return NativeHashTokenizer(vocab_size=vocab_size)
