"""Subword tokenizers for the neural sentiment backends.

This environment is zero-egress, so pretrained tokenizer assets may be
absent.  Three tiers, best available wins:

* a real WordPiece vocab (``vocab.txt``) or HF tokenizer directory supplied
  via path/env — exact DistilBERT tokenization;
* :class:`HashWordTokenizer` — deterministic hash of whitespace/punct-split
  words into the id space.  Calibration-free: architecture benchmarks and
  sharding tests don't depend on which subword each word maps to;
* :class:`ByteTokenizer` — raw UTF-8 bytes + specials, used by the decoder
  LM family offline.
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Sequence, Tuple

import numpy as np

_WORD_RE = re.compile(r"[a-z0-9']+|[^\sa-z0-9']", re.IGNORECASE)


class HashWordTokenizer:
    """Deterministic word→id hashing into a fixed vocab space."""

    def __init__(
        self,
        vocab_size: int = 30522,
        cls_id: int = 101,
        sep_id: int = 102,
        pad_id: int = 0,
        reserved: int = 1000,
    ) -> None:
        if vocab_size < 16:
            raise ValueError("vocab_size too small for special tokens")
        self.vocab_size = vocab_size
        # keep specials + reserved range inside small vocabs
        self.cls_id = min(cls_id, vocab_size - 2)
        self.sep_id = min(sep_id, vocab_size - 1)
        self.pad_id = pad_id
        self.reserved = min(reserved, vocab_size // 2)

    def _word_id(self, word: str) -> int:
        h = 2166136261
        for ch in word.encode("utf-8"):
            h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
        return self.reserved + (h % (self.vocab_size - self.reserved))

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        words = _WORD_RE.findall(text.lower())[: max_len - 2]
        ids = [self.cls_id] + [self._word_id(w) for w in words] + [self.sep_id]
        length = len(ids)
        out = np.full(max_len, self.pad_id, dtype=np.int32)
        out[:length] = ids
        return out, length

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths


class WordPieceTokenizer:
    """Greedy longest-match-first WordPiece over a provided ``vocab.txt``.

    Matches the BERT algorithm: basic whitespace+punctuation split,
    lowercase, then greedy subword segmentation with ``##`` continuations;
    unknown words map to ``[UNK]``.
    """

    def __init__(self, vocab_path: str, max_word_chars: int = 100) -> None:
        with open(vocab_path, encoding="utf-8") as fh:
            self.vocab = {line.rstrip("\n"): i for i, line in enumerate(fh)}
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.cls_id = self.vocab["[CLS]"]
        self.sep_id = self.vocab["[SEP]"]
        self.unk_id = self.vocab.get("[UNK]", 100)
        self.max_word_chars = max_word_chars
        self.vocab_size = len(self.vocab)

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > self.max_word_chars:
            return [self.unk_id]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        ids: List[int] = [self.cls_id]
        for word in _WORD_RE.findall(text.lower()):
            ids.extend(self._wordpiece(word))
            if len(ids) >= max_len - 1:
                break
        ids = ids[: max_len - 1] + [self.sep_id]
        out = np.full(max_len, self.pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out, len(ids)

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths


class ByteTokenizer:
    """UTF-8 bytes + specials: the offline tokenizer for the decoder LM."""

    PAD, BOS, EOS = 256, 257, 258

    def __init__(self, vocab_size: int = 512) -> None:
        assert vocab_size >= 259
        self.vocab_size = vocab_size
        self.pad_id = self.PAD

    def encode(self, text: str, max_len: int) -> Tuple[np.ndarray, int]:
        data = text.encode("utf-8")[: max_len - 1]
        ids = [self.BOS] + list(data)
        out = np.full(max_len, self.PAD, dtype=np.int32)
        out[: len(ids)] = ids
        return out, len(ids)

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        batch = np.full((len(texts), max_len), self.PAD, dtype=np.int32)
        lengths = np.zeros(len(texts), dtype=np.int32)
        for i, text in enumerate(texts):
            row, n = self.encode(text, max_len)
            batch[i] = row
            lengths[i] = n
        return batch, lengths

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def resolve_bert_tokenizer(
    vocab_path: Optional[str] = None, vocab_size: int = 30522
):
    """Best-available encoder tokenizer (WordPiece if a vocab is supplied)."""
    path = vocab_path or os.environ.get("MUSICAAL_BERT_VOCAB")
    if path and os.path.exists(path):
        return WordPieceTokenizer(path)
    return HashWordTokenizer(vocab_size=vocab_size)
