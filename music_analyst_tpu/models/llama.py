"""Llama-3-style decoder LM with tensor-parallel sharding and KV cache.

The reference's "large model" path is a prompt to a remote Ollama server
(``scripts/sentiment_classifier.py:32-36,85-100``).  Here the LM is a
first-class on-device family: pre-norm GQA decoder blocks (RMSNorm, RoPE,
SwiGLU), weights laid out for ``tp`` sharding (``parallel/sharding.py``),
and an explicit KV cache whose head axis shards with the attention heads.

Zero-shot sentiment reuses the reference's exact prompt (PROMPT_TEMPLATE,
lyrics truncated to 4,000 chars) but replaces free-text generation +
normalization with *constrained label scoring*: one shared prompt prefill,
then teacher-forced log-likelihood of each candidate label continuation —
three tiny decode passes instead of an unbounded generation loop, which is
both deterministic and TPU-shaped (static shapes, no dynamic stopping).
A ``generate`` + ``normalise_label`` path (the reference's semantics,
empty-output crash fixed) is kept for API parity.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from music_analyst_tpu.engines.sentiment import ClassifierBackend
from music_analyst_tpu.models.layers import (
    KVCache,
    MultiHeadAttention,
    RMSNorm,
    SwiGLU,
    causal_mask,
    padding_mask,
)
from music_analyst_tpu.models.tokenization import (
    ByteTokenizer,
    resolve_llama_tokenizer,
)
from music_analyst_tpu.utils.labels import SUPPORTED_LABELS, normalise_label

# Reference prompt, scripts/sentiment_classifier.py:32-36 (behavioral
# contract: same instruction, lyrics truncated to 4,000 characters).
PROMPT_TEMPLATE = (
    "You are an expert music analyst. Classify the overall sentiment of the "
    "following song lyrics as one of the following labels: Positive, "
    "Neutral, or Negative. Respond using only the label name with no "
    "explanations.\n\nLyrics:\n{lyrics}\n"
)
LYRICS_TRUNCATION = 4000


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14_336
    rope_theta: float = 500_000.0
    max_seq_len: int = 8192
    dtype: str = "bfloat16"
    # > 0 replaces the dense SwiGLU with a routed mixture of experts whose
    # expert axis shards over the ``ep`` mesh axis (models/moe.py).
    n_experts: int = 0
    moe_top_k: int = 2
    # "sparse" = capacity-bounded token-choice dispatch (k*cf FLOPs/token);
    # "dense" = all-experts oracle (E× FLOPs).  See models/moe.py.
    moe_dispatch: str = "sparse"
    moe_capacity_factor: float = 1.25
    # "flash" uses the Pallas blocked-attention kernel on the no-cache
    # (prefill/training) path; seq len must divide its block size.
    attn_impl: str = "dense"
    # "int8" routes attention/MLP projections through the dynamic int8
    # matmul (ops/quant.py) — inference-only; see DistilBertConfig.quant.
    quant: str = "none"
    # "int8"/"int4" stores projection + lm_head kernels weight-quantized
    # (QuantizedParam leaves; ops/quant.py): the bf16 tree never exists,
    # which is what lets the 8B config fit one 16 GB chip.  Mutually
    # exclusive with the dynamic `quant` path (it subsumes the matmul).
    weight_quant: str = "none"

    def __post_init__(self):
        if self.weight_quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"weight_quant must be none/int8/int4, got "
                f"{self.weight_quant!r}"
            )
        if self.weight_quant != "none" and self.quant != "none":
            raise ValueError(
                "weight_quant and dynamic quant are mutually exclusive — "
                "the stored-weight path already runs the int8 MXU matmul"
            )
        if self.weight_quant != "none" and self.n_experts > 0:
            raise ValueError(
                "weight_quant does not cover the MoE expert stacks yet; "
                "use the dynamic quant='int8' path for MoE configs"
            )

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Byte-vocab smoke config: same topology, laptop-sized."""
        return cls(
            vocab_size=512, dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
            hidden_dim=256, rope_theta=10_000.0, max_seq_len=2048,
        )


PRESETS = {
    "llama3": LlamaConfig.llama3_8b,
    "llama3-8b": LlamaConfig.llama3_8b,
    "llama3-tiny": LlamaConfig.tiny,
    "llama-tiny": LlamaConfig.tiny,
}


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, mask, positions, cache: Optional[KVCache],
                 lengths: Optional[jax.Array] = None,
                 segment_ids: Optional[jax.Array] = None):
        cfg = self.config
        if segment_ids is not None and (
            cache is not None or cfg.attn_impl != "flash"
        ):
            # Refuse rather than silently attend across documents: the
            # dense impl expresses packing as `causal & same-segment` in
            # the mask array (see tests/test_packed_decoder.py), and the
            # decode/cache path has no packed-document support.
            raise ValueError(
                "segment_ids is consumed by the flash prefill path only; "
                "fold the segment mask into `mask` for the dense impl"
            )
        dtype = jnp.dtype(cfg.dtype)
        attn = MultiHeadAttention(
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.dim // cfg.n_heads,
            use_rope=True,
            rope_theta=cfg.rope_theta,
            max_positions=cfg.max_seq_len,
            dtype=dtype,
            attn_impl=cfg.attn_impl,
            flash_causal=True,
            quant=cfg.quant,
            weight_quant=cfg.weight_quant,
            name="attention",
        )
        h = RMSNorm(name="attention_norm")(x)
        if cache is not None:
            attn_out, new_cache = attn(
                h, mask=mask, positions=positions, cache=cache
            )
        else:
            # Flash path: masking is fully described by flash_causal=True +
            # lengths (+ optional packed-document segment_ids), so the
            # (causal & padding) mask array stays out.  Dense callers fold
            # segment masking into the mask array themselves.
            attn_out = attn(
                h,
                mask=None if cfg.attn_impl == "flash" else mask,
                positions=positions,
                lengths=lengths,
                segment_ids=(segment_ids if cfg.attn_impl == "flash"
                             else None),
            )
            new_cache = None
        x = x + attn_out
        h = RMSNorm(name="ffn_norm")(x)
        if cfg.n_experts > 0:
            from music_analyst_tpu.models.moe import MoESwiGLU

            # quant composes: the expert einsums (the bulk of MoE FLOPs)
            # run the per-expert int8 batched matmul alongside the
            # attention projections' int8 path, so an "int8" MoE model is
            # quantized where the FLOPs actually are.
            ffn = MoESwiGLU(
                cfg.n_experts, cfg.hidden_dim, top_k=cfg.moe_top_k,
                dtype=dtype, dispatch=cfg.moe_dispatch,
                capacity_factor=cfg.moe_capacity_factor,
                quant=cfg.quant,
                name="feed_forward_moe",
            )
        else:
            ffn = SwiGLU(cfg.hidden_dim, dtype=dtype, quant=cfg.quant,
                         weight_quant=cfg.weight_quant, name="feed_forward")
        x = x + ffn(h)
        return x, new_cache


class LlamaModel(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        token_ids: jax.Array,                      # [B, S]
        positions: jax.Array,                      # [B, S]
        mask: jax.Array,                           # broadcastable [B,H,S,KV]
        caches: Optional[List[KVCache]] = None,
        lengths: Optional[jax.Array] = None,       # [B] — flash path masks
        last_position: Optional[jax.Array] = None,  # [B] — see below
        segment_ids: Optional[jax.Array] = None,   # [B, S] — packed docs
    ):
        # CONTRACT: with cfg.attn_impl == "flash" (and no caches), the
        # `mask` argument is NOT applied — attention is causal + key-
        # padding-by-`lengths` + optional same-segment (packed documents,
        # ``segment_ids``; pair with per-segment-restarted ``positions``).
        # Callers needing any other mask (sliding window, prefix-LM,
        # cross-attention) must use the dense impl — where `mask` is
        # arbitrary, so packed-causal is expressed there as
        # ``causal & same-segment`` in the array; MultiHeadAttention
        # raises if a mask array reaches the flash branch directly.
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=dtype,
                     name="tok_embeddings")(token_ids)
        new_caches: List[KVCache] = []
        for i in range(cfg.n_layers):
            cache_i = caches[i] if caches is not None else None
            x, new_cache = LlamaBlock(cfg, name=f"layer_{i}")(
                x, mask, positions, cache_i, lengths,
                segment_ids=segment_ids,
            )
            if new_cache is not None:
                new_caches.append(new_cache)
        x = RMSNorm(name="norm")(x)
        if last_position is not None:
            # Gather ONE position per row BEFORE the vocab projection:
            # prefill callers only consume the last prompt logits, and a
            # materialized [B, S, vocab] float32 tensor is the largest
            # array in the whole model (e.g. 33 GB at B=256, S=256,
            # V=128k — past a v5e's HBM on its own).  Returns [B, 1, V].
            x = jnp.take_along_axis(
                x, last_position[:, None, None].astype(jnp.int32), axis=1
            )
        if cfg.weight_quant != "none":
            from music_analyst_tpu.models.layers import WqDenseGeneral

            logits = WqDenseGeneral(
                features=cfg.vocab_size, axis=-1, use_bias=False,
                dtype=jnp.float32, name="lm_head",
            )(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32, name="lm_head")(x)
        return logits, (new_caches if caches is not None else None)


def init_caches(
    cfg: LlamaConfig, batch: int, max_len: int, dtype=jnp.bfloat16
) -> List[KVCache]:
    head_dim = cfg.dim // cfg.n_heads
    return [
        KVCache.zeros(batch, max_len, cfg.n_kv_heads, head_dim, dtype)
        for _ in range(cfg.n_layers)
    ]


def load_torch_state_dict(path: str, mmap: bool = False) -> dict:
    """Merge a ``pytorch_model.bin``-style file or a directory of shards
    (``pytorch_model*.bin`` / ``*.pt``) into one raw state dict.

    Shared by the Flax param mapper below and the validation harness's
    transformers oracle (``engines/validate.py``), so both sides of a
    label-agreement report read the checkpoint identically.

    ``mmap=True`` (the streaming quantize-on-load path) keeps tensor
    storage memory-mapped: pages materialize per-tensor as the per-unit
    iterator touches them, so peak host memory stays O(one layer) instead
    of O(checkpoint).  Falls back to an eager load for formats torch
    cannot mmap (legacy non-zip archives).
    """
    import torch

    if os.path.isdir(path):
        names = sorted(os.listdir(path))
        # HF Trainer dirs also hold training_args.bin / optimizer.pt etc.;
        # prefer the canonical weight-shard names when present.
        shards = [n for n in names
                  if n.startswith("pytorch_model") and n.endswith(".bin")]
        if not shards:
            shards = [n for n in names
                      if n.endswith((".bin", ".pt"))
                      and n not in ("training_args.bin", "optimizer.pt",
                                    "scheduler.pt", "rng_state.pth")]
        shards = [os.path.join(path, n) for n in shards]
        if not shards:
            raise FileNotFoundError(f"no *.bin/*.pt weight shards under {path}")
    else:
        shards = [path]
    sd = {}
    for shard in shards:
        try:
            if mmap:
                try:
                    loaded = torch.load(shard, map_location="cpu",
                                        weights_only=True, mmap=True)
                except (RuntimeError, ValueError):
                    loaded = torch.load(shard, map_location="cpu",
                                        weights_only=True)
            else:
                loaded = torch.load(shard, map_location="cpu",
                                    weights_only=True)
        except Exception as exc:
            # Never skip silently: a truncated weight shard skipped here
            # would surface as a confusing missing-key error (or worse,
            # a silent tied-embedding fallback) far from the cause.
            raise RuntimeError(f"failed to load shard {shard}") from exc
        if isinstance(loaded, dict):
            sd.update(loaded)
    if not sd:
        raise ValueError(
            f"no tensors found in {path} — not a torch state_dict?"
        )
    return sd


def iter_hf_param_units(params, path: str, mmap: bool = False):
    """Yield an HF ``LlamaForCausalLM`` checkpoint as per-unit leaf lists.

    The single definition of the torch→Flax mapping: torch Linear kernels
    ``[out, in]`` transpose to ``[in, out]``; attention projections reshape
    to ``[dim, heads, head_dim]``.  The RoPE convention needs no weight
    permutation: HF's ``rotate_half`` splits the head dim into contiguous
    halves, exactly as ``layers.apply_rope`` does.

    Yields ``(unit_name, [(tree_path, np.ndarray), …])`` one decoder layer
    (or embeddings / final norm / lm_head) at a time — the granularity the
    streaming quantize-on-load pipeline (``engines/checkpoint.py``)
    overlaps; with ``mmap=True`` only each unit's tensors are ever paged
    in.  ``params`` provides shapes only — ``ShapeDtypeStruct`` trees work.
    """
    import torch

    sd = load_torch_state_dict(path, mmap=mmap)
    # Tolerate both bare-model ("model.layers...") and prefixed keys.
    sd = { (k[len("model."):] if k.startswith("model.") else k): v
           for k, v in sd.items() }

    def t(name):
        return np.asarray(sd[name].to(torch.float32).numpy())

    dim = params["tok_embeddings"]["embedding"].shape[1]
    embed = t("embed_tokens.weight")
    want = tuple(params["tok_embeddings"]["embedding"].shape)
    if embed.shape != want:
        raise ValueError(
            f"checkpoint embed_tokens is {embed.shape} but the model config "
            f"expects {want} — config (vocab_size/dim) doesn't match the "
            "checkpoint"
        )
    yield "tok_embeddings", [("tok_embeddings/embedding", embed)]
    n_layers = sum(1 for k in params if k.startswith("layer_"))
    for i in range(n_layers):
        hf = f"layers.{i}"
        attn = params[f"layer_{i}"]["attention"]
        n_heads = attn["q_proj"]["kernel"].shape[1]
        n_kv = attn["k_proj"]["kernel"].shape[1]
        head_dim = attn["q_proj"]["kernel"].shape[2]
        pre = f"layer_{i}"
        leaves = [
            (f"{pre}/attention/q_proj/kernel",
             t(f"{hf}.self_attn.q_proj.weight").T.reshape(
                 dim, n_heads, head_dim)),
            (f"{pre}/attention/k_proj/kernel",
             t(f"{hf}.self_attn.k_proj.weight").T.reshape(
                 dim, n_kv, head_dim)),
            (f"{pre}/attention/v_proj/kernel",
             t(f"{hf}.self_attn.v_proj.weight").T.reshape(
                 dim, n_kv, head_dim)),
            (f"{pre}/attention/o_proj/kernel",
             t(f"{hf}.self_attn.o_proj.weight").T.reshape(
                 n_heads, head_dim, dim)),
            (f"{pre}/attention_norm/scale", t(f"{hf}.input_layernorm.weight")),
            (f"{pre}/ffn_norm/scale",
             t(f"{hf}.post_attention_layernorm.weight")),
            (f"{pre}/feed_forward/gate_proj/kernel",
             t(f"{hf}.mlp.gate_proj.weight").T),
            (f"{pre}/feed_forward/up_proj/kernel",
             t(f"{hf}.mlp.up_proj.weight").T),
            (f"{pre}/feed_forward/down_proj/kernel",
             t(f"{hf}.mlp.down_proj.weight").T),
        ]
        yield pre, leaves
    yield "norm", [("norm/scale", t("norm.weight"))]
    if "lm_head.weight" in sd:
        lm = t("lm_head.weight").T
    else:  # tied embeddings (Llama-3.2 style)
        lm = t("embed_tokens.weight").T
    yield "lm_head", [("lm_head/kernel", lm)]


def _set_tree_path(tree, path: str, leaf):
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = leaf


def load_hf_torch_checkpoint(params, path: str):
    """Map an HF ``LlamaForCausalLM`` torch state_dict onto the Flax params.

    Eager wrapper over :func:`iter_hf_param_units` (one mapping
    definition; the streaming quantized loader consumes the iterator
    directly).  Replaces nothing in the reference — its large-model path
    is a remote Ollama server (``scripts/sentiment_classifier.py:85-100``);
    here the weights become first-class on-device arrays.
    """
    new = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    for _, leaves in iter_hf_param_units(new, path):
        for tree_path, leaf in leaves:
            _set_tree_path(new, tree_path, leaf)
    return new


def _wq_group_size() -> int:
    """One group-size definition per family so the cache key, the loader,
    and the random-init quantizer can never disagree."""
    from music_analyst_tpu.ops.quant import WQ_DEFAULT_GROUP

    return WQ_DEFAULT_GROUP


class LlamaZeroShotClassifier(ClassifierBackend):
    """Constrained-label zero-shot sentiment over the decoder LM."""

    name = "llama"

    def __init__(
        self,
        config: Optional[LlamaConfig] = None,
        checkpoint_path: Optional[str] = None,
        max_prompt_len: int = 1024,
        mesh=None,
        seed: int = 0,
        decode_mode: str = "score",
        wq_cache_dir: Optional[str] = None,
        continuous_slots: Optional[int] = None,
    ) -> None:
        if decode_mode not in ("score", "generate"):
            raise ValueError(
                f"decode_mode must be 'score' or 'generate', got "
                f"{decode_mode!r}"
            )
        self.decode_mode = decode_mode
        # > 0 routes classify_batch_by_generation / generate_batch through
        # the continuous slot runtime (ops/kv_slots.py) at that slot count;
        # None/0 keeps the static scan path.  Env fallback so CLI runs can
        # opt in without new plumbing at every call site.
        if continuous_slots is None:
            env = os.environ.get("MUSICAAL_CONTINUOUS_SLOTS", "").strip()
            if env:
                try:
                    continuous_slots = int(env)
                except ValueError:
                    raise ValueError(
                        f"MUSICAAL_CONTINUOUS_SLOTS must be an integer, "
                        f"got {env!r}"
                    ) from None
        self.continuous_slots = int(continuous_slots or 0)
        self._slot_schedulers: dict = {}
        self.config = config or LlamaConfig.tiny()
        self.max_prompt_len = max_prompt_len
        self.tokenizer = resolve_llama_tokenizer(self.config.vocab_size)
        # Ids above vocab_size would be silently clamped by nn.Embed's
        # gather, producing garbage labels with no diagnostic.  With real
        # weights that's fatal; on random-weight smoke runs (labels are
        # garbage regardless) a warning keeps e.g. --model llama3-tiny
        # usable while MUSICAAL_LLAMA_TOKENIZER points at a full BPE dir.
        if self.tokenizer.vocab_size > self.config.vocab_size:
            message = (
                f"tokenizer vocab ({self.tokenizer.vocab_size}) exceeds "
                f"model vocab ({self.config.vocab_size})"
            )
            if checkpoint_path:
                raise ValueError(message)
            import warnings

            warnings.warn(message + "; out-of-range ids will clamp",
                          stacklevel=2)
        self.model = LlamaModel(self.config)
        dummy_ids = jnp.zeros((1, 8), jnp.int32)
        dummy_pos = jnp.zeros((1, 8), jnp.int32)
        dummy_mask = causal_mask(8, 8, 0)
        wq = self.config.weight_quant
        self.pretrained = False
        if checkpoint_path and wq != "none":
            # Streaming quantize-on-load: the float tree is never
            # materialized — shapes come from eval_shape, checkpoint
            # tensors stream through quantize→H2D one layer at a time,
            # and a warm wq-cache hit skips torch entirely.
            from music_analyst_tpu.engines import wq_cache
            from music_analyst_tpu.engines.checkpoint import (
                load_quantized_params,
            )

            params_shape = jax.eval_shape(
                self.model.init, jax.random.key(seed), dummy_ids,
                dummy_pos, dummy_mask,
            )["params"]
            cache_dir = wq_cache.resolve_cache_dir(wq_cache_dir)
            cache_key = (
                wq_cache.wq_key(checkpoint_path, "llama", wq,
                                _wq_group_size())
                if cache_dir else None
            )
            self.params = load_quantized_params(
                params_shape,
                lambda: iter_hf_param_units(
                    params_shape, checkpoint_path, mmap=True
                ),
                wq,
                group_size=_wq_group_size(),
                mesh=mesh,
                cache_dir=cache_dir,
                cache_key=cache_key,
            )
            self.pretrained = True
        else:
            self.params = self.model.init(
                jax.random.key(seed), dummy_ids, dummy_pos, dummy_mask
            )["params"]
            if checkpoint_path:
                self.params = load_hf_torch_checkpoint(
                    self.params, checkpoint_path
                )
                self.pretrained = True
            if wq != "none":
                # Random-init WQ model (smoke/A-B runs): quantize the
                # just-initialized tree in place so the forward exercises
                # the exact stored-weight path a checkpoint load produces.
                from music_analyst_tpu.ops.quant import quantize_tree

                self.params = quantize_tree(
                    self.params, wq, _wq_group_size()
                )
        if self.pretrained and isinstance(self.tokenizer, ByteTokenizer):
            import warnings

            warnings.warn(
                "real checkpoint loaded but no matching tokenizer found "
                "— byte-level ids won't line up with the checkpoint's "
                "BPE vocabulary; set MUSICAAL_LLAMA_TOKENIZER to the "
                "checkpoint's tokenizer directory for meaningful labels",
                stacklevel=2,
            )
        self.mesh = mesh
        if mesh is not None:
            from music_analyst_tpu.parallel.sharding import shard_params

            self.params = shard_params(self.params, mesh)

        # Label continuations are scored teacher-forced after a shared
        # prompt prefill.  All three labels are padded to one fixed length
        # so a single jitted function scores them as a batch dimension.
        bos_id = getattr(self.tokenizer, "bos_id", None)
        label_rows, label_lens = [], []
        for label in SUPPORTED_LABELS:
            row, n = self.tokenizer.encode(label, 16)
            # Drop the leading BOS only if this tokenizer actually adds one
            # (HF tokenizers with add_bos_token=False don't).
            skip = 1 if (n > 0 and bos_id is not None
                         and row[0] == bos_id) else 0
            label_rows.append(row[skip:skip + 8])  # fixed len 8
            label_lens.append(min(n - skip, 8))
        self._label_ids = np.stack(label_rows)
        self._label_lens = np.array(label_lens, dtype=np.int32)

        @jax.jit
        def _score_labels(params, prompt_ids, prompt_lens, label_ids,
                          label_lens):
            """Log-likelihood of each label continuation per batch row.

            prompt_ids [B, S]; label_ids [3, L].  Returns [B, 3].
            """
            B, S = prompt_ids.shape
            n_labels, L = label_ids.shape
            # prompt_lens may arrive int16 (wire narrowing) — widen once
            # on device before the arithmetic/broadcast uses below.
            prompt_lens = prompt_lens.astype(jnp.int32)
            positions = jnp.arange(S)[None, :].repeat(B, 0)
            # kv length is S+L (the cache buffer); the label slots are
            # causally unreachable during prefill and masked out anyway.
            mask = causal_mask(S, S + L, 0) & jnp.pad(
                padding_mask(prompt_lens, S),
                ((0, 0), (0, 0), (0, 0), (0, L)),
            )
            caches = init_caches(self.config, B, S + L)
            # last_position: only the final prompt logits are consumed, so
            # the [B,S,V] prefill logits are never materialized.
            logits, caches = self.model.apply(
                {"params": params}, prompt_ids, positions, mask, caches,
                last_position=prompt_lens - 1,
            )
            # Force every cache to report the true prompt length so label
            # positions line up even though the buffer was written at 0..S.
            caches = [
                KVCache(c.keys, c.values, jnp.asarray(S, jnp.int32))
                for c in caches
            ]
            last_logits = logits[:, 0]  # [B, V]

            def score_one(label_row, label_len):
                lab = jnp.broadcast_to(label_row[None, :], (B, L))
                pos = prompt_lens[:, None] + jnp.arange(L)[None, :]
                # decode attends to the full prompt (masked by its length)
                # plus the causal prefix of the label tokens
                kv_len = S + L
                kv_pos = jnp.arange(kv_len)[None, None, None, :]
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                label_part = (kv_pos >= S) & (
                    kv_pos - S <= jnp.arange(L)[None, None, :, None]
                )
                mask2 = prompt_part | label_part
                logits2, _ = self.model.apply(
                    {"params": params}, lab, pos, mask2, caches
                )
                # token 0 scored from the prompt's last logits; tokens i>0
                # from the label forward pass
                logp_all = jax.nn.log_softmax(logits2, axis=-1)
                first_lp = jnp.take_along_axis(
                    jax.nn.log_softmax(last_logits, axis=-1),
                    lab[:, :1], axis=1,
                )[:, 0]
                rest_lp = jnp.take_along_axis(
                    logp_all[:, :-1], lab[:, 1:, None], axis=2
                )[:, :, 0]
                idx = jnp.arange(L - 1)[None, :]
                rest_lp = jnp.where(idx < label_len - 1, rest_lp, 0.0)
                # Length-normalize: summed log-probs otherwise favor the
                # shortest label ("Neutral" is one byte shorter than the
                # other two under the byte tokenizer).
                total = first_lp + rest_lp.sum(axis=1)
                return total / jnp.maximum(label_len.astype(jnp.float32), 1.0)

            scores = jax.vmap(score_one, in_axes=(0, 0), out_axes=1)(
                label_ids, label_lens
            )
            return scores  # [B, 3]

        self._score_labels = _score_labels

        @jax.jit
        def _decode_step(params, token, position, caches):
            B = token.shape[0]
            kv_len = caches[0].keys.shape[1]
            kv_pos = jnp.arange(kv_len)[None, None, None, :]
            mask = kv_pos <= position[:, None, None, None]
            logits, caches = self.model.apply(
                {"params": params}, token, position[:, None], mask, caches
            )
            return jnp.argmax(logits[:, -1], axis=-1), caches

        self._decode_step = _decode_step

        @partial(jax.jit, static_argnames=("max_new_tokens", "early_exit"))
        def _generate_scan(params, prompt_ids, prompt_lens, max_new_tokens,
                           early_exit=True):
            """Batched greedy decode as ONE compiled program.

            The reference's generation is a remote server call per song
            (``scripts/sentiment_classifier.py:94``); a naive on-device port
            would still pay one host→device round-trip per token.  Here
            prefill + every decode step run inside a single jit: the token
            loop is a ``lax.scan`` over the KV cache (static trip count,
            EOS handled by masking — XLA-shaped control flow, SURVEY.md
            §2.4 design notes).  With ``early_exit`` the scan is cut into
            fixed-size segments under a ``lax.while_loop`` whose predicate
            stops once every row has emitted EOS: the all-done tail of a
            short batch is skipped instead of decoded, and because the
            token buffer is pre-filled with EOS (exactly what the skipped
            steps would have emitted) the outputs are identical to the
            full scan.
            """
            B, S = prompt_ids.shape
            positions = jnp.arange(S)[None, :].repeat(B, 0)
            total = S + max_new_tokens
            mask = causal_mask(S, total, 0) & jnp.pad(
                padding_mask(prompt_lens, S),
                ((0, 0), (0, 0), (0, 0), (0, max_new_tokens)),
            )
            caches = init_caches(self.config, B, total)
            logits, caches = self.model.apply(
                {"params": params}, prompt_ids, positions, mask, caches,
                last_position=prompt_lens - 1,
            )
            caches = [
                KVCache(c.keys, c.values, jnp.asarray(S, jnp.int32))
                for c in caches
            ]
            first = jnp.argmax(logits[:, 0], axis=-1)  # [B]
            eos = jnp.asarray(self.tokenizer.eos_id, jnp.int32)

            def step(carry, t):
                # Ragged prompts: row b's decode token t sits at *slot*
                # S + t (uniform, so one dynamic_update_slice serves the
                # whole batch) while its *position* is prompt_lens[b] + t
                # (per-row, for RoPE and the mask) — the same slot/position
                # split _score_labels uses.
                token, done, caches = carry
                pos = prompt_lens + t                              # [B]
                kv_pos = jnp.arange(total)[None, None, None, :]
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                decode_part = (kv_pos >= S) & (kv_pos - S <= t)
                step_mask = prompt_part | decode_part
                lg, caches = self.model.apply(
                    {"params": params}, token[:, None], pos[:, None],
                    step_mask, caches,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                done = done | (token == eos)
                nxt = jnp.where(done, eos, nxt)
                return (nxt, done, caches), token

            init = (first.astype(jnp.int32), first == eos, caches)
            if not early_exit:
                (_, _, caches), tokens = jax.lax.scan(
                    step, init, jnp.arange(max_new_tokens)
                )
                return tokens.T  # [B, max_new_tokens]

            # Early exit: fixed-size scan segments inside a while_loop with
            # an all-done predicate between segments.  Segment boundaries
            # keep the compiled-shape set O(1); the EOS-pre-filled buffer
            # makes a skipped tail byte-identical to a decoded one (post-
            # done steps emit exactly EOS).
            seg = min(8, max_new_tokens)
            n_seg = -(-max_new_tokens // seg)
            buf = jnp.full((n_seg * seg, B), eos, jnp.int32)

            def seg_cond(state):
                k, _, done, _, _ = state
                return (k < n_seg) & ~jnp.all(done)

            def seg_body(state):
                k, token, done, caches, buf = state
                (token, done, caches), seg_tokens = jax.lax.scan(
                    step, (token, done, caches),
                    k * seg + jnp.arange(seg),
                )
                buf = jax.lax.dynamic_update_slice(
                    buf, seg_tokens, (k * seg, jnp.asarray(0, jnp.int32))
                )
                return (k + 1, token, done, caches, buf)

            state = (jnp.asarray(0, jnp.int32),) + init + (buf,)
            _, _, _, _, buf = jax.lax.while_loop(seg_cond, seg_body, state)
            return buf[:max_new_tokens].T  # [B, max_new_tokens]

        self._generate_scan = _generate_scan

    @classmethod
    def from_pretrained_or_random(cls, model: str, **kwargs):
        quant = "none"
        if model.endswith("-int8"):
            model, quant = model[: -len("-int8")], "int8"
        preset = PRESETS.get(model)
        if preset is None:
            raise ValueError(
                f"unknown llama preset {model!r}; options: {sorted(PRESETS)}"
            )
        config = kwargs.pop("config", None) or preset()
        if quant != "none":
            config = dataclasses.replace(config, quant=quant)
        weight_quant = kwargs.pop("weight_quant", "none") or "none"
        if weight_quant != "none":
            config = dataclasses.replace(config, weight_quant=weight_quant)
        ckpt = kwargs.pop("checkpoint_path", None) or os.environ.get(
            "MUSICAAL_LLAMA_CKPT"
        )
        if model in ("llama3", "llama3-8b") and not ckpt:
            raise RuntimeError(
                "llama3-8b needs a checkpoint (set MUSICAAL_LLAMA_CKPT) and "
                "a multi-chip mesh; use --model llama3-tiny for smoke runs "
                "or --mock for the keyword kernel"
            )
        return cls(config=config, checkpoint_path=ckpt, **kwargs)

    def _trim_prompt_pad(self, ids, lens):
        """Trim tokenizer padding to the smallest power-of-two width (floor
        64) that covers the batch's longest prompt, capped at
        ``max_prompt_len``.

        The decoder analogue of the encoder's length buckets: a
        short-lyric batch previously paid full ``max_prompt_len`` (1024)
        prefill FLOPs per row.  Rounding to powers of two keeps the
        compiled-shape set O(log max_prompt_len); no content is cut
        (width ≥ lens.max()), and padding columns are masked out of
        attention either way, so labels/generations are unchanged.
        """
        from music_analyst_tpu.utils.shapes import round_pow2

        longest = int(lens.max()) if len(lens) else 1
        width = min(round_pow2(longest, 64), self.max_prompt_len)
        return ids[:, :width], lens

    def _encode_prompts(self, texts: Sequence[str]):
        prompts = [
            PROMPT_TEMPLATE.format(lyrics=t.strip()[:LYRICS_TRUNCATION])
            for t in texts
        ]
        ids, lens = self.tokenizer.encode_batch(prompts, self.max_prompt_len)
        return self._trim_prompt_pad(ids, lens)

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        if self.decode_mode == "generate":
            return self.classify_batch_by_generation(texts)
        prompt_ids, prompt_lens = self._encode_prompts(texts)
        # Prompt lengths cross the wire int16 (llama's 128k vocab keeps the
        # ids themselves int32); widened on device in _score_labels.
        from music_analyst_tpu.runtime.wire import (
            count_h2d_bytes,
            narrow_lengths,
        )

        prompt_lens = narrow_lengths(prompt_lens, self.max_prompt_len)
        count_h2d_bytes([prompt_ids, prompt_lens])
        scores = np.asarray(
            self._score_labels(
                self.params,
                jnp.asarray(prompt_ids),
                jnp.asarray(prompt_lens),
                jnp.asarray(self._label_ids),
                jnp.asarray(self._label_lens),
            )
        )
        best = scores.argmax(axis=1)
        labels = []
        for text, idx in zip(texts, best):
            if not text.strip():
                labels.append("Neutral")  # reference empty-lyric rule
            else:
                labels.append(SUPPORTED_LABELS[int(idx)])
        return labels

    def generate(
        self, prompt: str, max_new_tokens: int = 16
    ) -> str:
        """Greedy generation (API-parity path; label scoring is preferred)."""
        ids, lens = self.tokenizer.encode_batch([prompt], self.max_prompt_len)
        S = self.max_prompt_len
        caches = init_caches(self.config, 1, S + max_new_tokens)
        positions = jnp.arange(S)[None, :]
        mask = causal_mask(S, S + max_new_tokens, 0) & jnp.pad(
            padding_mask(jnp.asarray(lens), S),
            ((0, 0), (0, 0), (0, 0), (0, max_new_tokens)),
        )
        logits, caches = self.model.apply(
            {"params": self.params}, jnp.asarray(ids), positions, mask, caches,
            last_position=jnp.asarray(lens, jnp.int32) - 1,
        )
        caches = [
            KVCache(c.keys, c.values, jnp.asarray(int(lens[0]), jnp.int32))
            for c in caches
        ]
        token = jnp.argmax(logits[:, 0], axis=-1)
        out_tokens = []
        position = jnp.asarray([int(lens[0])], jnp.int32)
        for _ in range(max_new_tokens):
            out_tokens.append(int(token[0]))
            if out_tokens[-1] == getattr(self.tokenizer, "eos_id",
                                         ByteTokenizer.EOS):
                break
            token, caches = self._decode_step(
                self.params, token[:, None], position, caches
            )
            position = position + 1
        return self.tokenizer.decode(out_tokens)

    def generate_batch(
        self, prompts: Sequence[str], max_new_tokens: int = 16,
        early_exit: bool = True,
    ) -> List[str]:
        """Greedy generation for a whole batch in ONE compiled program.

        Prefill and all ``max_new_tokens`` decode steps run inside a single
        jit (``lax.scan`` over the KV cache) — no per-token host↔device
        round-trips, unlike :meth:`generate`'s explicit step loop (kept for
        API parity and as the differential oracle).  ``early_exit`` stops
        decoding once every row has emitted EOS (identical outputs either
        way; ``False`` keeps the always-``max_new_tokens`` scan as the
        equivalence oracle).
        """
        ids, lens = self.tokenizer.encode_batch(prompts, self.max_prompt_len)
        ids, lens = self._trim_prompt_pad(ids, lens)
        tokens = np.asarray(
            self._generate_scan(
                self.params, jnp.asarray(ids), jnp.asarray(lens),
                max_new_tokens, early_exit=early_exit,
            )
        )
        eos = self.tokenizer.eos_id
        outs = []
        for row in tokens:
            ids_out = []
            for t in row:
                if t == eos:
                    break
                ids_out.append(int(t))
            outs.append(self.tokenizer.decode(ids_out))
        return outs

    def slot_runtime(
        self,
        n_slots: int = 8,
        prefill_chunk: int = 64,
        max_new_tokens: int = 16,
        prompt_region: Optional[int] = None,
        decode_span: int = 4,
    ):
        """Build the continuous-batching device runtime for this model.

        The presence of this method is the capability probe the serving
        layer uses (``hasattr(backend, "slot_runtime")``) to decide whether
        a server can host the ``generate`` task.
        """
        from music_analyst_tpu.ops.kv_slots import SlotDecodeRuntime, SlotPlan

        chunk = max(1, min(int(prefill_chunk), self.max_prompt_len))
        if prompt_region is None:
            prompt_region = self.max_prompt_len
        region = min(int(prompt_region), self.max_prompt_len)
        region = max(chunk, chunk * ((region + chunk - 1) // chunk))
        plan = SlotPlan(
            n_slots=int(n_slots),
            prefill_chunk=chunk,
            prompt_region=region,
            max_new=int(max_new_tokens),
            decode_span=int(decode_span),
        )
        eos_id = getattr(self.tokenizer, "eos_id", ByteTokenizer.EOS)
        return SlotDecodeRuntime(self.model, self.config, plan, eos_id,
                                 mesh=self.mesh)

    def paged_runtime(
        self,
        n_slots: int = 8,
        prefill_chunk: int = 64,
        max_new_tokens: int = 16,
        prompt_region: Optional[int] = None,
        decode_span: int = 4,
        page_size: int = 16,
        kv_pages: int = 0,
        kv_quant: str = "none",
    ):
        """Build the prefix-shared paged decode runtime for this model.

        The paged sibling of :meth:`slot_runtime` (and the capability
        probe the serving layer uses for the default KV backend): the
        per-slot KV buffer becomes a view through an int32 page table
        over a shared page pool, so sequences with a common token prefix
        — every zero-shot prompt shares ``PROMPT_TEMPLATE``'s head —
        can map the same physical pages.  Prefix identity is keyed on
        *token ids* (whatever tokenizer is resolved), not on text, so
        byte/llama tokenizers share exactly what their encodings share.
        ``kv_pages=0`` auto-sizes the pool to one full sequence per slot.
        ``kv_quant="int8"`` stores the page pool as int8 codes with
        per-(page, row) scales, dequantized inside the fused
        paged-attention kernel (ops/paged_attention.py).
        """
        import math

        from music_analyst_tpu.ops.kv_pages import PagedDecodeRuntime, PagePlan
        from music_analyst_tpu.utils.shapes import round_pow2

        chunk = max(1, min(int(prefill_chunk), self.max_prompt_len))
        if prompt_region is None:
            prompt_region = self.max_prompt_len
        region = min(int(prompt_region), self.max_prompt_len)
        region = max(chunk, chunk * ((region + chunk - 1) // chunk))
        page = min(round_pow2(max(1, int(page_size)), 1), region)
        # The region must be a multiple of both the chunk and the page.
        unit = math.lcm(chunk, page)
        region = unit * ((region + unit - 1) // unit)
        pages_per_slot = region // page + -(-int(max_new_tokens) // page)
        n_pages = int(kv_pages) or int(n_slots) * pages_per_slot
        n_pages = max(n_pages, int(n_slots), pages_per_slot)
        plan = PagePlan(
            n_slots=int(n_slots),
            prefill_chunk=chunk,
            prompt_region=region,
            max_new=int(max_new_tokens),
            decode_span=int(decode_span),
            page_size=page,
            n_pages=n_pages,
        )
        eos_id = getattr(self.tokenizer, "eos_id", ByteTokenizer.EOS)
        return PagedDecodeRuntime(self.model, self.config, plan, eos_id,
                                  mesh=self.mesh, kv_quant=kv_quant)

    def generate_batch_continuous(
        self,
        prompts: Sequence[str],
        max_new_tokens: int = 16,
        n_slots: Optional[int] = None,
        prefill_chunk: int = 64,
        decode_span: int = 4,
        budgets: Optional[Sequence[int]] = None,
        page_size: Optional[int] = None,
        kv_pages: Optional[int] = None,
        kv_quant: Optional[str] = None,
        prefix_cache: bool = True,
        speculate_k: Optional[int] = None,
    ) -> List[str]:
        """Greedy generation via the continuous slot runtime, synchronously.

        Same outputs as :meth:`generate_batch` (byte-identical tokens per
        prompt — the slot cache mirrors the static layout, see
        ``ops/kv_slots.py``), but requests flow through admit→prefill→
        decode slots instead of one padded static batch, so rows with
        small ``budgets`` release their compute to waiting prompts
        mid-flight.  The scheduler is cached per geometry, so repeat calls
        reuse the compiled programs.

        The KV cache is paged with prefix sharing by default (see
        :meth:`paged_runtime`): prompts sharing a token-id prefix — the
        zero-shot template head, repeat songs — skip the shared prefill
        chunks and share physical pages.  ``page_size=0`` pins the
        monolithic slot cache; ``prefix_cache=False`` pages without
        sharing.  ``speculate_k > 0`` turns on draft-and-verify
        speculative decoding (see ``serving/decode_loop.py``) — fewer
        dispatches on self-similar completions.  All routes emit
        byte-identical tokens.
        """
        from music_analyst_tpu.serving.decode_loop import ContinuousScheduler
        from music_analyst_tpu.utils.shapes import round_pow2

        if not prompts:
            return []
        n_slots = int(n_slots or self.continuous_slots or 8)
        budgets = (
            [int(b) for b in budgets]
            if budgets is not None
            else [int(max_new_tokens)] * len(prompts)
        )
        if len(budgets) != len(prompts):
            raise ValueError("budgets must match prompts 1:1")
        # Match the static path's padded prompt width exactly so the slot
        # cache's KV geometry (and therefore every greedy token) lines up
        # with generate_batch on the same prompts.
        _, lens = self.tokenizer.encode_batch(prompts, self.max_prompt_len)
        longest = int(lens.max()) if len(lens) else 1
        region = min(round_pow2(longest, 64), self.max_prompt_len)
        chunk = min(int(prefill_chunk), region)
        cap = max(1, max(budgets))
        key = (n_slots, chunk, region, cap, int(decode_span),
               page_size, kv_pages, kv_quant, bool(prefix_cache), speculate_k)
        sched = self._slot_schedulers.get(key)
        if sched is None:
            sched = ContinuousScheduler(
                self,
                n_slots=n_slots,
                prefill_chunk=chunk,
                prompt_region=region,
                max_new_tokens=cap,
                decode_span=int(decode_span),
                max_queue=max(len(prompts), 64),
                page_size=page_size,
                kv_pages=kv_pages,
                kv_quant=kv_quant,
                prefix_cache=prefix_cache,
                speculate_k=speculate_k,
            )
            self._slot_schedulers[key] = sched
        reqs = [
            sched.submit(i, prompt, max_new_tokens=budget)
            for i, (prompt, budget) in enumerate(zip(prompts, budgets))
        ]
        sched.run_until_idle()
        outs = []
        for req in reqs:
            resp = req.response or {}
            if not resp.get("ok"):
                raise RuntimeError(
                    f"continuous generation failed for prompt {req.id}: "
                    f"{resp.get('error', 'unknown error')}"
                )
            outs.append(resp["text"])
        return outs

    def classify_by_generation(self, text: str) -> str:
        """Reference-semantics path: generate text, normalise first token."""
        prompt = PROMPT_TEMPLATE.format(lyrics=text.strip()[:LYRICS_TRUNCATION])
        return normalise_label(self.generate(prompt))

    def classify_batch_by_generation(
        self, texts: Sequence[str]
    ) -> List[str]:
        """Reference generation semantics at batch speed: free-text decode
        (one scan-jitted program for the whole batch) then the shared label
        normalizer (``scripts/sentiment_classifier.py:102-108``, empty-
        output crash fixed)."""
        prompts = [
            PROMPT_TEMPLATE.format(lyrics=t.strip()[:LYRICS_TRUNCATION])
            for t in texts
        ]
        # Same token budget as generate()'s default so the batch path and
        # the single-song reference path yield identical labels.  With
        # continuous_slots set, batch generation rides the continuous slot
        # runtime (identical tokens; see generate_batch_continuous).
        if self.continuous_slots:
            generations = self.generate_batch_continuous(
                prompts, max_new_tokens=16, n_slots=self.continuous_slots
            )
        else:
            generations = self.generate_batch(prompts, max_new_tokens=16)
        return [
            "Neutral" if not text.strip() else normalise_label(gen)
            for text, gen in zip(texts, generations)
        ]
