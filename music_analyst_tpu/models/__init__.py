"""Model families: keyword mock, encoder classifier, decoder LM."""
