"""Shared Flax building blocks for the model families.

Written TPU-first: bfloat16 activations by default (MXU-native), static
shapes everywhere, fused residual blocks XLA can pipeline, and attention
formulated so heads can be sharded over the ``tp`` mesh axis (head counts
are kept divisible by the tp degree by construction in the model configs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def rope_frequencies(
    head_dim: int, max_positions: int, theta: float = 10_000.0
) -> Tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables ``[max_positions, head_dim/2]``."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    positions = jnp.arange(max_positions, dtype=jnp.float32)
    angles = jnp.outer(positions, inv_freq)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array
) -> jax.Array:
    """Rotate ``x [B, S, H, D]`` by position-dependent angles.

    ``positions [B, S]`` indexes the precomputed tables, supporting both
    prefill (0..S) and decode (cache_len + step) without recompilation.
    """
    cos_p = cos[positions][:, :, None, :]  # [B, S, 1, D/2]
    sin_p = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate(
        (x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p), axis=-1
    )
    return rotated.astype(x.dtype)


class RMSNorm(nn.Module):
    """Root-mean-square norm (no mean subtraction), fp32 accumulation."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.epsilon
        )
        return (normed * scale).astype(x.dtype)


@dataclasses.dataclass
class KVCache:
    """Per-layer decode cache; keys/values ``[B, max_len, n_kv_heads, D]``.

    Replaces nothing in the reference (its LLM path is a remote Ollama
    server, ``scripts/sentiment_classifier.py:85-100``); on TPU the cache is
    an explicit on-device buffer whose head axis shards over ``tp`` so
    decode attention stays local to each chip.
    """

    keys: jax.Array
    values: jax.Array
    # int32 — filled positions.  A scalar means every row shares one write
    # offset (static batch decode); a ``[B]`` vector gives each row its own
    # offset (slot-indexed continuous decode, ops/kv_slots.py).
    length: jax.Array

    @classmethod
    def zeros(
        cls,
        batch: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "KVCache":
        shape = (batch, max_len, n_kv_heads, head_dim)
        return cls(
            keys=jnp.zeros(shape, dtype),
            values=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )

    def update(self, k_new: jax.Array, v_new: jax.Array) -> "KVCache":
        start = self.length
        k_new = k_new.astype(self.keys.dtype)
        v_new = v_new.astype(self.values.dtype)
        if start.ndim == 1:
            # Per-row offsets: each slot writes its new tokens at its own
            # fill level (dynamic_update_slice clamps, so callers must keep
            # every row's length strictly below max_len - new + 1).
            write = jax.vmap(
                lambda buf, new, s: jax.lax.dynamic_update_slice(
                    buf, new, (s, 0, 0)
                )
            )
            keys = write(self.keys, k_new, start)
            values = write(self.values, v_new, start)
        else:
            keys = jax.lax.dynamic_update_slice(
                self.keys, k_new, (0, start, 0, 0)
            )
            values = jax.lax.dynamic_update_slice(
                self.values, v_new, (0, start, 0, 0)
            )
        return KVCache(keys, values, start + k_new.shape[1])


jax.tree_util.register_dataclass(
    KVCache, data_fields=["keys", "values", "length"], meta_fields=[]
)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain attention ``[B, S, H, D]`` with fp32 softmax accumulation.

    Grouped-query support: when ``k``/``v`` carry fewer heads than ``q``,
    KV heads are broadcast over the query-head groups (Llama-3 GQA).
    """
    n_q_heads = q.shape[2]
    n_kv_heads = k.shape[2]
    if n_kv_heads != n_q_heads:
        group = n_q_heads // n_kv_heads
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class QuantDenseGeneral(nn.Module):
    """Drop-in for the two ``nn.DenseGeneral`` layouts with int8 compute.

    Parameter names/shapes are IDENTICAL to ``nn.DenseGeneral`` (`kernel`,
    `bias`), so checkpoint loaders, TP sharding rules, and params trained
    or initialized by the float modules apply unchanged — only the matmul
    runs through the dynamic int8 path (``ops/quant.py``).
    """

    features: Any          # int or tuple, as nn.DenseGeneral
    axis: Any = -1         # -1 or (-2, -1)
    use_bias: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from music_analyst_tpu.ops.quant import (
            quant_dense_axis_last,
            quant_dense_axis_last2,
        )

        feat = (
            (self.features,)
            if isinstance(self.features, int)
            else tuple(self.features)
        )
        if self.axis == -1:
            kshape = (x.shape[-1],) + feat
            n_contract = 1
        elif not isinstance(self.axis, int) and tuple(self.axis) == (-2, -1):
            assert len(feat) == 1
            kshape = (x.shape[-2], x.shape[-1], feat[0])
            n_contract = 2
        else:
            raise ValueError(f"unsupported axis {self.axis!r}")

        def kernel_init(key, shape, dtype):
            # Match nn.DenseGeneral: initialize on the FLATTENED 2-D shape
            # (fan_in = prod of contracted axes) and reshape — raw
            # lecun_normal on a 3-D shape would treat the leading dim as a
            # conv receptive field and under-scale by sqrt(n_heads).
            import numpy as _np

            flat = (
                int(_np.prod(shape[:n_contract])),
                int(_np.prod(shape[n_contract:])),
            )
            return nn.initializers.lecun_normal()(key, flat, dtype).reshape(
                shape
            )

        kernel = self.param("kernel", kernel_init, kshape, jnp.float32)
        bias = (
            self.param("bias", nn.initializers.zeros, feat, jnp.float32)
            if self.use_bias
            else None
        )
        fn = quant_dense_axis_last if self.axis == -1 else quant_dense_axis_last2
        return fn(x, kernel, bias, out_dtype=self.dtype)


class WqDenseGeneral(nn.Module):
    """DenseGeneral over a *stored* weight-quantized kernel.

    Same two layouts (and identical param names, shapes, and init) as
    ``nn.DenseGeneral``/``QuantDenseGeneral``, but the ``kernel`` slot may
    hold a ``QuantizedParam`` (ops/quant.py): int8 or packed-int4 codes +
    scales, dequant fused into the matmul epilogue (w8/w4 stored,
    activations dynamically row-quantized inside the op).  With a plain
    float array in the slot (random init, bf16 A/B baselines) it computes
    the ordinary float contraction, so one module serves both.

    The kernel is read through ``scope.get_variable`` rather than
    ``self.param`` when a QuantizedParam is stored: packed int4 halves
    axis 0, which Flax's declared-shape check would (correctly) reject for
    a plain param — the quantized store is a different *representation* of
    the declared kernel, not a different kernel.
    """

    features: Any          # int or tuple, as nn.DenseGeneral
    axis: Any = -1         # -1 or (-2, -1)
    use_bias: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from music_analyst_tpu.ops.quant import (
            QuantizedParam,
            wq_dense_axis_last,
            wq_dense_axis_last2,
        )

        feat = (
            (self.features,)
            if isinstance(self.features, int)
            else tuple(self.features)
        )
        if self.axis == -1:
            kshape = (x.shape[-1],) + feat
            n_contract = 1
        elif not isinstance(self.axis, int) and tuple(self.axis) == (-2, -1):
            assert len(feat) == 1
            kshape = (x.shape[-2], x.shape[-1], feat[0])
            n_contract = 2
        else:
            raise ValueError(f"unsupported axis {self.axis!r}")

        def kernel_init(key, shape, dtype):
            # Same flattened-fan-in init as QuantDenseGeneral (see above).
            import numpy as _np

            flat = (
                int(_np.prod(shape[:n_contract])),
                int(_np.prod(shape[n_contract:])),
            )
            return nn.initializers.lecun_normal()(key, flat, dtype).reshape(
                shape
            )

        kernel = None
        if self.scope is not None and self.scope.has_variable(
            "params", "kernel"
        ):
            stored = self.scope.get_variable("params", "kernel")
            if isinstance(stored, QuantizedParam):
                kernel = stored
        if kernel is None:
            kernel = self.param("kernel", kernel_init, kshape, jnp.float32)
        bias = (
            self.param("bias", nn.initializers.zeros, feat, jnp.float32)
            if self.use_bias
            else None
        )
        if isinstance(kernel, QuantizedParam):
            fn = (
                wq_dense_axis_last if self.axis == -1 else wq_dense_axis_last2
            )
            return fn(x, kernel, bias, out_dtype=self.dtype)
        # Float fallback: the contraction nn.DenseGeneral performs.
        xd = x.astype(self.dtype)
        kd = kernel.astype(self.dtype)
        contract = (
            ((xd.ndim - 1,), (0,))
            if n_contract == 1
            else ((xd.ndim - 2, xd.ndim - 1), (0, 1))
        )
        out = jax.lax.dot_general(xd, kd, (contract, ((), ())))
        if bias is not None:
            out = out + bias.astype(self.dtype)
        return out.astype(self.dtype)


def pick_dense_cls(weight_quant: str, quant: str):
    """One projection-class decision shared by every model family: stored
    weight-quant wins (it subsumes the matmul), then dynamic int8, then
    plain float."""
    if weight_quant != "none":
        return WqDenseGeneral
    if quant == "int8":
        return QuantDenseGeneral
    return nn.DenseGeneral


class MultiHeadAttention(nn.Module):
    """MHA/GQA with optional RoPE and optional KV cache.

    Projections use a single fused kernel per Q/K/V/O so each matmul is
    large enough to tile onto the MXU; head axes are laid out so a ``tp``
    sharding splits ``n_heads`` (and ``n_kv_heads``) without resharding.
    """

    n_heads: int
    n_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    use_rope: bool = False
    rope_theta: float = 10_000.0
    max_positions: int = 4096
    dtype: jnp.dtype = jnp.bfloat16
    # "dense" materializes [B,H,S,KV] logits (any mask, any shape);
    # "flash" runs the Pallas blocked online-softmax kernel
    # (ops/flash_attention.py) — O(S·D) HBM, causal+lengths masks only,
    # seq len must divide the kernel block size.
    attn_impl: str = "dense"
    flash_causal: bool = False
    # BERT-family projections carry biases (HF q_lin/k_lin/v_lin/out_lin
    # each have one); Llama-family does not.
    use_bias: bool = False
    # "int8" routes the Q/K/V/O projections through the dynamic int8
    # matmul (ops/quant.py) — inference-only MXU throughput lever.
    quant: str = "none"
    # "int8"/"int4" stores the projection kernels weight-quantized
    # (QuantizedParam leaves; ops/quant.py) — takes precedence over the
    # dynamic `quant` path.
    weight_quant: str = "none"

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        cache: Optional[KVCache] = None,
        lengths: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
    ):
        features = x.shape[-1]
        n_kv = self.n_kv_heads or self.n_heads
        head_dim = self.head_dim or features // self.n_heads
        dense_cls = pick_dense_cls(self.weight_quant, self.quant)
        dense = lambda feats, name: dense_cls(  # noqa: E731
            features=feats,
            axis=-1,
            use_bias=self.use_bias,
            dtype=self.dtype,
            name=name,
        )
        q = dense((self.n_heads, head_dim), "q_proj")(x)
        k = dense((n_kv, head_dim), "k_proj")(x)
        v = dense((n_kv, head_dim), "v_proj")(x)

        if self.use_rope:
            if positions is None:
                positions = jnp.broadcast_to(
                    jnp.arange(x.shape[1]), x.shape[:2]
                )
            cos, sin = rope_frequencies(
                head_dim, self.max_positions, self.rope_theta
            )
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)

        new_cache = None
        paged = False
        if cache is not None:
            new_cache = cache.update(k, v)
            # A paged cache (ops/paged_attention.PagedAttnView) carries
            # the physical page pool, not a contiguous buffer: its
            # ``attend`` runs the fused gather+QK+softmax+V kernel, so
            # the contiguous k/v unpack below never happens for it.
            paged = hasattr(new_cache, "attend")
            if not paged:
                k, v = new_cache.keys, new_cache.values

        if paged:
            out = new_cache.attend(q, mask)
        elif self.attn_impl == "flash" and cache is None:
            from music_analyst_tpu.ops.flash_attention import flash_attention

            # The flash kernel expresses masking ONLY via flash_causal +
            # lengths; an arbitrary `mask` array can't reach it and would
            # be silently dropped — refuse outright.  Callers on the flash
            # path pass mask=None and encode semantics in flash_causal /
            # lengths (see LlamaBlock / DistilBert TransformerBlock).
            if mask is not None:
                raise ValueError(
                    "attn_impl='flash' cannot apply a mask array; pass "
                    "mask=None with lengths= (padding) and/or flash_causal "
                    "set, or use attn_impl='dense' for arbitrary masks"
                )
            out = flash_attention(
                q, k, v, lengths=lengths, causal=self.flash_causal,
                q_segment_ids=segment_ids,
            )
        else:
            if segment_ids is not None:
                raise ValueError(
                    "segment_ids is the flash path's masking vocabulary; "
                    "dense callers build the block-diagonal mask array "
                    "themselves (models/distilbert.py)"
                )
            out = dot_product_attention(q, k, v, mask)
        out = dense_cls(
            features=features,
            axis=(-2, -1),
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="o_proj",
        )(out)
        if cache is not None:
            return out, new_cache
        return out


class SwiGLU(nn.Module):
    """Llama-style gated MLP; hidden dim shards over ``tp``."""

    hidden_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    quant: str = "none"
    weight_quant: str = "none"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        features = x.shape[-1]
        if self.weight_quant != "none":
            dense = lambda feats, name: WqDenseGeneral(  # noqa: E731
                features=feats, use_bias=False, dtype=self.dtype, name=name
            )
        elif self.quant == "int8":
            dense = lambda feats, name: QuantDenseGeneral(  # noqa: E731
                features=feats, use_bias=False, dtype=self.dtype, name=name
            )
        else:
            dense = lambda feats, name: nn.Dense(  # noqa: E731
                feats, use_bias=False, dtype=self.dtype, name=name
            )
        gate = dense(self.hidden_dim, "gate_proj")(x)
        up = dense(self.hidden_dim, "up_proj")(x)
        return dense(features, "down_proj")(nn.silu(gate) * up)


class GeluMLP(nn.Module):
    """BERT-style 2-layer MLP with biases."""

    hidden_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    quant: str = "none"
    weight_quant: str = "none"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        features = x.shape[-1]
        if self.weight_quant != "none":
            dense = lambda feats, name: WqDenseGeneral(  # noqa: E731
                features=feats, dtype=self.dtype, name=name
            )
        elif self.quant == "int8":
            dense = lambda feats, name: QuantDenseGeneral(  # noqa: E731
                features=feats, dtype=self.dtype, name=name
            )
        else:
            dense = lambda feats, name: nn.Dense(  # noqa: E731
                feats, dtype=self.dtype, name=name
            )
        h = dense(self.hidden_dim, "lin1")(x)
        h = nn.gelu(h, approximate=False)
        return dense(features, "lin2")(h)


def causal_mask(q_len: int, kv_len: int, offset) -> jax.Array:
    """``[1, 1, q_len, kv_len]`` causal mask with a dynamic cache offset."""
    q_pos = jnp.arange(q_len)[:, None] + offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return (kv_pos <= q_pos)[None, None, :, :]


def padding_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """``[B, 1, 1, max_len]`` key-padding mask from per-row lengths."""
    return (jnp.arange(max_len)[None, :] < lengths[:, None])[:, None, None, :]


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """``[B, 1, S, S]`` block-diagonal mask from per-token segment ids.

    Token pairs attend iff they share a segment id (packed batches /
    packed documents).  The single definition shared by the encoder, the
    training loss, and tests, so packing semantics can't drift per site.
    """
    return segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
