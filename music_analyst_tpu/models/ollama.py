"""Ollama HTTP passthrough backend — exact parity with the reference's live path.

The TPU-native backends (``mock``/``distilbert``/``llama``) replace the
per-song HTTP loop, but the original remote path remains available behind
the same flag surface (``--model ollama:<tag>``) for users migrating from
the reference: same endpoint contract (``$OLLAMA_ENDPOINT/api/generate``,
default ``http://localhost:11434``), same prompt template, same 4,000-char
truncation, same 120 s timeout, same first-token label normalization
(``scripts/sentiment_classifier.py:32-36,85-108``) — with the empty-response
``IndexError`` fixed (SURVEY.md §5 contract #5).
"""

from __future__ import annotations

import os
import time
from typing import List, Sequence

from music_analyst_tpu.engines.sentiment import ClassifierBackend
from music_analyst_tpu.models.llama import LYRICS_TRUNCATION, PROMPT_TEMPLATE
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import (
    RetryPolicy,
    classify_retryable,
    resolve_http_retries,
)
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.utils.labels import normalise_label

DEFAULT_ENDPOINT = "http://localhost:11434"


class OllamaClassifier(ClassifierBackend):
    name = "ollama"

    def __init__(
        self,
        model: str = "llama3",
        endpoint: str | None = None,
        timeout: float = 120.0,
        retries: int | None = None,
        backoff_seconds: float = 0.5,
    ) -> None:
        try:
            import requests  # noqa: F401
        except ImportError as exc:  # pragma: no cover - env-dependent
            raise RuntimeError(
                "The 'requests' package is required for the Ollama backend. "
                "Install it or use --mock."
            ) from exc
        self.model = model
        self.endpoint = endpoint or os.environ.get(
            "OLLAMA_ENDPOINT", DEFAULT_ENDPOINT
        )
        self.timeout = timeout
        # Transient-failure retries (upgrade over the reference, which
        # crashes the whole run on the first HTTP error, SURVEY.md §5
        # "Failure detection: fail-fast only").
        self.retries = resolve_http_retries(retries)
        self.backoff_seconds = backoff_seconds
        # Network-scale backoff: exponential from backoff_seconds with
        # full jitter, capped well below the request timeout, and never
        # sleeping past an armed bench deadline.
        self._retry = RetryPolicy(
            retries=self.retries,
            base_s=self.backoff_seconds,
            cap_s=min(30.0, max(self.backoff_seconds, timeout / 4.0)),
            classify=self._classify_exc,
        )
        self.last_latencies: List[float] = []

    @staticmethod
    def _classify_exc(exc: BaseException):
        """HTTP-aware retryability: 4xx (bar 408/429) is a verdict."""
        import requests

        if isinstance(exc, requests.RequestException):
            status = getattr(
                getattr(exc, "response", None), "status_code", None
            )
            if (status is not None and 400 <= status < 500
                    and status not in (408, 429)):
                return False, "http_client_error"
            return True, "http_error"
        return classify_retryable(exc)

    def _classify_one(self, lyrics: str) -> tuple[str, float]:
        import requests

        lyrics = lyrics.strip()
        if not lyrics:
            return "Neutral", 0.0  # reference classify() short-circuit
        payload = {
            "model": self.model,
            "prompt": PROMPT_TEMPLATE.format(lyrics=lyrics[:LYRICS_TRUNCATION]),
            "stream": False,
        }
        def _request() -> tuple[str, float]:
            fault_point("ollama.request", model=self.model)
            start = time.perf_counter()
            response = requests.post(
                f"{self.endpoint}/api/generate",
                json=payload,
                timeout=self.timeout,
            )
            elapsed = time.perf_counter() - start
            response.raise_for_status()
            raw_output = response.json().get("response", "").strip()
            get_telemetry().observe("ollama.request_seconds", elapsed)
            return normalise_label(raw_output), elapsed

        return self._retry.call(_request, site="ollama.request")

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        labels: List[str] = []
        self.last_latencies = []
        with get_telemetry().span("ollama_batch", rows=len(texts)):
            for text in texts:
                label, latency = self._classify_one(text)
                labels.append(label)
                self.last_latencies.append(latency)
        return labels
