"""Ollama HTTP passthrough backend — exact parity with the reference's live path.

The TPU-native backends (``mock``/``distilbert``/``llama``) replace the
per-song HTTP loop, but the original remote path remains available behind
the same flag surface (``--model ollama:<tag>``) for users migrating from
the reference: same endpoint contract (``$OLLAMA_ENDPOINT/api/generate``,
default ``http://localhost:11434``), same prompt template, same 4,000-char
truncation, same 120 s timeout, same first-token label normalization
(``scripts/sentiment_classifier.py:32-36,85-108``) — with the empty-response
``IndexError`` fixed (SURVEY.md §5 contract #5).
"""

from __future__ import annotations

import os
import time
from typing import List, Sequence

from music_analyst_tpu.engines.sentiment import ClassifierBackend
from music_analyst_tpu.models.llama import LYRICS_TRUNCATION, PROMPT_TEMPLATE
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.utils.labels import normalise_label

DEFAULT_ENDPOINT = "http://localhost:11434"


class OllamaClassifier(ClassifierBackend):
    name = "ollama"

    def __init__(
        self,
        model: str = "llama3",
        endpoint: str | None = None,
        timeout: float = 120.0,
        retries: int | None = None,
        backoff_seconds: float = 0.5,
    ) -> None:
        try:
            import requests  # noqa: F401
        except ImportError as exc:  # pragma: no cover - env-dependent
            raise RuntimeError(
                "The 'requests' package is required for the Ollama backend. "
                "Install it or use --mock."
            ) from exc
        self.model = model
        self.endpoint = endpoint or os.environ.get(
            "OLLAMA_ENDPOINT", DEFAULT_ENDPOINT
        )
        self.timeout = timeout
        # Transient-failure retries (upgrade over the reference, which
        # crashes the whole run on the first HTTP error, SURVEY.md §5
        # "Failure detection: fail-fast only").
        if retries is None:
            retries = int(os.environ.get("MUSICAAL_HTTP_RETRIES", "2"))
        self.retries = max(0, retries)
        self.backoff_seconds = backoff_seconds
        self.last_latencies: List[float] = []

    def _classify_one(self, lyrics: str) -> tuple[str, float]:
        import requests

        lyrics = lyrics.strip()
        if not lyrics:
            return "Neutral", 0.0  # reference classify() short-circuit
        payload = {
            "model": self.model,
            "prompt": PROMPT_TEMPLATE.format(lyrics=lyrics[:LYRICS_TRUNCATION]),
            "stream": False,
        }
        last_exc: Exception | None = None
        for attempt in range(self.retries + 1):
            start = time.perf_counter()
            try:
                response = requests.post(
                    f"{self.endpoint}/api/generate",
                    json=payload,
                    timeout=self.timeout,
                )
                elapsed = time.perf_counter() - start
                response.raise_for_status()
                raw_output = response.json().get("response", "").strip()
                get_telemetry().observe("ollama.request_seconds", elapsed)
                return normalise_label(raw_output), elapsed
            except requests.RequestException as exc:
                status = getattr(
                    getattr(exc, "response", None), "status_code", None
                )
                # Client errors are not transient — except 408 (request
                # timeout) and 429 (rate limit), the canonical retryables.
                if (status is not None and 400 <= status < 500
                        and status not in (408, 429)):
                    raise
                last_exc = exc
                if attempt < self.retries:
                    get_telemetry().count("http_retries")
                    time.sleep(self.backoff_seconds * (2 ** attempt))
        assert last_exc is not None
        raise last_exc

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        labels: List[str] = []
        self.last_latencies = []
        with get_telemetry().span("ollama_batch", rows=len(texts)):
            for text in texts:
                label, latency = self._classify_one(text)
                labels.append(label)
                self.last_latencies.append(latency)
        return labels
