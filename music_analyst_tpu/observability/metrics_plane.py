"""Fleet metrics plane: windowed time-series + SLO burn-rate alerting.

PR 16's request traces answer *why one request* was slow; this module is
the macro half — a continuous, windowed record of every serving signal
the fleet already computes (queue depth, TPOT/TTFT EWMAs, sheds by
reason, page-pool occupancy, prefix-cache hit rate, speculation
acceptance, journal fsync latency, respawns) so a burn-rate alert can
say *the fleet* is eating its error budget, and point at the trace that
shows why.

**Sampling.**  :class:`MetricsPlane` owns a daemon timer that, every
``--metrics-interval-ms`` (``$MUSICAAL_METRICS_INTERVAL_MS``; default
off — zero wire effect when disabled), scrapes one stats snapshot from
its attached source (``SentimentServer.stats_snapshot`` — the same dict
the ``stats`` wire op returns), flattens it into dotted scalar keys, and
appends the sample to a bounded ring.  Each sample also lands as one
crash-safe O_APPEND line in ``<profile-dir>/metrics.jsonl`` (the same
single-``write`` discipline as ``request_traces.jsonl`` — multi-process
safe, never torn) and refreshes a Prometheus-style text exposition file
(``metrics.<pid>.prom``, atomic replace).

**Fleet merge.**  The replica router's existing stats poll doubles as
the fleet scraper: every poll reply is fed to :meth:`ingest_replica`,
which keeps a per-replica breakdown and merges the fresh replicas into
one fleet view — histograms merged *exactly* (bucket counts, totals and
min/max fold; quantiles re-derived from the merged buckets), rates and
counters summed.  A failed scrape (fault site ``metrics.scrape``) marks
that replica's series stale and bumps ``scrape_errors``; stale replicas
are excluded from the fleet merge and serving replies are never
affected — the same degrade-don't-die contract as every other seam.

**Burn-rate alerts.**  Multi-window SLO burn: over a fast (1 min) and a
slow (10 min) window the plane differences the cumulative per-tenant
shed ledger and the decode TTFT/TPOT miss counters, normalises by the
offered load, and divides by the error budget (1%).  An alert fires
only when BOTH windows burn above the fast-burn threshold (14× budget —
the SRE page threshold) and resolves only when the fast window drops
below half of it: hysteresis, so steady state stays silent and a
recovering fleet doesn't flap.  Fired alerts are structured records on
``metrics.jsonl`` carrying the ``trace_id`` of the kept PR-16 exemplar
nearest the breach, so "the SLO is burning" dereferences to an actual
request waterfall.

Host-side only, no jax imports — importable before the test harness
pins ``JAX_PLATFORMS``.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

DEFAULT_METRICS_INTERVAL_MS = 0.0  # off: zero wire effect, no thread
METRICS_FILE = "metrics.jsonl"

_ENV_INTERVAL = "MUSICAAL_METRICS_INTERVAL_MS"
_ENV_DIR = "MUSICAAL_METRICS_DIR"

# Ring bound: at a 1 s interval this holds ~68 min of series — the slow
# burn window (10 min) always fits; beyond the bound the OLDEST sample
# is evicted and counted, never silently.
_MAX_SAMPLES = 4096
# Alert history kept in memory (the JSONL file holds everything).
_MAX_ALERTS = 256
# Flatten recursion guard: stats snapshots are shallow; a pathological
# self-referencing payload must not wedge the sampler.
_MAX_DEPTH = 8

# Burn-rate calibration (SRE multi-window, multi-burn paging alert):
# error budget 1% of offered load; page when BOTH windows burn at >= 14x
# budget; resolve when the fast window falls under half the threshold.
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 600.0
SLO_BUDGET = 0.01
BURN_FIRE = 14.0
BURN_RESOLVE = BURN_FIRE / 2.0


def resolve_metrics_interval_ms(value: Optional[Any] = None) -> float:
    """Sampling interval in ms: explicit flag > $MUSICAAL_METRICS_INTERVAL_MS
    > 0 (off).  A malformed/negative explicit flag raises (usage error);
    a malformed env var falls back to off, like every other serving
    ``resolve_*`` knob (serving/batcher.py)."""
    if value is not None:
        try:
            interval = float(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"--metrics-interval-ms expects a number >= 0, got {value!r}"
            ) from None
        if not math.isfinite(interval) or interval < 0.0:
            raise ValueError(
                f"--metrics-interval-ms expects a number >= 0, got {value!r}"
            )
        return interval
    raw = os.environ.get(_ENV_INTERVAL, "").strip()
    if raw:
        try:
            interval = float(raw)
        except ValueError:
            return DEFAULT_METRICS_INTERVAL_MS
        if math.isfinite(interval) and interval >= 0.0:
            return interval
    return DEFAULT_METRICS_INTERVAL_MS


def resolve_metrics_dir(value: Optional[str] = None) -> Optional[str]:
    """Series output directory: explicit (``--profile-dir``) >
    $MUSICAAL_METRICS_DIR > $MUSICAAL_TRACE_DIR (one profile dir feeds
    both planes) > None (in-memory ring only)."""
    if value:
        return value
    return (os.environ.get(_ENV_DIR)
            or os.environ.get("MUSICAAL_TRACE_DIR") or None)


# ----------------------------------------------------------- flattening


def _is_histogram(value: Any) -> bool:
    return (isinstance(value, dict)
            and isinstance(value.get("buckets_le"), list)
            and isinstance(value.get("counts"), list)
            and len(value["counts"]) == len(value["buckets_le"]))


def flatten_stats(
    snap: Any, prefix: str = "",
    out: Optional[Dict[str, float]] = None,
    hists: Optional[Dict[str, Dict[str, Any]]] = None,
    depth: int = 0,
) -> Tuple[Dict[str, float], Dict[str, Dict[str, Any]]]:
    """A stats snapshot → (dotted scalar series, histogram dicts).

    Numeric leaves keep their dotted path (``requests.rates.req_s``,
    ``slo.tenants.gold.shed``); bools count as 0/1; strings, lists and
    None are dropped (the series is numbers only).  Histogram-shaped
    dicts (``telemetry.core.Histogram.as_dict``) are captured whole for
    the exact fleet merge AND have their scalar summary fields (count,
    sum_s, p50_s, …) flattened like everything else.
    """
    if out is None:
        out = {}
    if hists is None:
        hists = {}
    if depth > _MAX_DEPTH or not isinstance(snap, dict):
        return out, hists
    for key, value in snap.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(value, bool):
            out[path] = float(value)
        elif isinstance(value, (int, float)):
            if math.isfinite(value):
                out[path] = float(value)
        elif isinstance(value, dict):
            if _is_histogram(value):
                hists[path] = value
            flatten_stats(value, path, out, hists, depth + 1)
    return out, hists


# ----------------------------------------------------- exact fleet merge


def _bucket_quantile(
    buckets_le: List[Any], counts: List[int], q: float
) -> Optional[float]:
    """Upper-bound quantile estimate from merged bucket counts: the
    bound of the first bucket whose cumulative count reaches ``q``.
    The overflow bin reports the histogram's max (the only finite bound
    we have for it)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    seen = 0
    for bound, count in zip(buckets_le, counts):
        seen += count
        if seen >= rank:
            return None if bound == "inf" else float(bound)
    return None


def merge_histograms(
    hists: List[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """Exact merge of same-bucket histogram dicts: counts summed
    elementwise, count/sum summed, min/max folded — every value each
    process observed is accounted for exactly.  Quantiles are re-derived
    from the merged buckets (upper-bound estimates; the per-process
    reservoirs cannot be merged exactly and are not pretended to be).
    Mismatched bucket layouts refuse to merge (None)."""
    hists = [h for h in hists if _is_histogram(h)]
    if not hists:
        return None
    buckets = hists[0]["buckets_le"]
    if any(h["buckets_le"] != buckets for h in hists[1:]):
        return None
    counts = [0] * len(buckets)
    total = 0.0
    n = 0
    vmin: Optional[float] = None
    vmax: Optional[float] = None
    for h in hists:
        for i, c in enumerate(h["counts"]):
            counts[i] += int(c)
        n += int(h.get("count") or 0)
        total += float(h.get("sum_s") or 0.0)
        for src, fold in (("min_s", min), ("max_s", max)):
            v = h.get(src)
            if isinstance(v, (int, float)):
                prev = vmin if src == "min_s" else vmax
                folded = v if prev is None else fold(prev, v)
                if src == "min_s":
                    vmin = folded
                else:
                    vmax = folded
    out: Dict[str, Any] = {
        "buckets_le": list(buckets),
        "counts": counts,
        "count": n,
        "sum_s": round(total, 9),
    }
    if n:
        if vmin is not None:
            out["min_s"] = round(vmin, 9)
        if vmax is not None:
            out["max_s"] = round(vmax, 9)
        out["avg_s"] = round(total / n, 9)
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            est = _bucket_quantile(buckets, counts, q)
            if est is None and vmax is not None:
                est = vmax  # overflow bin: max is the only finite bound
            out[f"{name}_s"] = None if est is None else round(est, 9)
    return out


# Leaf names that add across replicas: monotonic counters and capacity/
# depth gauges (two replicas each holding 3 queued requests ARE 6
# queued requests fleet-wide).  Everything else (EWMAs, ratios,
# quantiles, configuration) stays per-replica only — averaging them
# would invent numbers no process measured.
_SUM_LEAVES = frozenset((
    "admitted", "shed", "completed", "failed", "batches", "rows",
    "padded_rows", "dedup_folded", "queue_depth", "queue_depth_max",
    "shed_queue_full", "shed_slo_unattainable", "shed_tenant_budget",
    "shed_evicted", "sheds", "preemptions", "resumes", "requeues",
    "requeued", "dispatched", "respawns", "respawned", "in_flight",
    "ttft_slo_misses", "tpot_slo_misses", "active_slots", "free_slots",
    "prefill_backlog", "pages_free", "pages_total", "scrape_errors",
    "trace_drops", "flushed", "tail_kept", "started", "discarded",
    "fsyncs", "appended", "replayed", "dispatches", "fallbacks",
    "plain_ticks", "count",
))

# Engine-ledger merge (serving.decode.ledger): attribution seconds and
# per-tenant chip-seconds are additive chip-time across replicas, so
# every leaf under these subtrees sums; the scalar ledger counters sum
# by leaf name.  Fractions/coverage/goodput stay per-replica (they'd be
# meaningless added) — recompute fleet fractions from the merged
# seconds against the merged engine_wall_s.
_LEDGER_SUM_SUBTREES = (
    ".ledger.seconds.", ".ledger.chip_seconds.", ".ledger.prefill_chunks.",
)
_LEDGER_SUM_LEAVES = frozenset((
    "ticks", "idle_ticks", "engine_wall_s", "tokens_committed",
    "flushes", "ledger_drops",
))


def _summable(key: str) -> bool:
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "window_s":
        return False
    dotted = f".{key}."
    if ".rates." in dotted:
        return True  # req_s / tokens_s / shed_s fleet rate = sum
    if ".ledger." in dotted:
        if any(sub in dotted for sub in _LEDGER_SUM_SUBTREES):
            return True
        return leaf in _LEDGER_SUM_LEAVES
    return leaf in _SUM_LEAVES


def merge_flat(flats: List[Dict[str, float]]) -> Dict[str, float]:
    """Fleet view of per-replica scalar series: summable leaves (rates,
    counters, depths — see ``_SUM_LEAVES``) added across replicas."""
    fleet: Dict[str, float] = {}
    for flat in flats:
        for key, value in flat.items():
            if _summable(key):
                fleet[key] = fleet.get(key, 0.0) + value
    return {k: round(v, 6) for k, v in fleet.items()}


# --------------------------------------------------------------- plane


class MetricsPlane:
    """Per-process ring-buffer time-series store + burn-rate alerting."""

    def __init__(self, interval_ms: float = 0.0,
                 directory: Optional[str] = None,
                 role: str = "server",
                 max_samples: int = _MAX_SAMPLES) -> None:
        self.interval_ms = float(interval_ms)
        self.directory = directory
        self.role = role
        self.enabled = self.interval_ms > 0.0
        self.path = (
            os.path.join(directory, METRICS_FILE) if directory else None
        )
        self.prom_path = (
            os.path.join(directory, f"metrics.{os.getpid()}.prom")
            if directory else None
        )
        self.max_samples = int(max_samples)
        self.stale = False  # last local scrape failed
        self._source: Optional[Callable[[], Dict[str, Any]]] = None
        self._lock = threading.Lock()
        self._series: "deque[Dict[str, Any]]" = deque()
        self._hists: Dict[str, Dict[str, Any]] = {}
        self._replicas: Dict[str, Dict[str, Any]] = {}
        self._alert_state: Dict[Tuple[str, str], bool] = {}
        self._alerts: List[Dict[str, Any]] = []
        self._stats = {
            "samples": 0, "evicted": 0, "scrape_errors": 0,
            "flush_errors": 0, "alerts_fired": 0, "alerts_resolved": 0,
        }
        self._cost_ewma_s = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._closed = False

    # ---------------------------------------------------------- lifecycle

    def attach(self, source: Callable[[], Dict[str, Any]]) -> None:
        """Install the stats source (``SentimentServer.stats_snapshot``
        or any zero-arg callable returning a stats-shaped dict)."""
        self._source = source

    def start(self) -> None:
        """Take a baseline sample and start the interval timer.  The
        baseline makes the very first window delta well-defined even
        when the run is shorter than one interval."""
        if not self.enabled or self._thread is not None:
            return
        self.sample_now()
        self._thread = threading.Thread(
            target=self._run, name="metrics-plane", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        interval_s = self.interval_ms / 1000.0
        while not self._stop_evt.wait(interval_s):
            self.sample_now()

    def close(self) -> None:
        """End of serving: stop the timer and take one final sample so
        short runs still land a complete series (baseline + final)."""
        if self._closed:
            return
        self._closed = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.enabled:
            self.sample_now()

    # ----------------------------------------------------------- sampling

    def sample_now(self) -> Optional[Dict[str, Any]]:
        """One scrape: snapshot → flatten → ring + JSONL + exposition +
        alert evaluation.  A failed scrape (fault site
        ``metrics.scrape``) degrades to a stale-marked series and a
        counted ``scrape_errors`` — nothing is written, the file is
        never torn, and serving is never touched."""
        if self._source is None:
            return None
        t0 = time.perf_counter()
        try:
            from music_analyst_tpu.resilience.faults import fault_point

            fault_point("metrics.scrape", role=self.role)
            flat, hists = flatten_stats(self._source())
        except Exception:
            with self._lock:
                self._stats["scrape_errors"] += 1
            self.stale = True
            return None
        self.stale = False
        sample = {
            "type": "sample",
            "t": round(time.time(), 6),
            "pid": os.getpid(),
            "role": self.role,
            "metrics": flat,
        }
        with self._lock:
            if len(self._series) >= self.max_samples:
                self._series.popleft()
                self._stats["evicted"] += 1
            self._series.append(sample)
            self._stats["samples"] += 1
            self._hists = hists
        alerts = self._evaluate_alerts(sample)
        self._append_line(sample)
        for record in alerts:
            self._append_line(record)
        self._write_prom(flat, hists)
        cost = time.perf_counter() - t0
        self._cost_ewma_s = (
            cost if self._cost_ewma_s == 0.0
            else 0.8 * self._cost_ewma_s + 0.2 * cost
        )
        return sample

    def _append_line(self, record: Dict[str, Any]) -> None:
        """One appended write per record — same multi-process-safe
        discipline as ``reqtrace._flush``; a failure degrades to a
        counted ``flush_errors``, never a raise."""
        if self.path is None:
            return
        try:
            os.makedirs(self.directory, exist_ok=True)
            line = json.dumps(record, separators=(",", ":"), default=str)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, (line + "\n").encode("utf-8"))
            finally:
                os.close(fd)
        except Exception:  # noqa: BLE001 — degrade, don't die
            with self._lock:
                self._stats["flush_errors"] += 1

    def _write_prom(self, flat: Dict[str, float],
                    hists: Dict[str, Dict[str, Any]]) -> None:
        """Prometheus text exposition, atomically replaced per sample."""
        if self.prom_path is None:
            return
        lines: List[str] = []
        for key in sorted(flat):
            name = _prom_name(key)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {flat[key]:g}")
        for key in sorted(hists):
            hist = hists[key]
            name = _prom_name(key)
            lines.append(f"# TYPE {name} histogram")
            cumulative = 0
            for bound, count in zip(hist["buckets_le"], hist["counts"]):
                cumulative += int(count)
                le = "+Inf" if bound == "inf" else f"{float(bound):g}"
                lines.append(f'{name}_bucket{{le="{le}"}} {cumulative}')
            lines.append(f"{name}_sum {float(hist.get('sum_s') or 0.0):g}")
            lines.append(f"{name}_count {int(hist.get('count') or 0)}")
        try:
            tmp = f"{self.prom_path}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write("\n".join(lines) + "\n")
            os.replace(tmp, self.prom_path)
        except Exception:  # noqa: BLE001 — degrade, don't die
            with self._lock:
                self._stats["flush_errors"] += 1

    # -------------------------------------------------------- fleet merge

    def ingest_replica(self, name: str, stats: Any) -> None:
        """One replica's stats-poll reply → its series slot.  The
        router's poll loop is the fleet scraper; a scrape that trips the
        fault site (or hands back junk) marks the replica stale and
        counts ``scrape_errors`` — it never touches dispatch."""
        try:
            from music_analyst_tpu.resilience.faults import fault_point

            fault_point("metrics.scrape", replica=name)
            if not isinstance(stats, dict):
                raise TypeError(f"replica {name} stats: {type(stats)!r}")
            flat, hists = flatten_stats(stats)
        except Exception:
            with self._lock:
                self._stats["scrape_errors"] += 1
                entry = self._replicas.setdefault(name, {})
                entry["stale"] = True
            return
        with self._lock:
            self._replicas[name] = {
                "stale": False,
                "t": round(time.time(), 6),
                "flat": flat,
                "hists": hists,
            }

    def mark_replica_stale(self, name: str) -> None:
        """A replica the router already knows is unreachable (dead
        socket, respawning) keeps its last series, marked stale."""
        with self._lock:
            entry = self._replicas.setdefault(name, {})
            entry["stale"] = True

    def fleet_snapshot(self) -> Dict[str, Any]:
        """Fleet-level merge with per-replica breakdown.  Stale replicas
        are listed but EXCLUDED from the merged view — a dead replica's
        frozen counters must not be double-counted as live capacity."""
        with self._lock:
            replicas = {
                name: dict(entry) for name, entry in self._replicas.items()
            }
        fresh = {
            name: entry for name, entry in replicas.items()
            if not entry.get("stale") and entry.get("flat") is not None
        }
        hist_keys = sorted({
            key for entry in fresh.values()
            for key in (entry.get("hists") or {})
        })
        merged_hists = {}
        for key in hist_keys:
            merged = merge_histograms([
                entry["hists"][key] for entry in fresh.values()
                if key in (entry.get("hists") or {})
            ])
            if merged is not None:
                merged_hists[key] = merged
        return {
            "replica_count": len(replicas),
            "fresh_count": len(fresh),
            "stale": sorted(
                name for name, entry in replicas.items()
                if entry.get("stale")
            ),
            "merged": merge_flat(
                [entry["flat"] for entry in fresh.values()]
            ),
            "histograms": merged_hists,
            "replicas": {
                name: {
                    "stale": bool(entry.get("stale")),
                    "t": entry.get("t"),
                    "metrics": entry.get("flat") or {},
                }
                for name, entry in replicas.items()
            },
        }

    # ------------------------------------------------- burn-rate alerting

    def _window_burn(self, bad_key: str, total_keys: List[str],
                     window_s: float, now: float) -> float:
        """Burn rate over one window: (Δbad / Δoffered) / budget, from
        the cumulative counters in the ring.  Caller holds no lock."""
        with self._lock:
            series = list(self._series)
        if len(series) < 2:
            return 0.0
        cutoff = now - window_s
        base = series[0]
        for sample in series:
            if sample["t"] >= cutoff:
                base = sample
                break
        newest = series[-1]
        if base is newest:
            return 0.0

        def delta(key: str) -> float:
            return max(
                (newest["metrics"].get(key) or 0.0)
                - (base["metrics"].get(key) or 0.0),
                0.0,
            )

        bad = delta(bad_key)
        total = sum(delta(k) for k in total_keys)
        if total <= 0.0:
            return 0.0
        return (bad / total) / SLO_BUDGET

    def _signals(self, flat: Dict[str, float]) -> List[Dict[str, Any]]:
        """The burn signals live in this sample: one per tenant ledger
        (shed rate) plus the fleet-level decode TTFT/TPOT miss rates."""
        signals: List[Dict[str, Any]] = []
        for key in flat:
            m = re.fullmatch(r"slo\.tenants\.(.+)\.shed", key)
            if m:
                tenant = m.group(1)
                signals.append({
                    "alert": "shed_burn_rate",
                    "tenant": tenant,
                    "bad": key,
                    "total": [key, f"slo.tenants.{tenant}.admitted"],
                })
        for alert, bad in (("ttft_slo_burn", "decode.ttft_slo_misses"),
                           ("tpot_slo_burn", "decode.tpot_slo_misses")):
            if bad in flat:
                signals.append({
                    "alert": alert,
                    "tenant": None,
                    "bad": bad,
                    "total": ["requests.admitted"],
                })
        return signals

    def _evaluate_alerts(
        self, sample: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        """Multi-window evaluation with hysteresis: fire when both the
        fast and slow windows burn >= BURN_FIRE, resolve when the fast
        window falls under BURN_RESOLVE.  Returns the records to flush
        (the caller appends them after the sample line)."""
        now = sample["t"]
        records: List[Dict[str, Any]] = []
        for sig in self._signals(sample["metrics"]):
            fast = self._window_burn(
                sig["bad"], sig["total"], FAST_WINDOW_S, now
            )
            slow = self._window_burn(
                sig["bad"], sig["total"], SLOW_WINDOW_S, now
            )
            key = (sig["alert"], sig["tenant"] or "")
            active = self._alert_state.get(key, False)
            if not active and fast >= BURN_FIRE and slow >= BURN_FIRE:
                self._alert_state[key] = True
                records.append(
                    self._alert_record(sig, "firing", fast, slow, now)
                )
            elif active and fast < BURN_RESOLVE:
                self._alert_state[key] = False
                records.append(
                    self._alert_record(sig, "resolved", fast, slow, now)
                )
        if records:
            with self._lock:
                for record in records:
                    if record["state"] == "firing":
                        self._stats["alerts_fired"] += 1
                    else:
                        self._stats["alerts_resolved"] += 1
                    self._alerts.append(record)
                if len(self._alerts) > _MAX_ALERTS:
                    del self._alerts[: len(self._alerts) - _MAX_ALERTS]
        return records

    def _alert_record(self, sig: Dict[str, Any], state: str,
                      fast: float, slow: float,
                      now: float) -> Dict[str, Any]:
        record: Dict[str, Any] = {
            "type": "alert",
            "schema": 1,
            "alert": sig["alert"],
            "state": state,
            "severity": "page",
            "t": round(now, 6),
            "pid": os.getpid(),
            "role": self.role,
            "tenant": sig["tenant"],
            "burn_fast": round(fast, 3),
            "burn_slow": round(slow, 3),
            "threshold": BURN_FIRE,
            "budget": SLO_BUDGET,
            "window_fast_s": FAST_WINDOW_S,
            "window_slow_s": SLOW_WINDOW_S,
        }
        # Join to PR 16: the kept trace exemplar nearest the breach —
        # "the SLO is burning" comes with a waterfall to pull.
        try:
            from music_analyst_tpu.telemetry.reqtrace import get_reqtrace

            exemplar = get_reqtrace().nearest_kept(now)
            if exemplar:
                record["trace_id"] = exemplar["trace_id"]
                record["trace_kept"] = exemplar["kept"]
        except Exception:  # noqa: BLE001 — alerting must not raise
            pass
        return record

    # ----------------------------------------------------------- readouts

    def alerts(self, active_only: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            alerts = list(self._alerts)
            state = dict(self._alert_state)
        if not active_only:
            return alerts
        active = {key for key, on in state.items() if on}
        return [
            a for a in alerts
            if a["state"] == "firing"
            and (a["alert"], a["tenant"] or "") in active
        ]

    def overhead_fraction(self) -> Optional[float]:
        """Measured sampling cost as a fraction of the interval — the
        plane's whole decode-path overhead (sampling runs off-path; the
        only shared cost is the source's stats locks)."""
        if not self.enabled or self._cost_ewma_s == 0.0:
            return None
        return self._cost_ewma_s / (self.interval_ms / 1000.0)

    def series(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._series)

    def snapshot(self) -> Dict[str, Any]:
        """The ``metrics`` section of the ``stats`` op and the run
        manifest: counters, the newest sample, active alerts, and the
        fleet merge when this process scrapes replicas."""
        with self._lock:
            out: Dict[str, Any] = dict(self._stats)
            newest = self._series[-1] if self._series else None
            series_len = len(self._series)
            have_replicas = bool(self._replicas)
        out.update(
            interval_ms=self.interval_ms,
            role=self.role,
            stale=self.stale,
            series_len=series_len,
            path=self.path,
        )
        overhead = self.overhead_fraction()
        if overhead is not None:
            out["overhead_fraction"] = round(overhead, 6)
        if newest is not None:
            out["last"] = newest
        active = self.alerts(active_only=True)
        if active:
            out["active_alerts"] = active
        if have_replicas:
            out["fleet"] = self.fleet_snapshot()
        return out


def _prom_name(key: str) -> str:
    return "musicaal_" + re.sub(r"[^a-zA-Z0-9_]", "_", key)


# ------------------------------------------------------- process registry

_DISABLED = MetricsPlane()
_PLANE: MetricsPlane = _DISABLED


def get_metrics_plane() -> MetricsPlane:
    return _PLANE


def configure_metrics(
    interval_ms: Optional[Any] = None,
    directory: Optional[str] = None,
    role: str = "server",
) -> MetricsPlane:
    """Install the process plane.  When enabled, the resolved interval
    and directory are exported to the environment so spawned replica
    workers inherit the fleet's metrics configuration without extra
    plumbing — the same contract as ``configure_reqtrace``."""
    global _PLANE
    resolved_interval = resolve_metrics_interval_ms(interval_ms)
    resolved_dir = resolve_metrics_dir(directory)
    _PLANE.close()
    plane = MetricsPlane(resolved_interval, resolved_dir, role=role)
    if plane.enabled:
        os.environ[_ENV_INTERVAL] = repr(resolved_interval)
        if resolved_dir:
            os.environ[_ENV_DIR] = resolved_dir
    _PLANE = plane
    return plane
