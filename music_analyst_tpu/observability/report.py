"""Cross-run telemetry analytics: ``telemetry-report``.

PR 1 gave each run a telemetry dir, PR 2 a perf gate between *two* runs;
this reads *across* runs: bench driver captures (``BENCH_r*.json``), raw
bench JSON lines, and telemetry run dirs (``run_manifest.json`` +
``telemetry.jsonl`` + ``flight_record.json``) aggregate into one
run-over-run report — metric trajectory, error-taxonomy histogram,
stall/queue-depth breakdown, recompile counts.

Two classification sources, newest-wins:

* explicit ``error_kind`` (bench lines written after this PR carry the
  watchdog's verdict; flight records carry ``taxonomy``), else
* :func:`classify_error`, a pattern table over legacy error strings and
  process tails — this is what turns the committed ``BENCH_r05.json``
  ("device probe timed out after 40s (tunnel dead?)") into a structured
  ``tunnel_dead`` without rewriting history.

Exit codes follow ``profiling/diff.py``: 0 = newest run healthy, 1 = the
newest run failed (the report names its taxonomy), 2 = no usable input.
Jax-free by design — it must run against a dead tunnel.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

# Ordered pattern table: first match wins.  Tunnel patterns outrank the
# compile ones because a dead-tunnel traceback contains "setup/compile
# error" (see BENCH_r01.json) and must not read as a compile hang.
_ERROR_PATTERNS = (
    ("tunnel_dead", (
        "tunnel dead", "tunnel hang", "probe timed out",
        "unable to initialize backend", "backend setup/compile error",
        "unavailable:",
    )),
    ("fault_injected", ("fault injected", "injectedfault", "injectedfatal")),
    ("host_oom", (
        "memoryerror", "out of memory", "cannot allocate memory",
        "oom-kill",
    )),
    ("compile_hang", (
        "compile timed out", "compile hang", "compile stall",
        "stuck compiling",
    )),
    ("stage_stall", ("stage stall", "stage_stall")),
    ("serve_stall", ("serve stall", "serve_stall", "serve.dispatch")),
    ("decode_stall", ("decode stall", "decode_stall", "decode.dispatch")),
    ("router_stall", ("router stall", "router_stall", "router.dispatch",
                      "replica lost", "replica_lost")),
    ("deadline_expired", ("deadline",)),
    ("unclean_shutdown", ("unclean shutdown", "unclean_shutdown",
                          "journal without clean marker")),
    ("harness_killed", ("killed by harness", "sigkill")),
)


def classify_error(
    message: Optional[str], rc: Optional[int] = None
) -> Optional[str]:
    """Map a legacy error string (and/or exit code) to a taxonomy code.

    Returns None for "no error" (empty message with a zero rc); a
    nonempty message that matches nothing classifies as
    ``unknown_error`` — the histogram should show *that* the run failed
    even when it cannot say why.
    """
    text = (message or "").lower()
    for kind, needles in _ERROR_PATTERNS:
        if any(needle in text for needle in needles):
            return kind
    if rc == 124:  # coreutils `timeout` — the driver's outer kill
        return "harness_killed"
    if "timed out" in text or "timeout" in text:
        return "attempt_timeout"
    if text:
        return "unknown_error"
    if rc not in (None, 0):
        return "unknown_error"
    return None


# ---------------------------------------------------------------- loading


def _label(source: str) -> str:
    base = os.path.basename(os.path.normpath(source))
    return base[:-5] if base.endswith(".json") else base


def _bench_line_record(
    payload: Dict[str, Any], label: str, rc: Optional[int] = None
) -> Dict[str, Any]:
    error = payload.get("error")
    kind = payload.get("error_kind") or classify_error(error, rc)
    return {
        "label": label,
        "kind": "bench",
        "ok": kind is None,
        "metric": payload.get("metric"),
        "value": payload.get("value"),
        "unit": payload.get("unit"),
        "error": error,
        "error_kind": kind,
        "flight_record": payload.get("flight_record"),
        "telemetry": payload.get("telemetry"),
    }


def _capture_record(payload: Dict[str, Any], label: str) -> Dict[str, Any]:
    """A driver capture: {"n", "cmd", "rc", "tail", "parsed"}."""
    rc = payload.get("rc")
    parsed = payload.get("parsed")
    if isinstance(parsed, dict):
        rec = _bench_line_record(parsed, label, rc)
        rec["rc"] = rc
        return rec
    # No bench line survived: classify the process tail.
    kind = classify_error(payload.get("tail"), rc) or "unknown_error"
    return {
        "label": label,
        "kind": "bench",
        "ok": False,
        "metric": None,
        "value": None,
        "error": f"no bench line (rc={rc})",
        "error_kind": kind,
        "rc": rc,
    }


def _scan_jsonl(path: str) -> Dict[str, Any]:
    """Cheap single pass over a telemetry.jsonl: event count, watchdog
    trips, and the resilience events (injected faults, retries,
    recoveries, failovers) keyed by site."""
    events = 0
    trips: List[Dict[str, Any]] = []
    faults: Dict[str, int] = {}
    retries: Dict[str, int] = {}
    recoveries: Dict[str, int] = {}
    failovers: Dict[str, int] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            events += 1
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            name = event.get("name")
            attrs = event.get("attrs") or {}
            site = attrs.get("site", "?")
            if name == "watchdog_trip":
                trips.append(attrs)
            elif name == "fault_injected":
                faults[site] = faults.get(site, 0) + 1
            elif name == "retry":
                retries[site] = retries.get(site, 0) + 1
            elif name == "retry_recovered":
                recoveries[site] = recoveries.get(site, 0) + 1
            elif name in ("failover_retry", "failover_degraded"):
                failovers[site] = failovers.get(site, 0) + 1
            elif name == "serving_failover":  # batcher reload — no site attr
                failovers["serving.dispatch"] = (
                    failovers.get("serving.dispatch", 0) + 1
                )
    return {
        "events": events,
        "trips": trips,
        "faults": faults,
        "retries": retries,
        "recoveries": recoveries,
        "failovers": failovers,
    }


def _dir_record(directory: str, label: str) -> Optional[Dict[str, Any]]:
    """A telemetry run dir: manifest + JSONL + optional flight record."""
    manifest_path = os.path.join(directory, "run_manifest.json")
    jsonl_path = os.path.join(directory, "telemetry.jsonl")
    flight_path = os.path.join(directory, "flight_record.json")
    rec: Dict[str, Any] = {
        "label": label, "kind": "run_dir", "ok": True,
        "error": None, "error_kind": None,
    }
    found = False
    if os.path.exists(manifest_path):
        found = True
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (json.JSONDecodeError, OSError):
            manifest = {}
        counters = manifest.get("counters") or {}
        compile_info = manifest.get("compile") or {}
        rec.update(
            engine=manifest.get("engine"),
            wall_seconds=manifest.get("wall_seconds"),
            compile_count=compile_info.get("count"),
            compile_seconds=compile_info.get("seconds"),
            recompiles=int(counters.get("profiling.recompiles", 0)),
            pipeline=manifest.get("pipeline") or {},
        )
        obs = manifest.get("observability") or {}
        trips = (obs.get("watchdog") or {}).get("trips") or []
        if trips:
            rec["trips"] = trips
        # Histogram quantile summaries (p50/p95/p99) — serving latency
        # first and foremost, but any quantile-bearing histogram shows.
        quantiles: Dict[str, Dict[str, Any]] = {}
        for name, hist in (manifest.get("histograms") or {}).items():
            if isinstance(hist, dict) and hist.get("p50_s") is not None:
                quantiles[name] = {
                    k: hist.get(k) for k in ("p50_s", "p95_s", "p99_s")
                }
        if quantiles:
            rec["latency_quantiles"] = quantiles
        serving = manifest.get("serving")
        if serving:
            rec["serving"] = serving
        resilience = manifest.get("resilience")
        if resilience:
            rec["resilience"] = resilience
        if manifest.get("degraded"):
            rec["degraded"] = True
            rec["degraded_site"] = manifest.get("degraded_site")
            rec["degraded_reason"] = manifest.get("degraded_reason")
        # A run that started after an unclean predecessor (SIGKILL, cord
        # pull): the *previous* run's failure, witnessed by this one's
        # journal scan — reported without failing this run.
        if manifest.get("unclean_shutdown"):
            rec["unclean_shutdown"] = True
            rec["unclean_witness"] = manifest.get("unclean_witness")
    if os.path.exists(jsonl_path):
        found = True
        scan = _scan_jsonl(jsonl_path)
        rec["events"] = scan["events"]
        if scan["trips"]:
            rec.setdefault("trips", [])
            rec["trips"] = scan["trips"]  # JSONL is ground truth
        for key in ("faults", "retries", "recoveries", "failovers"):
            if scan[key]:
                rec.setdefault("resilience_events", {})[key] = scan[key]
    if os.path.exists(flight_path):
        found = True
        try:
            with open(flight_path, "r", encoding="utf-8") as fh:
                flight = json.load(fh)
            rec["flight_record"] = flight_path
            rec["error_kind"] = (
                flight.get("taxonomy")
                or classify_error(flight.get("detail"))
                or "unknown_error"
            )
            rec["error"] = flight.get("detail") or flight.get("reason")
            rec["ok"] = False
        except (json.JSONDecodeError, OSError):
            pass
    if rec.get("trips") and rec.get("error_kind") is None:
        rec["error_kind"] = rec["trips"][-1].get("taxonomy", "unknown_error")
        rec["error"] = f"watchdog tripped on {rec['trips'][-1].get('task')}"
        rec["ok"] = False
    return rec if found else None


def load_run(source: str) -> Optional[Dict[str, Any]]:
    """Normalize one source (file or dir) into a run record, or None."""
    label = _label(source)
    if os.path.isdir(source):
        return _dir_record(source, label)
    if not os.path.exists(source):
        return None
    try:
        with open(source, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except (json.JSONDecodeError, OSError):
        return None
    if not isinstance(payload, dict):
        return None
    if "parsed" in payload and "rc" in payload:
        return _capture_record(payload, label)
    if "metric" in payload and "value" in payload:
        return _bench_line_record(payload, label)
    if "schema" in payload and "reason" in payload:  # bare flight record
        return {
            "label": label, "kind": "flight", "ok": False,
            "error": payload.get("detail") or payload.get("reason"),
            "error_kind": payload.get("taxonomy") or "unknown_error",
            "flight_record": source,
        }
    return None


# -------------------------------------------------------------- reporting


def build_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate normalized run records (oldest→newest input order)."""
    taxonomy: Dict[str, int] = {}
    trajectory: List[Dict[str, Any]] = []
    stalls: List[Dict[str, Any]] = []
    recompiles: Dict[str, int] = {}
    latencies: List[Dict[str, Any]] = []
    resilience_sites: Dict[str, Dict[str, int]] = {}
    degraded_runs: List[Dict[str, Any]] = []
    router_fleet: List[Dict[str, Any]] = []
    speculation_runs: List[Dict[str, Any]] = []

    def _site(site: str) -> Dict[str, int]:
        return resilience_sites.setdefault(
            site,
            {"trips": 0, "retries": 0, "recoveries": 0,
             "gave_up": 0, "failovers": 0},
        )

    for rec in records:
        if rec.get("error_kind"):
            taxonomy[rec["error_kind"]] = taxonomy.get(rec["error_kind"], 0) + 1
        if rec.get("metric") is not None:
            trajectory.append({
                "label": rec["label"],
                "metric": rec["metric"],
                "value": rec.get("value"),
                "ok": rec["ok"],
            })
        if rec.get("recompiles"):
            recompiles[rec["label"]] = rec["recompiles"]
        for name, q in (rec.get("latency_quantiles") or {}).items():
            latencies.append({
                "label": rec["label"],
                "name": name,
                "p50_s": q.get("p50_s"),
                "p95_s": q.get("p95_s"),
                "p99_s": q.get("p99_s"),
            })
        for name, pipe in (rec.get("pipeline") or {}).items():
            for stage in pipe.get("stages") or []:
                if stage.get("stall_s") or stage.get("queue_depth_max"):
                    stalls.append({
                        "label": rec["label"],
                        "pipeline": name,
                        "stage": stage.get("stage"),
                        "stall_s": stage.get("stall_s"),
                        "queue_depth_max": stage.get("queue_depth_max"),
                    })
        # Per-site fault/retry/failover rollup.  The manifest's digest is
        # authoritative where present; JSONL event counts fill in for
        # dirs whose run died before the manifest landed.
        resilience = rec.get("resilience") or {}
        scanned = rec.get("resilience_events") or {}
        for site, info in (resilience.get("faults") or {}).items():
            _site(site)["trips"] += int(info.get("trips", 0))
        for site, info in (resilience.get("retries") or {}).items():
            entry = _site(site)
            entry["retries"] += int(info.get("retries", 0))
            entry["recoveries"] += int(info.get("recoveries", 0))
            entry["gave_up"] += int(info.get("gave_up", 0))
        if not resilience:
            for site, n in (scanned.get("faults") or {}).items():
                _site(site)["trips"] += int(n)
            for site, n in (scanned.get("retries") or {}).items():
                _site(site)["retries"] += int(n)
            for site, n in (scanned.get("recoveries") or {}).items():
                _site(site)["recoveries"] += int(n)
        for site, n in (scanned.get("failovers") or {}).items():
            _site(site)["failovers"] += int(n)
        if rec.get("degraded"):
            degraded_runs.append({
                "label": rec["label"],
                "site": rec.get("degraded_site"),
                "reason": rec.get("degraded_reason"),
            })
        # Scale-out serving: per-replica rollup of the manifest's
        # serving.router section (serving/router.py stats()).
        router = (rec.get("serving") or {}).get("router")
        if router:
            router_fleet.append({
                "label": rec["label"],
                "replica_count": router.get("replica_count"),
                "healthy_count": router.get("healthy_count"),
                "dispatched": router.get("dispatched"),
                "requeued": router.get("requeued"),
                "shed": router.get("shed"),
                "respawned": router.get("respawns"),
                "health_transitions": len(
                    router.get("health_transitions") or []
                ),
                "replicas": {
                    name: {
                        "dispatched": snap.get("dispatched"),
                        "requeues": snap.get("requeues"),
                        "respawns": snap.get("respawns"),
                        "health": snap.get("health"),
                    }
                    for name, snap in (router.get("replicas") or {}).items()
                },
            })
        # Speculative decoding: per-run acceptance digest from the
        # manifest's serving.decode.speculation section (decode_loop
        # stats()), rolled up into cross-run quantiles below.
        spec = ((rec.get("serving") or {}).get("decode") or {}).get(
            "speculation"
        ) or {}
        if spec.get("enabled"):
            speculation_runs.append({
                "label": rec["label"],
                "k": spec.get("k"),
                "dispatches": spec.get("dispatches"),
                "plain_ticks": spec.get("plain_ticks"),
                "fallbacks": spec.get("fallbacks"),
                "acceptance_rate": spec.get("acceptance_rate"),
                "accepted_tokens_per_dispatch": spec.get(
                    "accepted_tokens_per_dispatch"
                ),
            })

    def _quantiles(values: List[Any]) -> Optional[Dict[str, Any]]:
        vals = sorted(
            float(v) for v in values if isinstance(v, (int, float))
        )
        if not vals:
            return None

        def q(p: float) -> float:
            return vals[min(len(vals) - 1, int(round(p * (len(vals) - 1))))]

        return {"n": len(vals), "p50": q(0.5), "p95": q(0.95),
                "max": vals[-1]}

    speculation = {
        "runs": speculation_runs,
        "acceptance_rate": _quantiles(
            [r["acceptance_rate"] for r in speculation_runs]
        ),
        "accepted_tokens_per_dispatch": _quantiles(
            [r["accepted_tokens_per_dispatch"] for r in speculation_runs]
        ),
    }
    newest = records[-1] if records else None
    return {
        "schema": 1,
        "runs": records,
        "n_runs": len(records),
        "n_failed": sum(1 for r in records if not r["ok"]),
        "metric_trajectory": trajectory,
        "taxonomy_histogram": dict(
            sorted(taxonomy.items(), key=lambda kv: (-kv[1], kv[0]))
        ),
        "stalls": stalls,
        "recompiles": recompiles,
        "latency_quantiles": latencies,
        "resilience": dict(sorted(resilience_sites.items())),
        "degraded_runs": degraded_runs,
        "router_fleet": router_fleet,
        "speculation": speculation,
        "newest": {
            "label": newest["label"],
            "ok": newest["ok"],
            "error_kind": newest.get("error_kind"),
        } if newest else None,
    }


def render_report(report: Dict[str, Any]) -> List[str]:
    """The human-facing text rendering (one line list, print-ready)."""
    lines = [
        f"telemetry-report: {report['n_runs']} run(s), "
        f"{report['n_failed']} failed"
    ]
    if report["metric_trajectory"]:
        lines.append("metric trajectory:")
        for point in report["metric_trajectory"]:
            value = point["value"]
            shown = f"{value:.1f}" if isinstance(value, (int, float)) else "-"
            flag = "" if point["ok"] else "  [FAILED]"
            lines.append(
                f"  {point['label']}: {point['metric']} = {shown}{flag}"
            )
    if report["taxonomy_histogram"]:
        lines.append("error taxonomy:")
        width = max(len(k) for k in report["taxonomy_histogram"])
        for kind, n in report["taxonomy_histogram"].items():
            lines.append(f"  {kind.ljust(width)}  {'#' * n} ({n})")
    if report["stalls"]:
        lines.append("pipeline stalls (stall_s / queue_depth_max):")
        for s in report["stalls"]:
            lines.append(
                f"  {s['label']} {s['pipeline']}.{s['stage']}: "
                f"{s['stall_s']} / {s['queue_depth_max']}"
            )
    if report["recompiles"]:
        lines.append("recompiles:")
        for label, n in report["recompiles"].items():
            lines.append(f"  {label}: {n}")
    if report.get("latency_quantiles"):
        lines.append("latency quantiles (p50/p95/p99 s):")
        for q in report["latency_quantiles"]:
            def _fmt(value: Any) -> str:
                return (f"{value:.6f}"
                        if isinstance(value, (int, float)) else "-")
            lines.append(
                f"  {q['label']} {q['name']}: "
                f"{_fmt(q['p50_s'])} / {_fmt(q['p95_s'])} / "
                f"{_fmt(q['p99_s'])}"
            )
    if report.get("resilience"):
        lines.append(
            "fault/retry recovery (trips / retries / recoveries / "
            "gave_up / failovers):"
        )
        width = max(len(site) for site in report["resilience"])
        for site, c in report["resilience"].items():
            lines.append(
                f"  {site.ljust(width)}  {c['trips']} / {c['retries']} / "
                f"{c['recoveries']} / {c['gave_up']} / {c['failovers']}"
            )
    if report.get("router_fleet"):
        lines.append(
            "router fleet (per replica: dispatched / requeues / health):"
        )
        for fleet in report["router_fleet"]:
            lines.append(
                f"  {fleet['label']}: {fleet['replica_count']} replica(s), "
                f"{fleet['dispatched']} dispatched, "
                f"{fleet['requeued']} requeued, "
                f"{fleet['respawned'] or 0} respawned, "
                f"{fleet['health_transitions']} health transition(s)"
            )
            for name, snap in (fleet["replicas"] or {}).items():
                lines.append(
                    f"    {name}: {snap['dispatched']} / "
                    f"{snap['requeues']} / {snap['health']}"
                )
    speculation = report.get("speculation") or {}
    if speculation.get("runs"):
        lines.append(
            "speculative decoding (k / tok-per-dispatch / acceptance / "
            "fallbacks):"
        )

        def _num(value: Any) -> str:
            return (f"{value:.2f}"
                    if isinstance(value, (int, float)) else "-")

        for run in speculation["runs"]:
            lines.append(
                f"  {run['label']}: k={run['k']}, "
                f"{_num(run['accepted_tokens_per_dispatch'])} / "
                f"{_num(run['acceptance_rate'])} / "
                f"{run['fallbacks'] or 0}"
            )
        for key, title in (
            ("acceptance_rate", "acceptance rate"),
            ("accepted_tokens_per_dispatch", "accepted tokens/dispatch"),
        ):
            quants = speculation.get(key)
            if quants:
                lines.append(
                    f"  {title} across {quants['n']} run(s): "
                    f"p50={_num(quants['p50'])} p95={_num(quants['p95'])} "
                    f"max={_num(quants['max'])}"
                )
    for run in report.get("degraded_runs") or []:
        lines.append(
            f"  DEGRADED {run['label']}: {run['site']} ({run['reason']})"
        )
    newest = report.get("newest")
    if newest is not None:
        verdict = ("ok" if newest["ok"]
                   else f"FAILED ({newest['error_kind']})")
        lines.append(f"newest run {newest['label']}: {verdict}")
    return lines


def run_telemetry_report(
    sources: List[str], json_output: bool = False
) -> int:
    """CLI entry.  Exit 0 = newest healthy, 1 = newest failed, 2 = no
    usable input — diff.py's gate semantics, so CI can chain them."""
    import sys

    records: List[Dict[str, Any]] = []
    skipped: List[str] = []
    for source in sources:
        rec = load_run(source)
        if rec is None:
            skipped.append(source)
        else:
            records.append(rec)
    for source in skipped:
        print(f"telemetry-report: skipping unusable source: {source}",
              file=sys.stderr)
    if not records:
        print("telemetry-report: no usable runs among "
              f"{len(sources)} source(s)", file=sys.stderr)
        return 2
    report = build_report(records)
    if json_output:
        print(json.dumps(report, default=str))
    else:
        for line in render_report(report):
            print(line)
    return 0 if report["newest"]["ok"] else 1
