"""Cross-run telemetry analytics: ``telemetry-report``.

PR 1 gave each run a telemetry dir, PR 2 a perf gate between *two* runs;
this reads *across* runs: bench driver captures (``BENCH_r*.json``), raw
bench JSON lines, and telemetry run dirs (``run_manifest.json`` +
``telemetry.jsonl`` + ``flight_record.json``) aggregate into one
run-over-run report — metric trajectory, error-taxonomy histogram,
stall/queue-depth breakdown, recompile counts.

Two classification sources, newest-wins:

* explicit ``error_kind`` (bench lines written after this PR carry the
  watchdog's verdict; flight records carry ``taxonomy``), else
* :func:`classify_error`, a pattern table over legacy error strings and
  process tails — this is what turns the committed ``BENCH_r05.json``
  ("device probe timed out after 40s (tunnel dead?)") into a structured
  ``tunnel_dead`` without rewriting history.

Exit codes follow ``profiling/diff.py``: 0 = newest run healthy, 1 = the
newest run failed (the report names its taxonomy), 2 = no usable input.
Jax-free by design — it must run against a dead tunnel.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

# Ordered pattern table: first match wins.  Tunnel patterns outrank the
# compile ones because a dead-tunnel traceback contains "setup/compile
# error" (see BENCH_r01.json) and must not read as a compile hang.
_ERROR_PATTERNS = (
    ("tunnel_dead", (
        "tunnel dead", "tunnel hang", "probe timed out",
        "unable to initialize backend", "backend setup/compile error",
        "unavailable:",
    )),
    ("fault_injected", ("fault injected", "injectedfault", "injectedfatal")),
    ("host_oom", (
        "memoryerror", "out of memory", "cannot allocate memory",
        "oom-kill",
    )),
    ("compile_hang", (
        "compile timed out", "compile hang", "compile stall",
        "stuck compiling",
    )),
    ("stage_stall", ("stage stall", "stage_stall")),
    ("serve_stall", ("serve stall", "serve_stall", "serve.dispatch")),
    ("decode_stall", ("decode stall", "decode_stall", "decode.dispatch")),
    ("router_stall", ("router stall", "router_stall", "router.dispatch",
                      "replica lost", "replica_lost")),
    ("deadline_expired", ("deadline",)),
    ("unclean_shutdown", ("unclean shutdown", "unclean_shutdown",
                          "journal without clean marker")),
    ("harness_killed", ("killed by harness", "sigkill")),
)


def classify_error(
    message: Optional[str], rc: Optional[int] = None
) -> Optional[str]:
    """Map a legacy error string (and/or exit code) to a taxonomy code.

    Returns None for "no error" (empty message with a zero rc); a
    nonempty message that matches nothing classifies as
    ``unknown_error`` — the histogram should show *that* the run failed
    even when it cannot say why.
    """
    text = (message or "").lower()
    for kind, needles in _ERROR_PATTERNS:
        if any(needle in text for needle in needles):
            return kind
    if rc == 124:  # coreutils `timeout` — the driver's outer kill
        return "harness_killed"
    if "timed out" in text or "timeout" in text:
        return "attempt_timeout"
    if text:
        return "unknown_error"
    if rc not in (None, 0):
        return "unknown_error"
    return None


# ---------------------------------------------------------------- loading


def _label(source: str) -> str:
    base = os.path.basename(os.path.normpath(source))
    return base[:-5] if base.endswith(".json") else base


def _bench_line_record(
    payload: Dict[str, Any], label: str, rc: Optional[int] = None
) -> Dict[str, Any]:
    error = payload.get("error")
    kind = payload.get("error_kind") or classify_error(error, rc)
    return {
        "label": label,
        "kind": "bench",
        "ok": kind is None,
        "metric": payload.get("metric"),
        "value": payload.get("value"),
        "unit": payload.get("unit"),
        "error": error,
        "error_kind": kind,
        "flight_record": payload.get("flight_record"),
        "telemetry": payload.get("telemetry"),
    }


def _capture_record(payload: Dict[str, Any], label: str) -> Dict[str, Any]:
    """A driver capture: {"n", "cmd", "rc", "tail", "parsed"}."""
    rc = payload.get("rc")
    parsed = payload.get("parsed")
    if isinstance(parsed, dict):
        rec = _bench_line_record(parsed, label, rc)
        rec["rc"] = rc
        return rec
    # No bench line survived: classify the process tail.
    kind = classify_error(payload.get("tail"), rc) or "unknown_error"
    return {
        "label": label,
        "kind": "bench",
        "ok": False,
        "metric": None,
        "value": None,
        "error": f"no bench line (rc={rc})",
        "error_kind": kind,
        "rc": rc,
    }


def _scan_jsonl(path: str) -> Dict[str, Any]:
    """Cheap single pass over a telemetry.jsonl: event count, watchdog
    trips, and the resilience events (injected faults, retries,
    recoveries, failovers) keyed by site."""
    events = 0
    trips: List[Dict[str, Any]] = []
    faults: Dict[str, int] = {}
    retries: Dict[str, int] = {}
    recoveries: Dict[str, int] = {}
    failovers: Dict[str, int] = {}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            events += 1
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            name = event.get("name")
            attrs = event.get("attrs") or {}
            site = attrs.get("site", "?")
            if name == "watchdog_trip":
                trips.append(attrs)
            elif name == "fault_injected":
                faults[site] = faults.get(site, 0) + 1
            elif name == "retry":
                retries[site] = retries.get(site, 0) + 1
            elif name == "retry_recovered":
                recoveries[site] = recoveries.get(site, 0) + 1
            elif name in ("failover_retry", "failover_degraded"):
                failovers[site] = failovers.get(site, 0) + 1
            elif name == "serving_failover":  # batcher reload — no site attr
                failovers["serving.dispatch"] = (
                    failovers.get("serving.dispatch", 0) + 1
                )
    return {
        "events": events,
        "trips": trips,
        "faults": faults,
        "retries": retries,
        "recoveries": recoveries,
        "failovers": failovers,
    }


# Headline series the cross-run trajectory tracks (first→last per run).
# These are the fleet-health numbers an operator graphs first; the full
# series stays in metrics.jsonl for anything deeper.
_METRICS_HEADLINES = (
    "requests.rates.req_s",
    "requests.rates.shed_s",
    "decode.rates.tokens_s",
    "requests.admitted",
    "requests.shed",
)

# The alert-record fields worth carrying into the cross-run history.
_ALERT_FIELDS = (
    "alert", "state", "tenant", "t", "burn_fast", "burn_slow",
    "threshold", "trace_id",
)


def _scan_metrics_jsonl(path: str) -> Dict[str, Any]:
    """Single pass over a ``metrics.jsonl`` (observability/metrics_plane):
    sample count + time span, first→last of each headline series, and
    every burn-rate alert record."""
    samples = 0
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    first: Dict[str, float] = {}
    last: Dict[str, float] = {}
    alerts: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("type") == "alert":
                    alerts.append(
                        {k: rec.get(k) for k in _ALERT_FIELDS}
                    )
                    continue
                if rec.get("type") != "sample":
                    continue
                samples += 1
                t = rec.get("t")
                if isinstance(t, (int, float)):
                    t_first = t if t_first is None else t_first
                    t_last = t
                flat = rec.get("metrics") or {}
                for key in _METRICS_HEADLINES:
                    value = flat.get(key)
                    if isinstance(value, (int, float)):
                        first.setdefault(key, value)
                        last[key] = value
    except OSError:
        return {"summary": None, "alerts": []}
    summary: Optional[Dict[str, Any]] = None
    if samples:
        summary = {
            "samples": samples,
            "span_s": (
                round(t_last - t_first, 6)
                if t_first is not None and t_last is not None else None
            ),
            "series": {
                key: {"first": first.get(key), "last": last[key]}
                for key in last
            },
        }
    return {"summary": summary, "alerts": alerts}


_LEDGER_FIELDS = (
    "goodput_fraction", "coverage", "engine_wall_s", "ticks",
    "tokens_committed", "ledger_drops",
)


def _ledger_summary(ledger: Dict[str, Any],
                    records: int = 0) -> Optional[Dict[str, Any]]:
    """Compact digest of one engine-ledger snapshot (engine_ledger.py's
    ``snapshot()`` shape); None when the engine never ticked."""
    if not isinstance(ledger, dict) or not ledger.get("ticks"):
        return None
    out: Dict[str, Any] = {k: ledger.get(k) for k in _LEDGER_FIELDS}
    out["records"] = records
    fractions = ledger.get("fractions")
    if isinstance(fractions, dict):
        out["fractions"] = dict(fractions)
    chip = ledger.get("chip_seconds")
    if isinstance(chip, dict):
        out["chip_seconds"] = dict(chip)
    return out


def _scan_ledger_jsonl(path: str) -> Dict[str, Any]:
    """Single pass over ``engine_ledger.jsonl``.  Each record is a
    CUMULATIVE snapshot, so the last one IS the run's final ledger;
    earlier goodput fractions form the within-run trajectory."""
    final: Optional[Dict[str, Any]] = None
    records = 0
    goodput_first: Optional[float] = None
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict) or rec.get("type") != "ledger":
                    continue
                ledger = rec.get("ledger")
                if not isinstance(ledger, dict):
                    continue
                records += 1
                final = ledger
                g = ledger.get("goodput_fraction")
                if goodput_first is None and isinstance(g, (int, float)):
                    goodput_first = g
    except OSError:
        return {"summary": None}
    summary = _ledger_summary(final, records) if final else None
    if summary is not None and goodput_first is not None:
        summary["goodput_first"] = goodput_first
    return {"summary": summary}


def _dir_record(directory: str, label: str) -> Optional[Dict[str, Any]]:
    """A telemetry run dir: manifest + JSONL + optional flight record."""
    manifest_path = os.path.join(directory, "run_manifest.json")
    jsonl_path = os.path.join(directory, "telemetry.jsonl")
    flight_path = os.path.join(directory, "flight_record.json")
    rec: Dict[str, Any] = {
        "label": label, "kind": "run_dir", "ok": True,
        "error": None, "error_kind": None,
    }
    found = False
    if os.path.exists(manifest_path):
        found = True
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (json.JSONDecodeError, OSError):
            manifest = {}
        counters = manifest.get("counters") or {}
        compile_info = manifest.get("compile") or {}
        rec.update(
            engine=manifest.get("engine"),
            wall_seconds=manifest.get("wall_seconds"),
            compile_count=compile_info.get("count"),
            compile_seconds=compile_info.get("seconds"),
            recompiles=int(counters.get("profiling.recompiles", 0)),
            pipeline=manifest.get("pipeline") or {},
        )
        obs = manifest.get("observability") or {}
        trips = (obs.get("watchdog") or {}).get("trips") or []
        if trips:
            rec["trips"] = trips
        # Histogram quantile summaries (p50/p95/p99) — serving latency
        # first and foremost, but any quantile-bearing histogram shows.
        quantiles: Dict[str, Dict[str, Any]] = {}
        for name, hist in (manifest.get("histograms") or {}).items():
            if isinstance(hist, dict) and hist.get("p50_s") is not None:
                quantiles[name] = {
                    k: hist.get(k) for k in ("p50_s", "p95_s", "p99_s")
                }
        if quantiles:
            rec["latency_quantiles"] = quantiles
        serving = manifest.get("serving")
        if serving:
            rec["serving"] = serving
        # Tail-sampled trace exemplars (telemetry/reqtrace.py): quantile
        # trace ids that dereference into request_traces.jsonl.
        exemplars = manifest.get("trace_exemplars")
        if exemplars:
            rec["trace_exemplars"] = exemplars
        resilience = manifest.get("resilience")
        if resilience:
            rec["resilience"] = resilience
        if manifest.get("degraded"):
            rec["degraded"] = True
            rec["degraded_site"] = manifest.get("degraded_site")
            rec["degraded_reason"] = manifest.get("degraded_reason")
        # A run that started after an unclean predecessor (SIGKILL, cord
        # pull): the *previous* run's failure, witnessed by this one's
        # journal scan — reported without failing this run.
        if manifest.get("unclean_shutdown"):
            rec["unclean_shutdown"] = True
            rec["unclean_witness"] = manifest.get("unclean_witness")
    if os.path.exists(jsonl_path):
        found = True
        scan = _scan_jsonl(jsonl_path)
        rec["events"] = scan["events"]
        if scan["trips"]:
            rec.setdefault("trips", [])
            rec["trips"] = scan["trips"]  # JSONL is ground truth
        for key in ("faults", "retries", "recoveries", "failovers"):
            if scan[key]:
                rec.setdefault("resilience_events", {})[key] = scan[key]
    metrics_path = os.path.join(directory, "metrics.jsonl")
    if os.path.exists(metrics_path):
        found = True
        scan = _scan_metrics_jsonl(metrics_path)
        if scan["summary"]:
            rec["metrics"] = scan["summary"]
        if scan["alerts"]:
            rec["alerts"] = scan["alerts"]
    ledger_path = os.path.join(directory, "engine_ledger.jsonl")
    if os.path.exists(ledger_path):
        found = True
        scan = _scan_ledger_jsonl(ledger_path)
        if scan["summary"]:
            rec["engine_ledger"] = scan["summary"]
    if "engine_ledger" not in rec:
        # No JSONL (flush disarmed) — the manifest's final decode stats
        # still carry the ledger snapshot.
        manifest_ledger = (
            ((rec.get("serving") or {}).get("decode") or {}).get("ledger")
        )
        summary = _ledger_summary(manifest_ledger or {})
        if summary is not None:
            rec["engine_ledger"] = summary
    if os.path.exists(flight_path):
        found = True
        try:
            with open(flight_path, "r", encoding="utf-8") as fh:
                flight = json.load(fh)
            rec["flight_record"] = flight_path
            rec["error_kind"] = (
                flight.get("taxonomy")
                or classify_error(flight.get("detail"))
                or "unknown_error"
            )
            rec["error"] = flight.get("detail") or flight.get("reason")
            rec["ok"] = False
        except (json.JSONDecodeError, OSError):
            pass
    if rec.get("trips") and rec.get("error_kind") is None:
        rec["error_kind"] = rec["trips"][-1].get("taxonomy", "unknown_error")
        rec["error"] = f"watchdog tripped on {rec['trips'][-1].get('task')}"
        rec["ok"] = False
    return rec if found else None


def load_run(source: str) -> Optional[Dict[str, Any]]:
    """Normalize one source (file or dir) into a run record, or None."""
    label = _label(source)
    if os.path.isdir(source):
        return _dir_record(source, label)
    if not os.path.exists(source):
        return None
    try:
        with open(source, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except (json.JSONDecodeError, OSError):
        return None
    if not isinstance(payload, dict):
        return None
    if "parsed" in payload and "rc" in payload:
        return _capture_record(payload, label)
    if "metric" in payload and "value" in payload:
        return _bench_line_record(payload, label)
    if "schema" in payload and "reason" in payload:  # bare flight record
        return {
            "label": label, "kind": "flight", "ok": False,
            "error": payload.get("detail") or payload.get("reason"),
            "error_kind": payload.get("taxonomy") or "unknown_error",
            "flight_record": source,
        }
    return None


# -------------------------------------------------------------- reporting


def build_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate normalized run records (oldest→newest input order)."""
    taxonomy: Dict[str, int] = {}
    trajectory: List[Dict[str, Any]] = []
    stalls: List[Dict[str, Any]] = []
    recompiles: Dict[str, int] = {}
    latencies: List[Dict[str, Any]] = []
    resilience_sites: Dict[str, Dict[str, int]] = {}
    degraded_runs: List[Dict[str, Any]] = []
    router_fleet: List[Dict[str, Any]] = []
    speculation_runs: List[Dict[str, Any]] = []
    metrics_runs: List[Dict[str, Any]] = []
    alert_history: List[Dict[str, Any]] = []
    ledger_runs: List[Dict[str, Any]] = []
    chip_seconds_by_tenant: Dict[str, float] = {}

    def _site(site: str) -> Dict[str, int]:
        return resilience_sites.setdefault(
            site,
            {"trips": 0, "retries": 0, "recoveries": 0,
             "gave_up": 0, "failovers": 0},
        )

    for rec in records:
        if rec.get("error_kind"):
            taxonomy[rec["error_kind"]] = taxonomy.get(rec["error_kind"], 0) + 1
        if rec.get("metric") is not None:
            trajectory.append({
                "label": rec["label"],
                "metric": rec["metric"],
                "value": rec.get("value"),
                "ok": rec["ok"],
            })
        if rec.get("recompiles"):
            recompiles[rec["label"]] = rec["recompiles"]
        for name, q in (rec.get("latency_quantiles") or {}).items():
            entry = {
                "label": rec["label"],
                "name": name,
                "p50_s": q.get("p50_s"),
                "p95_s": q.get("p95_s"),
                "p99_s": q.get("p99_s"),
            }
            # Attach the matching trace exemplars so "p99 is slow" comes
            # with a trace id to pull the waterfall for.
            exemplar = (rec.get("trace_exemplars") or {}).get(name)
            if isinstance(exemplar, dict):
                entry["exemplars"] = {
                    p: exemplar[p]
                    for p in ("p50", "p95", "p99") if p in exemplar
                }
            latencies.append(entry)
        for name, pipe in (rec.get("pipeline") or {}).items():
            for stage in pipe.get("stages") or []:
                if stage.get("stall_s") or stage.get("queue_depth_max"):
                    stalls.append({
                        "label": rec["label"],
                        "pipeline": name,
                        "stage": stage.get("stage"),
                        "stall_s": stage.get("stall_s"),
                        "queue_depth_max": stage.get("queue_depth_max"),
                    })
        # Per-site fault/retry/failover rollup.  The manifest's digest is
        # authoritative where present; JSONL event counts fill in for
        # dirs whose run died before the manifest landed.
        resilience = rec.get("resilience") or {}
        scanned = rec.get("resilience_events") or {}
        for site, info in (resilience.get("faults") or {}).items():
            _site(site)["trips"] += int(info.get("trips", 0))
        for site, info in (resilience.get("retries") or {}).items():
            entry = _site(site)
            entry["retries"] += int(info.get("retries", 0))
            entry["recoveries"] += int(info.get("recoveries", 0))
            entry["gave_up"] += int(info.get("gave_up", 0))
        if not resilience:
            for site, n in (scanned.get("faults") or {}).items():
                _site(site)["trips"] += int(n)
            for site, n in (scanned.get("retries") or {}).items():
                _site(site)["retries"] += int(n)
            for site, n in (scanned.get("recoveries") or {}).items():
                _site(site)["recoveries"] += int(n)
        for site, n in (scanned.get("failovers") or {}).items():
            _site(site)["failovers"] += int(n)
        if rec.get("degraded"):
            degraded_runs.append({
                "label": rec["label"],
                "site": rec.get("degraded_site"),
                "reason": rec.get("degraded_reason"),
            })
        # Scale-out serving: per-replica rollup of the manifest's
        # serving.router section (serving/router.py stats()).
        router = (rec.get("serving") or {}).get("router")
        if router:
            router_fleet.append({
                "label": rec["label"],
                "replica_count": router.get("replica_count"),
                "healthy_count": router.get("healthy_count"),
                "dispatched": router.get("dispatched"),
                "requeued": router.get("requeued"),
                "shed": router.get("shed"),
                "respawned": router.get("respawns"),
                "health_transitions": len(
                    router.get("health_transitions") or []
                ),
                "replicas": {
                    name: {
                        "dispatched": snap.get("dispatched"),
                        "requeues": snap.get("requeues"),
                        "respawns": snap.get("respawns"),
                        "health": snap.get("health"),
                    }
                    for name, snap in (router.get("replicas") or {}).items()
                },
            })
        # Speculative decoding: per-run acceptance digest from the
        # manifest's serving.decode.speculation section (decode_loop
        # stats()), rolled up into cross-run quantiles below.
        spec = ((rec.get("serving") or {}).get("decode") or {}).get(
            "speculation"
        ) or {}
        # Metrics-plane trajectory + burn-rate alert history (scanned
        # from metrics.jsonl by _dir_record above).
        metrics = rec.get("metrics")
        if metrics:
            metrics_runs.append({"label": rec["label"], **metrics})
        for alert in rec.get("alerts") or []:
            alert_history.append({"label": rec["label"], **alert})
        # Engine goodput ledger: per-run attribution digest (scanned from
        # engine_ledger.jsonl, or the manifest's serving.decode.ledger)
        # → cross-run goodput trajectory + fleet chip-second totals.
        ledger = rec.get("engine_ledger")
        if ledger:
            ledger_runs.append({"label": rec["label"], **ledger})
            for tenant, secs in (ledger.get("chip_seconds") or {}).items():
                if isinstance(secs, (int, float)):
                    chip_seconds_by_tenant[tenant] = round(
                        chip_seconds_by_tenant.get(tenant, 0.0) + secs, 6
                    )
        if spec.get("enabled"):
            speculation_runs.append({
                "label": rec["label"],
                "k": spec.get("k"),
                "dispatches": spec.get("dispatches"),
                "plain_ticks": spec.get("plain_ticks"),
                "fallbacks": spec.get("fallbacks"),
                "acceptance_rate": spec.get("acceptance_rate"),
                "accepted_tokens_per_dispatch": spec.get(
                    "accepted_tokens_per_dispatch"
                ),
            })

    def _quantiles(values: List[Any]) -> Optional[Dict[str, Any]]:
        vals = sorted(
            float(v) for v in values if isinstance(v, (int, float))
        )
        if not vals:
            return None

        def q(p: float) -> float:
            return vals[min(len(vals) - 1, int(round(p * (len(vals) - 1))))]

        return {"n": len(vals), "p50": q(0.5), "p95": q(0.95),
                "max": vals[-1]}

    speculation = {
        "runs": speculation_runs,
        "acceptance_rate": _quantiles(
            [r["acceptance_rate"] for r in speculation_runs]
        ),
        "accepted_tokens_per_dispatch": _quantiles(
            [r["accepted_tokens_per_dispatch"] for r in speculation_runs]
        ),
    }
    newest = records[-1] if records else None
    return {
        "schema": 1,
        "runs": records,
        "n_runs": len(records),
        "n_failed": sum(1 for r in records if not r["ok"]),
        "metric_trajectory": trajectory,
        "taxonomy_histogram": dict(
            sorted(taxonomy.items(), key=lambda kv: (-kv[1], kv[0]))
        ),
        "stalls": stalls,
        "recompiles": recompiles,
        "latency_quantiles": latencies,
        "resilience": dict(sorted(resilience_sites.items())),
        "degraded_runs": degraded_runs,
        "router_fleet": router_fleet,
        "speculation": speculation,
        "metrics_runs": metrics_runs,
        "alert_history": alert_history,
        "ledger_runs": ledger_runs,
        "chip_seconds_by_tenant": dict(
            sorted(chip_seconds_by_tenant.items(),
                   key=lambda kv: (-kv[1], kv[0]))
        ),
        "newest": {
            "label": newest["label"],
            "ok": newest["ok"],
            "error_kind": newest.get("error_kind"),
        } if newest else None,
    }


def render_report(report: Dict[str, Any]) -> List[str]:
    """The human-facing text rendering (one line list, print-ready)."""
    lines = [
        f"telemetry-report: {report['n_runs']} run(s), "
        f"{report['n_failed']} failed"
    ]
    if report["metric_trajectory"]:
        lines.append("metric trajectory:")
        for point in report["metric_trajectory"]:
            value = point["value"]
            shown = f"{value:.1f}" if isinstance(value, (int, float)) else "-"
            flag = "" if point["ok"] else "  [FAILED]"
            lines.append(
                f"  {point['label']}: {point['metric']} = {shown}{flag}"
            )
    if report["taxonomy_histogram"]:
        lines.append("error taxonomy:")
        width = max(len(k) for k in report["taxonomy_histogram"])
        for kind, n in report["taxonomy_histogram"].items():
            lines.append(f"  {kind.ljust(width)}  {'#' * n} ({n})")
    if report["stalls"]:
        lines.append("pipeline stalls (stall_s / queue_depth_max):")
        for s in report["stalls"]:
            lines.append(
                f"  {s['label']} {s['pipeline']}.{s['stage']}: "
                f"{s['stall_s']} / {s['queue_depth_max']}"
            )
    if report["recompiles"]:
        lines.append("recompiles:")
        for label, n in report["recompiles"].items():
            lines.append(f"  {label}: {n}")
    if report.get("latency_quantiles"):
        lines.append("latency quantiles (p50/p95/p99 s):")
        for q in report["latency_quantiles"]:
            def _fmt(value: Any) -> str:
                return (f"{value:.6f}"
                        if isinstance(value, (int, float)) else "-")
            lines.append(
                f"  {q['label']} {q['name']}: "
                f"{_fmt(q['p50_s'])} / {_fmt(q['p95_s'])} / "
                f"{_fmt(q['p99_s'])}"
            )
            exemplars = q.get("exemplars") or {}
            if exemplars:
                shown = " ".join(
                    f"{p}={exemplars[p].get('trace_id')}"
                    for p in ("p50", "p95", "p99") if p in exemplars
                )
                lines.append(f"    trace exemplars: {shown}")
    if report.get("resilience"):
        lines.append(
            "fault/retry recovery (trips / retries / recoveries / "
            "gave_up / failovers):"
        )
        width = max(len(site) for site in report["resilience"])
        for site, c in report["resilience"].items():
            lines.append(
                f"  {site.ljust(width)}  {c['trips']} / {c['retries']} / "
                f"{c['recoveries']} / {c['gave_up']} / {c['failovers']}"
            )
    if report.get("router_fleet"):
        lines.append(
            "router fleet (per replica: dispatched / requeues / health):"
        )
        for fleet in report["router_fleet"]:
            lines.append(
                f"  {fleet['label']}: {fleet['replica_count']} replica(s), "
                f"{fleet['dispatched']} dispatched, "
                f"{fleet['requeued']} requeued, "
                f"{fleet['respawned'] or 0} respawned, "
                f"{fleet['health_transitions']} health transition(s)"
            )
            for name, snap in (fleet["replicas"] or {}).items():
                lines.append(
                    f"    {name}: {snap['dispatched']} / "
                    f"{snap['requeues']} / {snap['health']}"
                )
    speculation = report.get("speculation") or {}
    if speculation.get("runs"):
        lines.append(
            "speculative decoding (k / tok-per-dispatch / acceptance / "
            "fallbacks):"
        )

        def _num(value: Any) -> str:
            return (f"{value:.2f}"
                    if isinstance(value, (int, float)) else "-")

        for run in speculation["runs"]:
            lines.append(
                f"  {run['label']}: k={run['k']}, "
                f"{_num(run['accepted_tokens_per_dispatch'])} / "
                f"{_num(run['acceptance_rate'])} / "
                f"{run['fallbacks'] or 0}"
            )
        for key, title in (
            ("acceptance_rate", "acceptance rate"),
            ("accepted_tokens_per_dispatch", "accepted tokens/dispatch"),
        ):
            quants = speculation.get(key)
            if quants:
                lines.append(
                    f"  {title} across {quants['n']} run(s): "
                    f"p50={_num(quants['p50'])} p95={_num(quants['p95'])} "
                    f"max={_num(quants['max'])}"
                )
    if report.get("metrics_runs"):
        lines.append("metrics plane (headline series, first -> last):")

        def _mnum(value: Any) -> str:
            return (f"{value:.2f}"
                    if isinstance(value, (int, float)) else "-")

        for run in report["metrics_runs"]:
            span = run.get("span_s")
            span_text = (f" over {span:.1f}s"
                         if isinstance(span, (int, float)) else "")
            lines.append(
                f"  {run['label']}: {run['samples']} sample(s){span_text}"
            )
            for key, point in sorted((run.get("series") or {}).items()):
                lines.append(
                    f"    {key}: {_mnum(point.get('first'))} -> "
                    f"{_mnum(point.get('last'))}"
                )
    if report.get("alert_history"):
        lines.append("burn-rate alert history:")
        for alert in report["alert_history"]:
            tenant = (f" tenant={alert['tenant']}"
                      if alert.get("tenant") else "")
            trace = (f" trace={alert['trace_id']}"
                     if alert.get("trace_id") else "")
            lines.append(
                f"  {alert['label']} {alert.get('alert')}{tenant}: "
                f"{alert.get('state')} "
                f"burn {alert.get('burn_fast')}x/{alert.get('burn_slow')}x "
                f"(threshold {alert.get('threshold')}x){trace}"
            )
    if report.get("ledger_runs"):
        lines.append("engine ledger (goodput trajectory):")

        def _lnum(value: Any) -> str:
            return (f"{value:.2f}"
                    if not isinstance(value, bool)
                    and isinstance(value, (int, float)) else "-")
        for run in report["ledger_runs"]:
            fractions = run.get("fractions") or {}
            wall = run.get("engine_wall_s")
            wall_text = (f" wall={wall:.2f}s"
                         if isinstance(wall, (int, float)) else "")
            drops = run.get("ledger_drops") or 0
            drops_text = f" drops={drops}" if drops else ""
            lines.append(
                f"  {run['label']}: goodput={_lnum(run.get('goodput_fraction'))} "
                f"prefill={_lnum(fractions.get('prefill'))} "
                f"spec_waste={_lnum(fractions.get('spec_waste'))} "
                f"idle={_lnum(fractions.get('idle_bubble'))} "
                f"coverage={_lnum(run.get('coverage'))}"
                f"{wall_text}{drops_text}"
            )
        if report.get("chip_seconds_by_tenant"):
            lines.append("chip-seconds by tenant (all runs):")
            total = sum(
                v for v in report["chip_seconds_by_tenant"].values()
                if isinstance(v, (int, float))
            )
            for tenant, secs in report["chip_seconds_by_tenant"].items():
                share = (f" ({secs / total:.0%})"
                         if total and isinstance(secs, (int, float)) else "")
                lines.append(f"  {tenant:<16} {_lnum(secs)}s{share}")
    for run in report.get("degraded_runs") or []:
        lines.append(
            f"  DEGRADED {run['label']}: {run['site']} ({run['reason']})"
        )
    newest = report.get("newest")
    if newest is not None:
        verdict = ("ok" if newest["ok"]
                   else f"FAILED ({newest['error_kind']})")
        lines.append(f"newest run {newest['label']}: {verdict}")
    return lines


def run_telemetry_report(
    sources: List[str], json_output: bool = False
) -> int:
    """CLI entry.  Exit 0 = newest healthy, 1 = newest failed, 2 = no
    usable input — diff.py's gate semantics, so CI can chain them."""
    import sys

    records: List[Dict[str, Any]] = []
    skipped: List[str] = []
    for source in sources:
        rec = load_run(source)
        if rec is None:
            skipped.append(source)
        else:
            records.append(rec)
    for source in skipped:
        print(f"telemetry-report: skipping unusable source: {source}",
              file=sys.stderr)
    if not records:
        print("telemetry-report: no usable runs among "
              f"{len(sources)} source(s)", file=sys.stderr)
        return 2
    report = build_report(records)
    if json_output:
        print(json.dumps(report, default=str))
    else:
        for line in render_report(report):
            print(line)
    return 0 if report["newest"]["ok"] else 1


# ----------------------------------------------------------- trace-report
#
# ``trace-report`` reconstructs cross-process request waterfalls from the
# per-process records in ``request_traces.jsonl`` (telemetry/reqtrace.py:
# each process that handled a kept request appended ONE line with its
# spans).  Records sharing a ``trace_id`` are one request's journey; the
# ``parent`` span pointer links a replica worker's record back to the
# router front end's record.  Jax-free, like telemetry-report.

from music_analyst_tpu.telemetry.reqtrace import (  # noqa: E402  (jax-free)
    PHASE_NAMES,
    TRACE_FILE,
)

_MAX_RENDERED_TRACES = 20


def _iter_trace_files(source: str) -> List[str]:
    """A source is a trace .jsonl itself, or a directory holding
    ``request_traces*.jsonl`` (the profile dir)."""
    if os.path.isdir(source):
        out = []
        try:
            names = sorted(os.listdir(source))
        except OSError:
            return []
        stem = TRACE_FILE[: -len(".jsonl")]
        for name in names:
            if name.startswith(stem) and name.endswith(".jsonl"):
                out.append(os.path.join(source, name))
        return out
    if source.endswith(".jsonl") and os.path.exists(source):
        return [source]
    return []


def _alert_trace_ids(source: str) -> List[str]:
    """Trace ids named by burn-rate alert records in an alert file
    (``metrics.jsonl``, or any JSONL of ``type == "alert"`` records from
    observability/metrics_plane.py).  Directories, non-JSONL files, and
    files without alert records return [] — they are trace sources, not
    alert sources."""
    if not os.path.isfile(source) or not source.endswith((".jsonl", ".json")):
        return []
    ids: List[str] = []
    try:
        with open(source, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (isinstance(rec, dict) and rec.get("type") == "alert"
                        and isinstance(rec.get("trace_id"), str)):
                    ids.append(rec["trace_id"])
    except OSError:
        return []
    return ids


def load_trace_records(sources: List[str]) -> List[Dict[str, Any]]:
    """Every parseable trace record across all sources, input order."""
    records: List[Dict[str, Any]] = []
    for source in sources:
        for path in _iter_trace_files(source):
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if (isinstance(rec, dict)
                                and isinstance(rec.get("trace_id"), str)
                                and isinstance(rec.get("spans"), list)):
                            records.append(rec)
            except OSError:
                continue
    return records


def _phase_spans(record: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [
        s for s in record.get("spans") or []
        if isinstance(s, dict) and s.get("cat") == "phase"
        and s.get("name") in PHASE_NAMES
        and isinstance(s.get("t"), (int, float))
        and isinstance(s.get("dur"), (int, float))
    ]


def _span_extent(record: Dict[str, Any]) -> Optional[float]:
    phases = _phase_spans(record)
    if not phases:
        return None
    t0 = min(s["t"] for s in phases)
    t1 = max(s["t"] + s["dur"] for s in phases)
    return max(t1 - t0, 0.0)


def _pick_root(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The request's entry process: a record with no parent span, else
    the one whose admit phase starts earliest (a journal-replay record
    points at a crashed predecessor whose line may never have landed)."""
    roots = [r for r in records if not r.get("parent")]
    pool = roots or records

    def admit_t(rec: Dict[str, Any]) -> float:
        starts = [
            s["t"] for s in _phase_spans(rec) if s["name"] == "admit"
        ]
        if starts:
            return min(starts)
        phases = _phase_spans(rec)
        return min((s["t"] for s in phases), default=float("inf"))

    return min(pool, key=admit_t)


def build_waterfall(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One trace id's records → waterfall + critical-path attribution.

    Attribution uses the ROOT record's phase spans only: by construction
    (the cursor partition in reqtrace.py) they tile the root process's
    wall time, so their shares of the wire latency are exact and sum to
    the coverage figure.  Child records (replica workers) show up both
    as the root's ``downstream`` phase and, nested, as their own
    per-phase breakdown under ``downstream/``.
    """
    root = _pick_root(records)
    phases = _phase_spans(root)
    wire = root.get("wire_s")
    if not isinstance(wire, (int, float)) or wire < 0:
        wire = _span_extent(root)
    phase_seconds: Dict[str, float] = {}
    for span in phases:
        phase_seconds[span["name"]] = (
            phase_seconds.get(span["name"], 0.0) + span["dur"]
        )
    covered = sum(phase_seconds.values())
    coverage = (covered / wire) if wire else None
    attribution = {
        name: {
            "seconds": round(seconds, 6),
            "share": round(seconds / wire, 4) if wire else None,
        }
        for name, seconds in sorted(
            phase_seconds.items(), key=lambda kv: -kv[1]
        )
    }
    children = [
        r for r in records
        if r is not root and r.get("parent") == root.get("span")
    ]
    downstream: Dict[str, Any] = {}
    for child in children:
        breakdown: Dict[str, float] = {}
        for span in _phase_spans(child):
            breakdown[span["name"]] = (
                breakdown.get(span["name"], 0.0) + span["dur"]
            )
        downstream[f"{child.get('role', 'worker')}:{child.get('span')}"] = {
            name: round(seconds, 6)
            for name, seconds in sorted(
                breakdown.items(), key=lambda kv: -kv[1]
            )
        }
    phase_names = {s["name"] for s in phases}
    complete = (
        "admit" in phase_names
        and "reply" in phase_names
        and isinstance(wire, (int, float)) and wire is not None
    )
    out: Dict[str, Any] = {
        "trace_id": root["trace_id"],
        "complete": complete,
        "wire_s": round(wire, 6) if isinstance(wire, (int, float)) else None,
        "coverage": round(coverage, 4) if coverage is not None else None,
        "kept": root.get("kept"),
        "op": root.get("op"),
        "tenant": root.get("tenant"),
        "role": root.get("role"),
        "n_records": len(records),
        "attribution": attribution,
        "records": records,
    }
    if downstream:
        out["downstream"] = downstream
    dropped = sum(int(r.get("spans_dropped") or 0) for r in records)
    if dropped:
        out["spans_dropped"] = dropped
    return out


def build_trace_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_id: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        by_id.setdefault(rec["trace_id"], []).append(rec)
    traces = [build_waterfall(recs) for recs in by_id.values()]
    traces.sort(key=lambda t: (t["wire_s"] is None, -(t["wire_s"] or 0.0)))
    complete = [t for t in traces if t["complete"]]
    kept_reasons: Dict[str, int] = {}
    for t in traces:
        reason = t.get("kept") or "?"
        kept_reasons[reason] = kept_reasons.get(reason, 0) + 1
    return {
        "schema": 1,
        "n_traces": len(traces),
        "n_complete": len(complete),
        "n_records": len(records),
        "kept_reasons": dict(
            sorted(kept_reasons.items(), key=lambda kv: (-kv[1], kv[0]))
        ),
        "traces": traces,
    }


def render_trace_report(report: Dict[str, Any]) -> List[str]:
    """Waterfall text: one block per trace (slowest first), each span on
    its own line offset-aligned to the trace's start."""

    def _pct(value: Any) -> str:
        return f"{value * 100.0:.1f}%" if isinstance(value, float) else "-"

    lines = [
        f"trace-report: {report['n_traces']} trace(s) "
        f"({report['n_complete']} complete) from "
        f"{report['n_records']} process record(s)"
    ]
    alert_filter = report.get("alert_filter")
    if alert_filter:
        lines.append(
            f"alert filter: {alert_filter['n_alert_records']} alert "
            f"record(s) -> {len(alert_filter['trace_ids'])} trace id(s)"
        )
    if report["kept_reasons"]:
        shown = ", ".join(
            f"{k}={n}" for k, n in report["kept_reasons"].items()
        )
        lines.append(f"kept: {shown}")
    for trace in report["traces"][:_MAX_RENDERED_TRACES]:
        wire = trace["wire_s"]
        wire_text = f"{wire:.6f}s" if isinstance(wire, float) else "?"
        flag = "" if trace["complete"] else "  [INCOMPLETE]"
        lines.append(
            f"trace {trace['trace_id']}: wire {wire_text}, "
            f"coverage {_pct(trace['coverage'])}, kept={trace['kept']}, "
            f"{trace['n_records']} process(es){flag}"
        )
        starts = [
            s["t"]
            for rec in trace["records"]
            for s in rec.get("spans") or []
            if isinstance(s.get("t"), (int, float))
        ]
        t_zero = min(starts) if starts else 0.0
        for rec in sorted(
            trace["records"],
            key=lambda r: min(
                (s["t"] for s in _phase_spans(r)), default=float("inf")
            ),
        ):
            depth = 0 if not rec.get("parent") else 1
            pad = "  " * (depth + 1)
            lines.append(
                f"{pad}[{rec.get('role', '?')} pid={rec.get('pid')}] "
                f"span={rec.get('span')}"
            )
            for span in sorted(
                rec.get("spans") or [], key=lambda s: s.get("t", 0.0)
            ):
                mark = "·" if span.get("cat") == "detail" else "█"
                lines.append(
                    f"{pad}  {mark} {span['name']:<14} "
                    f"+{span['t'] - t_zero:.6f}s  {span['dur']:.6f}s"
                )
        shares = " | ".join(
            f"{name} {_pct(info['share'])}"
            for name, info in trace["attribution"].items()
        )
        if shares:
            lines.append(f"  attribution: {shares}")
        for child, breakdown in (trace.get("downstream") or {}).items():
            inner = ", ".join(
                f"{name}={seconds:.6f}s"
                for name, seconds in breakdown.items()
            )
            lines.append(f"  downstream {child}: {inner}")
    hidden = report["n_traces"] - min(
        report["n_traces"], _MAX_RENDERED_TRACES
    )
    if hidden > 0:
        lines.append(f"... {hidden} more trace(s) not shown")
    return lines


def run_trace_report(sources: List[str], json_output: bool = False) -> int:
    """CLI entry.  Exit 0 = at least one complete waterfall, 1 = traces
    found but none complete, 2 = no usable input — the 0/1/2 gate
    semantics telemetry-report and profile-diff already use.

    A source holding burn-rate alert records (``metrics.jsonl``) is an
    *alert* source: its named ``trace_id``s become a filter, and the
    trace records are pulled from the alert file's own directory — so
    "the pager fired" resolves straight to the breaching waterfalls.
    """
    import sys

    alert_records = 0
    wanted: set = set()
    trace_sources: List[str] = []
    for source in sources:
        ids = _alert_trace_ids(source)
        if ids:
            alert_records += len(ids)
            wanted.update(ids)
            trace_sources.append(
                os.path.dirname(os.path.abspath(source))
            )
        else:
            trace_sources.append(source)
    records = load_trace_records(trace_sources)
    if wanted:
        records = [r for r in records if r["trace_id"] in wanted]
    if not records:
        print(
            f"trace-report: no trace records among {len(sources)} "
            "source(s) (expected request_traces*.jsonl lines"
            + (" matching the alert trace ids" if wanted else "")
            + ")",
            file=sys.stderr,
        )
        return 2
    report = build_trace_report(records)
    if wanted:
        report["alert_filter"] = {
            "n_alert_records": alert_records,
            "trace_ids": sorted(wanted),
        }
    if json_output:
        print(json.dumps(report, default=str))
    else:
        for line in render_trace_report(report):
            print(line)
    return 0 if report["n_complete"] else 1
