"""Heartbeat watchdog: classify stalls instead of reporting bare timeouts.

The r05 failure mode — "device probe timed out after 40s (tunnel dead?)"
— is a *guess* encoded in an error string.  This module makes the guess
structural: anything that can hang (a prefetch stage fn, a first
compile, a device probe, a device readback, an engine's fold loop) runs
inside a :func:`watch` scope carrying a **kind**, and a monitor thread
classifies any scope that stops beating into a taxonomy code:

========  ==================  =====================================
kind      taxonomy            typical owner
========  ==================  =====================================
stage     ``stage_stall``     ``runtime/prefetch.py`` stage fns
compile   ``compile_hang``    ``profiling/compile.py`` lower+compile
probe     ``tunnel_dead``     ``bench.py --probe`` device query
device    ``device_stall``    engine collect()/step dispatch paths
host      ``host_stall``      host-side loops (persong fold)
serve     ``serve_stall``     ``serving/batcher.py`` dispatch edge
========  ==================  =====================================

A trip emits a ``watchdog_trip`` telemetry event, records itself for the
run manifest (``telemetry/introspect.py``), and dumps a flight record
(``observability/flight.py``) — so the *artifact* carries the taxonomy,
and ``bench.py`` can put ``"error_kind": "compile_hang"`` in its error
line instead of a guess.  The monitor never kills anything: enforcement
(process timeouts) stays with the caller; classification lives here.

Disabled by default — ``--watchdog-timeout`` / ``$MUSICAAL_WATCHDOG_S``
turn it on (0 = off).  When no watchdog is active the module-level
:func:`watch` / :func:`beat` fast-path to no-ops, so instrumentation is
unconditional in the engines (the telemetry pattern).
"""

from __future__ import annotations

import math
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from music_analyst_tpu.telemetry import get_telemetry

# kind -> taxonomy code.  Unknown kinds classify as "unknown_stall" so a
# typo'd kind still produces a structured (if unhelpful) code, never a
# crash in the monitor thread.
TAXONOMY: Dict[str, str] = {
    "stage": "stage_stall",
    "compile": "compile_hang",
    "probe": "tunnel_dead",
    "device": "device_stall",
    "host": "host_stall",
    "serve": "serve_stall",
    "decode": "decode_stall",
    "router": "router_stall",
}


def resolve_watchdog_timeout(
    value: Any = None, default: float = 0.0
) -> float:
    """Resolve ``--watchdog-timeout``: explicit flag wins, then
    ``$MUSICAAL_WATCHDOG_S``, then ``default``.  0 disables.

    A malformed *explicit* value raises (usage error); a malformed env
    var falls back to the default — the watchdog is a diagnostic aid and
    must never be the thing that crashes a run before it starts
    (the ``bench.py`` ``_env_deadline`` rule).
    """
    if value is None:
        raw = os.environ.get("MUSICAAL_WATCHDOG_S", "").strip()
        if not raw:
            return float(default)
        try:
            parsed = float(raw)
        except ValueError:
            return float(default)
        if not math.isfinite(parsed) or parsed < 0:
            return float(default)
        return parsed  # an explicit env 0 DISABLES even over a default
    try:
        timeout = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"watchdog timeout must be a number of seconds >= 0, got {value!r}"
        ) from None
    if not math.isfinite(timeout) or timeout < 0:
        raise ValueError(
            f"watchdog timeout must be finite and >= 0, got {timeout}"
        )
    return timeout


class _Task:
    """One active watched scope."""

    __slots__ = ("name", "kind", "timeout_s", "last_beat", "started",
                 "thread", "tripped")

    def __init__(self, name: str, kind: str, timeout_s: float) -> None:
        self.name = name
        self.kind = kind
        self.timeout_s = timeout_s
        self.last_beat = time.monotonic()
        self.started = self.last_beat
        self.thread = threading.current_thread().name
        self.tripped = False


class HeartbeatWatchdog:
    """Monitor thread classifying stale heartbeats into the taxonomy.

    Tasks are keyed by name: re-entering a name (a looped engine) simply
    refreshes the entry.  A trip fires once per silence — a later beat
    rearms the task, so a slow-but-alive scope trips again only if it
    goes silent again.
    """

    def __init__(
        self,
        timeout_s: float,
        poll_s: Optional[float] = None,
        dump_flight_record: bool = True,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.poll_s = poll_s or max(0.05, min(1.0, self.timeout_s / 4.0))
        self.dump_flight_record = dump_flight_record
        self._tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.trips: List[Dict[str, Any]] = []

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "HeartbeatWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        self._thread = None

    # ------------------------------------------------------------- scoping

    @contextmanager
    def watch(
        self, name: str, kind: str = "stage",
        timeout_s: Optional[float] = None,
    ) -> Iterator[_Task]:
        """Mark ``name`` active for the duration; stale ⇒ trip."""
        task = _Task(name, kind, timeout_s or self.timeout_s)
        with self._lock:
            self._tasks[name] = task
        try:
            yield task
        finally:
            with self._lock:
                if self._tasks.get(name) is task:
                    del self._tasks[name]

    def beat(self, name: str) -> None:
        """Refresh + rearm a named task's heartbeat."""
        with self._lock:
            task = self._tasks.get(name)
            if task is not None:
                task.last_beat = time.monotonic()
                task.tripped = False

    # ------------------------------------------------------------- monitor

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            with self._lock:
                stale = [
                    t for t in self._tasks.values()
                    if not t.tripped and now - t.last_beat > t.timeout_s
                ]
                for t in stale:
                    t.tripped = True
            for task in stale:
                try:
                    self._trip(task, now)
                except Exception:
                    pass  # the monitor must outlive any reporting failure

    def _trip(self, task: _Task, now: float) -> None:
        taxonomy = TAXONOMY.get(task.kind, "unknown_stall")
        trip = {
            "task": task.name,
            "kind": task.kind,
            "taxonomy": taxonomy,
            "stalled_s": round(now - task.last_beat, 3),
            "timeout_s": task.timeout_s,
            "thread": task.thread,
            "t_wall": round(time.time(), 6),
        }
        self.trips.append(trip)
        get_telemetry().event("watchdog_trip", **trip)
        if self.dump_flight_record:
            from music_analyst_tpu.observability.flight import (
                get_flight_recorder,
            )

            get_flight_recorder().dump(
                reason="watchdog",
                taxonomy=taxonomy,
                detail=(
                    f"{task.name} (kind={task.kind}, thread={task.thread}) "
                    f"silent for {trip['stalled_s']}s "
                    f"(timeout {task.timeout_s}s)"
                ),
            )

    # ------------------------------------------------------------ readouts

    def last_trip(self) -> Optional[Dict[str, Any]]:
        return self.trips[-1] if self.trips else None

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able state for the run manifest / flight record."""
        now = time.monotonic()
        with self._lock:
            active = [
                {
                    "task": t.name,
                    "kind": t.kind,
                    "thread": t.thread,
                    "since_beat_s": round(now - t.last_beat, 3),
                    "tripped": t.tripped,
                }
                for t in self._tasks.values()
            ]
        return {
            "timeout_s": self.timeout_s,
            "active": active,
            "trips": list(self.trips),
        }


# ------------------------------------------------------- process singleton

_ACTIVE: Optional[HeartbeatWatchdog] = None


def start_watchdog(timeout_s: Any = None) -> Optional[HeartbeatWatchdog]:
    """Start (or replace) the process watchdog.  ``timeout_s`` resolves
    via :func:`resolve_watchdog_timeout`; <= 0 leaves it disabled and
    returns None."""
    global _ACTIVE
    timeout = resolve_watchdog_timeout(timeout_s)
    if timeout <= 0:
        return None
    if _ACTIVE is not None:
        _ACTIVE.stop()
    _ACTIVE = HeartbeatWatchdog(timeout).start()
    return _ACTIVE


def stop_watchdog() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.stop()
        _ACTIVE = None


def get_watchdog() -> Optional[HeartbeatWatchdog]:
    return _ACTIVE


@contextmanager
def watch(
    name: str, kind: str = "stage", timeout_s: Optional[float] = None
) -> Iterator[Optional[_Task]]:
    """Module-level scope: no-op (None) when no watchdog is active, so
    engines instrument unconditionally — the telemetry enabled-flag
    pattern."""
    wd = _ACTIVE
    if wd is None:
        yield None
        return
    with wd.watch(name, kind=kind, timeout_s=timeout_s) as task:
        yield task


def beat(name: str) -> None:
    wd = _ACTIVE
    if wd is not None:
        wd.beat(name)
