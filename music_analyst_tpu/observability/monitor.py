"""Live fleet monitor: the ``monitor`` CLI subcommand.

Attaches to a live serving front end (single server or replica router)
over its unix socket, polls the ``stats`` op, and renders a refreshing
per-replica table — req/s, tokens/s, batch occupancy, queue depth,
p50/p99 latency, health — plus the metrics plane's active burn-rate
alerts.  One NDJSON request per refresh; the server answers ``stats``
from its control path, so monitoring never competes with inference for
batch slots.

``--once`` renders a single snapshot and exits 0 on a healthy reply —
the scriptable liveness probe the smoke target uses.  Exit codes follow
the house 0/1/2 gate semantics: 0 = healthy reply, 1 = the server
answered but reported itself draining/unhealthy, 2 = no usable reply
(dead socket, bad payload).

Jax-free by design — a monitor must attach while the device is busy or
the tunnel is dead.
"""

from __future__ import annotations

import json
import socket
import sys
import time
from typing import Any, Dict, List, Optional

_CLEAR = "\x1b[2J\x1b[H"  # ANSI clear + home (the refresh between polls)


def _num(value: Any, digits: int = 2) -> str:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return "-"
    return f"{value:.{digits}f}"


def _ms(value: Any) -> str:
    """Seconds → ms column (latency quantiles are stored in seconds)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return "-"
    return f"{value * 1000.0:.1f}"


def _dig(payload: Any, *path: str) -> Any:
    for key in path:
        if not isinstance(payload, dict):
            return None
        payload = payload.get(key)
    return payload


def extract_row(name: str, stats: Optional[Dict[str, Any]],
                health: str = "healthy") -> Dict[str, Any]:
    """One table row from one process's stats snapshot (the ``stats``
    op payload, or a replica's ``last_stats``)."""
    stats = stats if isinstance(stats, dict) else {}
    row: Dict[str, Any] = {
        "name": name,
        "health": health,
        "req_s": _dig(stats, "requests", "rates", "req_s"),
        "shed_s": _dig(stats, "requests", "rates", "shed_s"),
        "tokens_s": _dig(stats, "decode", "rates", "tokens_s"),
        "occupancy": _dig(stats, "requests", "occupancy"),
        "queue_depth": (
            _dig(stats, "requests", "queue_depth")
            if _dig(stats, "requests", "queue_depth") is not None
            else _dig(stats, "requests", "queue_depth_max")
        ),
        "p50_s": _dig(stats, "requests", "latency", "p50_s"),
        "p99_s": _dig(stats, "requests", "latency", "p99_s"),
    }
    return row


def _bar(frac: Any, width: int = 8) -> str:
    """A fixed-width occupancy bar: ``[####----]``."""
    if isinstance(frac, bool) or not isinstance(frac, (int, float)):
        return "[" + "?" * width + "]"
    filled = int(round(min(1.0, max(0.0, frac)) * width))
    return "[" + "#" * filled + "-" * (width - filled) + "]"


def extract_engine_row(name: str,
                       stats: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """One engine-panel row from a stats snapshot's ``decode`` section
    (engine ledger + speculation EWMA); None when the process serves no
    continuous scheduler."""
    decode = stats.get("decode") if isinstance(stats, dict) else None
    if not isinstance(decode, dict):
        return None
    ledger = decode.get("ledger") or {}
    occ = ledger.get("occupancy") or {}
    fractions = ledger.get("fractions") or {}
    slots_total = occ.get("slots_total", decode.get("n_slots"))
    slots_active = occ.get("slots_active", decode.get("active_slots"))
    occupancy = None
    if (isinstance(slots_total, int) and slots_total > 0
            and isinstance(slots_active, int)):
        occupancy = slots_active / slots_total
    return {
        "name": name,
        "slots_active": slots_active,
        "slots_total": slots_total,
        "occupancy": occupancy,
        "goodput": ledger.get("goodput_fraction"),
        "prefill": fractions.get("prefill"),
        "idle_bubble": fractions.get("idle_bubble"),
        "pages_free": occ.get("pages_free"),
        "pages_pinned": occ.get("pages_pinned"),
        "spec_accept": _dig(decode, "speculation", "acceptance_rate"),
    }


def build_view(payload: Dict[str, Any]) -> Dict[str, Any]:
    """The reply payload of one ``stats`` op → rows + alerts + header."""
    stats = payload.get("stats") or {}
    rows: List[Dict[str, Any]] = []
    engine: List[Dict[str, Any]] = []
    router = stats.get("router")
    if isinstance(router, dict) and router.get("replicas"):
        for name, snap in sorted(router["replicas"].items()):
            rows.append(extract_row(
                name, (snap or {}).get("last_stats"),
                health=(snap or {}).get("health") or "?",
            ))
            engine_row = extract_engine_row(
                name, (snap or {}).get("last_stats")
            )
            if engine_row is not None:
                engine.append(engine_row)
        # The front end's own admission edge rides along as the fleet
        # row: its rates already aggregate what it dispatched.
        fleet = extract_row("fleet", stats)
        fleet["health"] = (
            f"{router.get('healthy_count')}/{router.get('replica_count')} "
            f"healthy"
        )
        rows.append(fleet)
    else:
        rows.append(extract_row("local", stats))
        engine_row = extract_engine_row("local", stats)
        if engine_row is not None:
            engine.append(engine_row)
    metrics = stats.get("metrics") or {}
    alerts = list(metrics.get("active_alerts") or [])
    idle_fracs = [
        r["idle_bubble"] for r in engine
        if isinstance(r.get("idle_bubble"), (int, float))
    ]
    return {
        "mode": stats.get("mode"),
        "uptime_s": stats.get("uptime_s"),
        "draining": bool(stats.get("draining")),
        "rows": rows,
        "engine": engine,
        "idle_bubble_max": max(idle_fracs) if idle_fracs else None,
        "alerts": alerts,
        "metrics": {
            k: metrics.get(k)
            for k in ("samples", "scrape_errors", "alerts_fired",
                      "alerts_resolved", "stale", "interval_ms")
            if k in metrics
        },
    }


def render_view(view: Dict[str, Any]) -> List[str]:
    lines = [
        f"monitor: mode={view['mode']} uptime={_num(view['uptime_s'], 1)}s"
        + (" DRAINING" if view["draining"] else "")
    ]
    header = (
        f"{'replica':<12} {'health':<14} {'req/s':>8} {'tok/s':>8} "
        f"{'occ':>6} {'queue':>6} {'p50ms':>8} {'p99ms':>8}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for row in view["rows"]:
        lines.append(
            f"{str(row['name'])[:12]:<12} {str(row['health'])[:14]:<14} "
            f"{_num(row['req_s']):>8} {_num(row['tokens_s']):>8} "
            f"{_num(row['occupancy']):>6} "
            f"{row['queue_depth'] if row['queue_depth'] is not None else '-':>6} "
            f"{_ms(row['p50_s']):>8} {_ms(row['p99_s']):>8}"
        )
    engine = view.get("engine") or []
    if engine:
        lines.append("engine panel (goodput ledger):")
        for row in engine:
            slots = (
                f"{row['slots_active']}/{row['slots_total']}"
                if row.get("slots_total") is not None else "-"
            )
            pool = (
                f" pool free={row['pages_free']} pinned={row['pages_pinned']}"
                if row.get("pages_free") is not None else ""
            )
            spec = (
                f" spec={_num(row['spec_accept'])}"
                if row.get("spec_accept") is not None else ""
            )
            lines.append(
                f"  {str(row['name'])[:12]:<12} occ {_bar(row['occupancy'])} "
                f"{slots:>5}  goodput={_num(row['goodput'])} "
                f"prefill={_num(row['prefill'])} "
                f"idle={_num(row['idle_bubble'])}{pool}{spec}"
            )
    metrics = view.get("metrics") or {}
    if metrics:
        shown = " ".join(f"{k}={v}" for k, v in metrics.items())
        lines.append(f"metrics plane: {shown}")
    if view["alerts"]:
        lines.append("ACTIVE ALERTS:")
        for alert in view["alerts"]:
            tenant = f" tenant={alert.get('tenant')}" \
                if alert.get("tenant") else ""
            trace = f" trace={alert.get('trace_id')}" \
                if alert.get("trace_id") else ""
            lines.append(
                f"  {alert.get('alert')}{tenant}: "
                f"burn {alert.get('burn_fast')}x/{alert.get('burn_slow')}x "
                f"(threshold {alert.get('threshold')}x){trace}"
            )
    else:
        lines.append("no active alerts")
    return lines


class _StatsClient:
    """One persistent NDJSON connection; a fresh wire id per poll."""

    def __init__(self, socket_path: str, timeout_s: float = 5.0) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout_s)
        self._sock.connect(socket_path)
        self._rfile = self._sock.makefile("r", encoding="utf-8")
        self._seq = 0

    def poll(self) -> Optional[Dict[str, Any]]:
        self._seq += 1
        wire_id = f"monitor-{self._seq}"
        line = json.dumps({"id": wire_id, "op": "stats"}) + "\n"
        self._sock.sendall(line.encode("utf-8"))
        for raw in self._rfile:
            raw = raw.strip()
            if not raw:
                continue
            try:
                payload = json.loads(raw)
            except ValueError:
                continue
            if isinstance(payload, dict) and payload.get("id") == wire_id:
                return payload
        return None

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def run_monitor(socket_path: str, once: bool = False,
                interval_s: float = 2.0,
                json_output: bool = False,
                idle_bubble_gate: Optional[float] = None) -> int:
    """CLI entry.  0 = healthy reply, 1 = server answered but draining
    (or, with ``--idle-bubble-gate``, reported an engine idle_bubble
    fraction above the threshold), 2 = no usable reply."""
    try:
        client = _StatsClient(socket_path)
    except OSError as exc:
        print(f"monitor: cannot connect to {socket_path}: {exc}",
              file=sys.stderr)
        return 2
    try:
        while True:
            try:
                payload = client.poll()
            except OSError as exc:
                print(f"monitor: poll failed: {exc}", file=sys.stderr)
                return 2
            if payload is None or not payload.get("ok"):
                print("monitor: no usable stats reply", file=sys.stderr)
                return 2
            view = build_view(payload)
            if json_output:
                print(json.dumps(view, default=str))
            else:
                if not once:
                    sys.stdout.write(_CLEAR)
                for line in render_view(view):
                    print(line)
                sys.stdout.flush()
            if once:
                idle_max = view.get("idle_bubble_max")
                gate_tripped = (
                    idle_bubble_gate is not None
                    and isinstance(idle_max, (int, float))
                    and idle_max > idle_bubble_gate
                )
                if gate_tripped:
                    print(
                        f"monitor: idle_bubble {idle_max} exceeds gate "
                        f"{idle_bubble_gate}", file=sys.stderr,
                    )
                return 1 if (view["draining"] or gate_tripped) else 0
            time.sleep(max(interval_s, 0.1))
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()
