"""Flight recorder: a bounded ring of recent telemetry + crash dumps.

``BENCH_r05.json`` is the motivating failure: the bench died on a device
probe timeout and left nothing behind — no thread stacks, no event
timeline, no way to tell a tunnel hang from a compile hang after the
process was gone.  The recorder fixes that class of blindness: it taps
the process telemetry registry (``telemetry/core.py``) into a bounded
in-memory ring (so a crashing run always has its last ~512 events even
when no JSONL sink was open), and dumps ``flight_record.json`` — ring +
process vitals + ``faulthandler`` stacks of every thread — on:

* an unhandled exception (``sys.excepthook`` chain),
* SIGTERM / SIGINT (handler chain; the previous disposition still runs,
  so a SIGTERM'd process still dies — it just leaves a post-mortem),
* a watchdog trip (``observability/watchdog.py`` calls :meth:`dump`),
* bench-deadline expiry (``bench.py`` dumps before its terminal line).

Zero hard deps on jax — installable before ``tests/conftest.py`` forces
the CPU platform, and cheap enough for ``bench.py --probe``.

Dump location: explicit ``directory`` > ``$MUSICAAL_FLIGHT_RECORD_DIR`` >
the open telemetry sink's directory > the system temp dir.  The file name
is always ``flight_record.json`` (overwritten — the *latest* failure is
the one being diagnosed); readers that care about staleness check mtime
(``bench.py`` does).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from music_analyst_tpu.telemetry import get_telemetry

DEFAULT_CAPACITY = 512

_START_MONO = time.monotonic()


def _thread_stacks() -> str:
    """Every thread's stack as text, via faulthandler (needs a real fd)."""
    import faulthandler

    try:
        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            return fh.read()
    except Exception:
        pass
    # No usable fd (exotic embedding): pure-Python fallback.
    try:
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for tid, frame in frames.items():
            parts.append(
                f"Thread {names.get(tid, tid)}:\n"
                + "".join(traceback.format_stack(frame))
            )
        return "\n".join(parts)
    except Exception:
        return "<thread stacks unavailable>"


def _vitals() -> Dict[str, Any]:
    """Cheap process health snapshot taken at dump time."""
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "thread_count": threading.active_count(),
        "thread_names": sorted(t.name for t in threading.enumerate())[:64],
        "python_version": sys.version.split()[0],
    }
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        out["peak_rss_bytes"] = ru.ru_maxrss * 1024  # Linux: KiB
        out["cpu_user_s"] = round(ru.ru_utime, 3)
        out["cpu_system_s"] = round(ru.ru_stime, 3)
    except Exception:  # pragma: no cover - non-POSIX
        pass
    return out


class FlightRecorder:
    """Bounded event ring + post-mortem dumper.  One per process."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._installed = False
        self._prev_excepthook = None
        self._prev_handlers: Dict[int, Any] = {}
        self.last_dump_path: Optional[str] = None
        self.dump_count = 0

    # ----------------------------------------------------------- recording

    def record(self, event: Dict[str, Any]) -> None:
        """Telemetry tap target: keep the most recent events, drop the
        oldest.  Events are append-only dicts; no copy needed."""
        with self._lock:
            self._ring.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # -------------------------------------------------------- installation

    def install(self, signals: bool = True, excepthook: bool = True
                ) -> "FlightRecorder":
        """Tap telemetry + hook crash paths.  Idempotent.

        Signal handlers chain to the previous disposition (a SIGTERM'd
        process still terminates; Ctrl-C still raises KeyboardInterrupt)
        and can only be installed from the main thread — elsewhere the
        tap + excepthook still install and signals are skipped.
        """
        if self._installed:
            return self
        self._installed = True
        get_telemetry().add_tap(self.record)
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if signals:
            for signum in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._prev_handlers[signum] = signal.signal(
                        signum, self._signal_handler
                    )
                except (ValueError, OSError):  # non-main thread / exotic os
                    pass
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        get_telemetry().remove_tap(self.record)
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    @property
    def installed(self) -> bool:
        return self._installed

    # --------------------------------------------------------- crash hooks

    def _excepthook(self, exc_type, exc, tb) -> None:
        taxonomy = None
        if isinstance(exc, MemoryError):
            taxonomy = "host_oom"
        self.dump(
            reason="unhandled_exception",
            taxonomy=taxonomy,
            detail=f"{exc_type.__name__}: {exc}"[:500],
        )
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _signal_handler(self, signum, frame) -> None:
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover
            name = str(signum)
        self.dump(reason=f"signal:{name}", detail=f"received {name}")
        prev = self._prev_handlers.get(signum, signal.SIG_DFL)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # Re-deliver under the default disposition so the process
            # status the parent sees (killed-by-SIGTERM) is unchanged.
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        # SIG_IGN: swallow, like the previous handler would have.

    # --------------------------------------------------------------- dumps

    def _resolve_dir(self, directory: Optional[str]) -> str:
        if directory:
            return directory
        env = os.environ.get("MUSICAAL_FLIGHT_RECORD_DIR", "").strip()
        if env:
            return env
        sink = get_telemetry().sink_path
        if sink:
            return os.path.dirname(sink)
        return tempfile.gettempdir()

    def dump(
        self,
        reason: str,
        taxonomy: Optional[str] = None,
        detail: str = "",
        directory: Optional[str] = None,
    ) -> Optional[str]:
        """Write ``flight_record.json``; never raises (returns None).

        Called from signal handlers, excepthooks, and the watchdog monitor
        thread — any failure here must not mask the original problem.
        """
        with self._dump_lock:
            try:
                tel = get_telemetry()
                with tel._lock:
                    counters = dict(tel.counters)
                    gauges = dict(tel.gauges)
                record: Dict[str, Any] = {
                    "schema": 1,
                    "reason": reason,
                    "taxonomy": taxonomy,
                    "detail": detail,
                    "t_wall": round(time.time(), 6),
                    "t_mono": round(time.monotonic(), 6),
                    "argv": list(sys.argv),
                    "vitals": _vitals(),
                    "counters": counters,
                    "gauges": gauges,
                    "events": self.events(),
                    "thread_stacks": _thread_stacks(),
                }
                try:
                    from music_analyst_tpu.observability.watchdog import (
                        get_watchdog,
                    )

                    wd = get_watchdog()
                    if wd is not None:
                        record["watchdog"] = wd.snapshot()
                except Exception:
                    pass
                out_dir = self._resolve_dir(directory)
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(out_dir, "flight_record.json")
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(record, fh, indent=2, default=str)
                    fh.write("\n")
                os.replace(tmp, path)
                self.last_dump_path = path
                self.dump_count += 1
            except Exception:
                return None
        # Outside the dump lock: the emit feeds the ring via the tap, and
        # a same-thread re-dump must not deadlock.
        try:
            get_telemetry().event(
                "flight_record_dumped",
                path=path, reason=reason, taxonomy=taxonomy,
            )
        except Exception:
            pass
        return path


# ------------------------------------------------------- process singleton

_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _RECORDER


def install_flight_recorder(
    signals: bool = True, excepthook: bool = True
) -> FlightRecorder:
    """Install (idempotently) and return the process flight recorder."""
    return _RECORDER.install(signals=signals, excepthook=excepthook)
