"""Crash forensics + stall classification + cross-run analytics.

Three pieces (see each module's docstring):

* :mod:`flight` — bounded telemetry ring dumped as ``flight_record.json``
  (thread stacks + vitals) on crash/signal/watchdog/deadline,
* :mod:`watchdog` — heartbeat monitor classifying hangs into the
  structured taxonomy (``tunnel_dead``, ``compile_hang``, ``stage_stall``,
  ``host_oom``, …),
* :mod:`report` — ``telemetry-report`` run-over-run aggregation.

Jax-free at import: safe before ``tests/conftest.py`` pins the platform
and inside ``bench.py --probe``.
"""

from music_analyst_tpu.observability.flight import (
    FlightRecorder,
    get_flight_recorder,
    install_flight_recorder,
)
from music_analyst_tpu.observability.report import (
    build_report,
    classify_error,
    load_run,
    render_report,
    run_telemetry_report,
)
from music_analyst_tpu.observability.watchdog import (
    TAXONOMY,
    HeartbeatWatchdog,
    beat,
    get_watchdog,
    resolve_watchdog_timeout,
    start_watchdog,
    stop_watchdog,
    watch,
)

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "install_flight_recorder",
    "build_report",
    "classify_error",
    "load_run",
    "render_report",
    "run_telemetry_report",
    "TAXONOMY",
    "HeartbeatWatchdog",
    "beat",
    "get_watchdog",
    "resolve_watchdog_timeout",
    "start_watchdog",
    "stop_watchdog",
    "watch",
]
