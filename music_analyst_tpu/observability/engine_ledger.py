"""Engine goodput ledger: per-tick decode timeline + occupancy accounting.

PR 16 answers *why one request* was slow and PR 17 says *the fleet* is
burning its SLO budget — this module answers "what is the *chip* doing?"
Every continuous-scheduler tick's wall time is classified into an
exhaustive attribution set that tiles to ~100% of engine wall:

* ``decode_useful``   — committed-token verify/decode dispatch time;
* ``prefill``         — prompt-chunk dispatch time (chunk counters split
  shared-hit vs cold alongside);
* ``spec_waste``      — drafted-but-rejected verify work (the slice of a
  verify dispatch whose rows produced no committed token);
* ``preempt_overhead``— checkpoint/restore/steal bookkeeping;
* ``host_gap``        — scheduler/readback host time between dispatches
  (the residual of an occupied tick);
* ``idle_bubble``     — ticks and loop waits with every slot empty.

The ledger keeps a running cursor so inter-tick gaps are attributed too
(to ``host_gap`` when the engine is occupied, ``idle_bubble`` when not):
bucket seconds sum to the engine wall span by construction.  Per-tenant
chip-seconds accumulate the same way — each accounted second lands on
the tenants occupying slots at that instant (slot-share split), or on
the reserved ``(idle)`` tenant — so tenant chip-seconds also sum to
engine wall, the cost-attribution number the SLO ledgers were missing.

Recording is always on: the hot path is a handful of float adds under
one lock, no device work, no readbacks, no per-tick allocation (a reused
scratch dict for tenant shares).  The ledger measures its *own* cost
(``overhead_fraction``) so the ≤1% claim is a reported number, not a
promise.  Flushing rides the PR-17 metrics cadence: every
``$MUSICAAL_LEDGER_INTERVAL_MS`` (default: the metrics interval) one
cumulative snapshot lands as a crash-safe O_APPEND line in
``<profile-dir>/engine_ledger.jsonl`` — single-``write`` discipline,
never torn; a flush failure (fault site ``ledger.flush``) degrades to a
counted ``ledger_drops``, never a failed reply.

Host-side only, no jax imports — importable before the test harness
pins ``JAX_PLATFORMS``.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

LEDGER_FILE = "engine_ledger.jsonl"
IDLE_TENANT = "(idle)"

_ENV_INTERVAL = "MUSICAAL_LEDGER_INTERVAL_MS"
_ENV_DIR = "MUSICAAL_LEDGER_DIR"

# The exhaustive attribution set — every accounted second lands in
# exactly one class (PERFORMANCE.md "Reading the engine ledger").
CLASSES = (
    "decode_useful",
    "prefill",
    "spec_waste",
    "preempt_overhead",
    "host_gap",
    "idle_bubble",
)


def resolve_ledger_interval_ms(value: Optional[Any] = None) -> float:
    """Flush cadence in ms: explicit flag > $MUSICAAL_LEDGER_INTERVAL_MS
    > the PR-17 metrics cadence ($MUSICAAL_METRICS_INTERVAL_MS) > 0 (no
    file flush; the in-memory ledger still records).  A malformed
    explicit flag raises; a malformed env var falls back, like every
    other serving ``resolve_*`` knob."""
    if value is not None:
        try:
            interval = float(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"--ledger-interval-ms expects a number >= 0, got {value!r}"
            ) from None
        if not math.isfinite(interval) or interval < 0.0:
            raise ValueError(
                f"--ledger-interval-ms expects a number >= 0, got {value!r}"
            )
        return interval
    raw = os.environ.get(_ENV_INTERVAL, "").strip()
    if raw:
        try:
            interval = float(raw)
        except ValueError:
            interval = None
        if interval is not None and math.isfinite(interval) and interval >= 0.0:
            return interval
    from music_analyst_tpu.observability.metrics_plane import (
        resolve_metrics_interval_ms,
    )

    return resolve_metrics_interval_ms(None)


def resolve_ledger_dir(value: Optional[str] = None) -> Optional[str]:
    """Ledger output directory: explicit (``--profile-dir``) >
    $MUSICAAL_LEDGER_DIR > the metrics/trace profile dir > None (no
    file; the ledger still surfaces through ``stats``)."""
    if value:
        return value
    explicit = os.environ.get(_ENV_DIR)
    if explicit:
        return explicit
    from music_analyst_tpu.observability.metrics_plane import resolve_metrics_dir

    return resolve_metrics_dir(None)


class EngineLedger:
    """Per-tick goodput recorder for one continuous scheduler."""

    def __init__(
        self,
        n_slots: int,
        interval_ms: Optional[Any] = None,
        directory: Optional[str] = None,
        role: str = "server",
    ) -> None:
        self.n_slots = max(1, int(n_slots))
        self.interval_ms = resolve_ledger_interval_ms(interval_ms)
        self.directory = resolve_ledger_dir(directory)
        self.path = (
            os.path.join(self.directory, LEDGER_FILE)
            if self.directory and self.interval_ms > 0.0 else None
        )
        self.role = role
        self._lock = threading.Lock()
        # Attribution accumulators (seconds per class).
        self._s: Dict[str, float] = {c: 0.0 for c in CLASSES}
        # Engine-wall span cursors (perf_counter domain): every instant
        # between _t_first and _cursor is attributed to exactly one
        # class, so bucket fractions tile to ~100% by construction.
        self._t_first: Optional[float] = None
        self._cursor: Optional[float] = None
        self.ticks = 0
        self.idle_ticks = 0
        self.tokens_committed = 0
        self.prefill_chunks_cold = 0
        self.prefill_chunks_shared = 0
        # Per-tenant chip-seconds (IDLE_TENANT collects empty-engine time).
        self._chip: Dict[str, float] = {}
        self._scratch: Dict[str, int] = {}  # reused per tick — no alloc
        # Self-measured recording cost (overhead_fraction).
        self._overhead_s = 0.0
        self.flushes = 0
        self.ledger_drops = 0
        self._t_last_flush = time.monotonic()
        self._occ_source: Optional[Callable[[], Dict[str, Any]]] = None
        self._pid = os.getpid()

    # ------------------------------------------------------------ wiring

    def attach_occupancy(self, fn: Callable[[], Dict[str, Any]]) -> None:
        """Register the (possibly O(pool)) occupancy sampler; called only
        at flush/stats time, never on the per-tick hot path."""
        self._occ_source = fn

    # ------------------------------------------------------------ hot path

    def record_tick(
        self,
        t_start: float,
        t_end: float,
        prefill_s: float = 0.0,
        chunks_cold: int = 0,
        chunks_shared: int = 0,
        decode_s: float = 0.0,
        useful_frac: float = 1.0,
        committed: int = 0,
        preempt_s: float = 0.0,
        slots: Optional[list] = None,
        shares: Optional[Dict[str, int]] = None,
    ) -> None:
        """Account one scheduler tick.  ``shares`` is the tenant→slot-count
        map captured right after admission (borrowed, not copied) — the
        authoritative attribution, since settle frees slots mid-tick.
        ``slots`` is the fallback: the live slot list, tenants read off
        occupied entries at record time."""
        o0 = time.perf_counter()
        with self._lock:
            if self._t_first is None:
                self._t_first = t_start
                self._cursor = t_start
            gap = max(0.0, t_start - self._cursor)
            wall = max(0.0, t_end - t_start)
            self._cursor = max(self._cursor, t_end)
            self.ticks += 1
            self.tokens_committed += committed
            self.prefill_chunks_cold += chunks_cold
            self.prefill_chunks_shared += chunks_shared
            if shares is None:
                # Tenant slot shares (scratch dict reused across ticks).
                shares = self._scratch
                shares.clear()
                if slots:
                    for s in slots:
                        if s is None:
                            continue
                        tenant = s.req.tenant
                        shares[tenant] = shares.get(tenant, 0) + 1
            n_occ = sum(shares.values())
            worked = (
                n_occ > 0 or decode_s > 0.0 or prefill_s > 0.0
                or preempt_s > 0.0 or committed > 0
                or chunks_cold > 0 or chunks_shared > 0
            )
            total = gap + wall
            if not worked:
                self.idle_ticks += 1
                self._s["idle_bubble"] += total
                self._chip[IDLE_TENANT] = (
                    self._chip.get(IDLE_TENANT, 0.0) + total
                )
            else:
                useful_frac = min(1.0, max(0.0, useful_frac))
                useful = decode_s * useful_frac
                self._s["decode_useful"] += useful
                self._s["spec_waste"] += decode_s - useful
                self._s["prefill"] += prefill_s
                self._s["preempt_overhead"] += preempt_s
                self._s["host_gap"] += gap + max(
                    0.0, wall - prefill_s - decode_s - preempt_s
                )
                chip = self._chip
                if n_occ > 0:
                    for tenant, n in shares.items():
                        chip[tenant] = (
                            chip.get(tenant, 0.0) + total * n / n_occ
                        )
                else:
                    # Work with no captured tenant (caller passed no
                    # shares and slots already settled) — keep the
                    # chip-second tiling exact rather than lose the time.
                    chip[IDLE_TENANT] = chip.get(IDLE_TENANT, 0.0) + total
            self._overhead_s += time.perf_counter() - o0

    def idle_wait(self, t_start: float, t_end: float) -> None:
        """Account one empty-engine wait in the threaded loop.  Counts
        from the cursor, not ``t_start``: the loop only waits after an
        empty tick, so the lock-acquisition gap between that tick's end
        and the wait start is idle engine time too — dropping it leaks
        ~100µs per iteration on a contended host."""
        with self._lock:
            if self._t_first is None:
                self._t_first = t_start
                self._cursor = t_start
            total = max(0.0, t_end - self._cursor)
            self._cursor = max(self._cursor, t_end)
            self._s["idle_bubble"] += total
            self._chip[IDLE_TENANT] = self._chip.get(IDLE_TENANT, 0.0) + total

    # ------------------------------------------------------------ reading

    def chip_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._chip)

    def snapshot(self, occupancy: bool = True) -> Dict[str, Any]:
        """The ``serving.decode.ledger`` block: cumulative counters plus
        derived fractions against the engine-wall span."""
        with self._lock:
            wall = (
                (self._cursor - self._t_first)
                if self._t_first is not None else 0.0
            )
            seconds = {c: round(v, 6) for c, v in self._s.items()}
            covered = sum(self._s.values())
            out: Dict[str, Any] = {
                "ticks": self.ticks,
                "idle_ticks": self.idle_ticks,
                "engine_wall_s": round(wall, 6),
                "seconds": seconds,
                "fractions": {
                    c: round(v / wall, 6) if wall > 0.0 else 0.0
                    for c, v in self._s.items()
                },
                "coverage": round(covered / wall, 6) if wall > 0.0 else 0.0,
                "goodput_fraction": (
                    round(self._s["decode_useful"] / wall, 6)
                    if wall > 0.0 else 0.0
                ),
                "tokens_committed": self.tokens_committed,
                "prefill_chunks": {
                    "cold": self.prefill_chunks_cold,
                    "shared_hit": self.prefill_chunks_shared,
                },
                "chip_seconds": {
                    t: round(v, 6) for t, v in sorted(self._chip.items())
                },
                "overhead_fraction": (
                    round(self._overhead_s / wall, 6) if wall > 0.0 else 0.0
                ),
                "interval_ms": self.interval_ms,
                "path": self.path,
                "flushes": self.flushes,
                "ledger_drops": self.ledger_drops,
            }
        if occupancy and self._occ_source is not None:
            try:
                out["occupancy"] = self._occ_source()
            except Exception:  # noqa: BLE001 — a torn sample never raises
                out["occupancy"] = {}
        else:
            out["occupancy"] = {}
        return out

    # ------------------------------------------------------------ flushing

    def maybe_flush(self, force: bool = False) -> bool:
        """Append one cumulative snapshot line when the cadence is due.
        Cheap when idle (one monotonic read); any failure — injected
        (``ledger.flush``) or real — degrades to a counted drop."""
        if self.path is None:
            return False
        now = time.monotonic()
        if not force and (now - self._t_last_flush) * 1000.0 < self.interval_ms:
            return False
        self._t_last_flush = now
        record = {
            "type": "ledger",
            "t": time.time(),
            "pid": self._pid,
            "role": self.role,
            "ledger": self.snapshot(),
        }
        from music_analyst_tpu.resilience.faults import fault_point

        try:
            fault_point("ledger.flush", path=self.path)
            line = json.dumps(
                record, separators=(",", ":"), default=str
            ) + "\n"
            os.makedirs(self.directory, exist_ok=True)
            fd = os.open(
                self.path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644
            )
            try:
                os.write(fd, line.encode("utf-8"))
            finally:
                os.close(fd)
            with self._lock:
                self.flushes += 1
            return True
        except Exception:  # noqa: BLE001 — degrade, never block the loop
            with self._lock:
                self.ledger_drops += 1
            return False

    def close(self) -> None:
        """Final flush on drain so short runs still land one record."""
        if self.path is not None and self.ticks:
            self.maybe_flush(force=True)
