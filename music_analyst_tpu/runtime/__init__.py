"""Host↔device data-plane runtime.

The reusable substrate every engine dispatches batches through:

* :mod:`prefetch` — the bounded-depth staged pipeline executor
  (tokenize → transfer → compute overlap with backpressure, stall
  accounting, clean cancellation/exception propagation);
* :mod:`wire` — H2D payload narrowing (int16 lengths, packed-uint8
  masks), byte accounting, and ``donate_argnums`` policy for the
  steady-state jitted forwards.

Zero hard deps on jax at import time (``wire`` lazy-imports it inside
the device-facing helpers), matching the telemetry package's rule: this
module must be importable before ``tests/conftest.py`` forces the CPU
platform.
"""

from music_analyst_tpu.runtime.prefetch import (  # noqa: F401
    DEFAULT_PREFETCH_DEPTH,
    PrefetchPipeline,
    Stage,
    resolve_prefetch_depth,
)
from music_analyst_tpu.runtime.wire import (  # noqa: F401
    count_h2d_bytes,
    forward_donation_kwargs,
    narrow_lengths,
    pack_mask,
    unpack_mask,
)

__all__ = [
    "DEFAULT_PREFETCH_DEPTH",
    "PrefetchPipeline",
    "Stage",
    "resolve_prefetch_depth",
    "count_h2d_bytes",
    "forward_donation_kwargs",
    "narrow_lengths",
    "pack_mask",
    "unpack_mask",
]
