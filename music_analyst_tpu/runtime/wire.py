"""H2D wire-format policy: narrow payloads, count bytes, donate buffers.

Host→device transfers ride a ~9.4 MB/s loopback tunnel in this
environment (PERFORMANCE.md roofline), so bytes on the wire are the
scarce resource.  The policy, mirroring ``_wire_dtype`` in
``models/distilbert.py``:

* **token ids** — int16 when the vocab fits 2¹⁵ (BERT's 30522 does,
  llama's 128256 does not);
* **lengths / segment starts / row lengths / bucket indices** — int16
  whenever the max representable position fits 2¹⁵
  (:func:`narrow_lengths`), widened back to int32 on device inside the
  jitted program;
* **boolean masks** — 8 mask bits per byte (:func:`pack_mask` /
  :func:`unpack_mask`).  The audit of current H2D payloads found **no**
  host-shipped mask arrays — every engine derives masks on device from
  lengths/segment ids, which is strictly cheaper — so these helpers
  exist for future payloads (and are contract-tested), not retrofits.

Every transfer site reports ``pipeline.h2d_bytes`` (what actually
shipped) and ``pipeline.h2d_bytes_saved`` (vs. the int32/bool baseline)
via :func:`count_h2d_bytes`, so the savings are a measured number in the
run manifest, not a comment.

:func:`forward_donation_kwargs` centralizes the ``donate_argnums``
policy for steady-state jitted forwards: on real accelerators donating
the input batch lets XLA reuse its H2D staging buffer for temporaries
instead of holding it live across the step; the CPU-emulated test mesh
gets no donation for pure data args (no matching output buffer to alias
— XLA would just warn "donated buffers were not usable" on every
compile).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from music_analyst_tpu.telemetry import get_telemetry

_INT16_MAX = 1 << 15


def narrow_lengths(values: np.ndarray, max_value: int) -> np.ndarray:
    """Cast an integer payload to int16 when every representable value
    (``0..max_value``) fits, else int32.  Lossless by construction —
    callers widen with ``.astype(jnp.int32)`` on device."""
    dtype = np.int16 if max_value < _INT16_MAX else np.int32
    return np.asarray(values, dtype=dtype)


def pack_mask(mask: np.ndarray) -> np.ndarray:
    """Pack a boolean mask's last axis to 8 bits per byte (uint8).

    ``[..., S]`` bool → ``[..., ceil(S/8)]`` uint8, big-endian within the
    byte (numpy's ``packbits`` default, matched by :func:`unpack_mask`).
    """
    mask = np.asarray(mask, dtype=bool)
    return np.packbits(mask, axis=-1)


def unpack_mask(packed, length: int):
    """Device-side inverse of :func:`pack_mask` (jnp has no unpackbits).

    ``[..., nbytes]`` uint8 → ``[..., length]`` bool, traceable inside a
    jitted program so the widened mask never crosses the wire.
    """
    import jax.numpy as jnp

    packed = jnp.asarray(packed, dtype=jnp.uint8)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)  # bit 7 first
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)   # [..., nbytes, 8]
    flat = bits.reshape(*packed.shape[:-1], packed.shape[-1] * 8)
    return flat[..., :length].astype(bool)


def count_h2d_bytes(
    arrays: Sequence[Any],
    baseline_bytes: Optional[int] = None,
    prefix: str = "pipeline",
) -> int:
    """Count one transfer's payload bytes into the run's telemetry.

    ``<prefix>.h2d_bytes`` accumulates what actually shipped;
    ``<prefix>.h2d_bytes_saved`` accumulates the reduction against
    ``baseline_bytes`` — by default the 4-bytes-per-element wire every
    payload used before narrowing.  Returns the shipped byte count.
    """
    shipped = sum(int(a.nbytes) for a in arrays)
    if baseline_bytes is None:
        baseline_bytes = sum(int(a.size) * 4 for a in arrays)
    tel = get_telemetry()
    tel.count(f"{prefix}.h2d_bytes", shipped)
    saved = int(baseline_bytes) - shipped
    if saved > 0:
        tel.count(f"{prefix}.h2d_bytes_saved", saved)
    return shipped


def forward_donation_kwargs(*argnums: int) -> Dict[str, Any]:
    """``jit`` kwargs donating the given input-batch argnums — on real
    accelerators only.

    Donating the steady-state forward's data args frees each batch's H2D
    staging buffer at program start (the runtime may reuse the space for
    temporaries) instead of pinning it for the whole step.  On the CPU
    test backend a data arg has no same-shape output to alias, so XLA
    ignores the donation and warns on every compile — skip it there.
    Train-step *state* donation is different (state-in aliases state-out
    exactly) and stays unconditional in ``engines/train.py``.
    """
    import jax

    if jax.default_backend() == "cpu":
        return {}
    return {"donate_argnums": argnums}
