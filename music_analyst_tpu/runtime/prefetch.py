"""Bounded-depth staged pipeline executor (host↔device overlap).

Every engine used to hand-roll its own overlap: the sentiment engine kept
one batch in flight, the per-song counter managed a deque of pool
futures, bench.py had a third copy, and everything else ran ingest →
tokenize → transfer → compute strictly serially.  This module is the one
shared executor: a source iterator feeds a chain of stages, each stage
runs in its own thread (or worker pool) connected by bounded queues, and
the consumer iterates results **in submission order** while up to
``depth`` items per hop are in flight ahead of it.

Why bounded: the host tokenizer sustains ~15× the device throughput
(PERFORMANCE.md), so an unbounded queue would happily buffer the whole
corpus in RAM.  ``depth`` is the backpressure knob — each queue holds at
most ``depth`` items, so a fast producer blocks instead of ballooning,
and device memory holds at most ``depth + 1`` staged batches.

Failure contract (tests/test_runtime_pipeline.py):

* an exception in any stage (or in the source) is forwarded down the
  chain as a poison pill and re-raised in the consumer **promptly** — a
  failing stage can never deadlock the run, because every blocking queue
  operation is a cancellable poll loop;
* closing the consumer generator early cancels the pipeline, drains the
  queues, and joins every thread before returning.

Accounting: each stage tracks items, work seconds, **stall** seconds
(waiting for input — the upstream stage is the bottleneck), backpressure
seconds (waiting for output space — the downstream is), and the max
depth its input queue reached.  On completion the pipeline publishes
``<name>.<stage>_stall_s`` / ``<name>.<stage>_queue_depth_max`` gauges
plus a structured record (:meth:`Telemetry.record_pipeline`) that lands
in the run manifest's ``pipeline`` section, and per-item stage spans so
the overlap shows up in ``trace_spans.json`` next to everything else.

``depth=0`` runs the same stages inline (no threads, no overlap) — the
apples-to-apples baseline the ``overlap`` bench suite compares against.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Iterator, List, Sequence

from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy
from music_analyst_tpu.telemetry import get_telemetry

# Stage bodies are retried on transiently-classified failures (tunnel
# drops, device loss, injected prefetch.stage faults) before poisoning
# the pipeline; logic errors still fail on the first throw.  Shared by
# the threaded and inline (depth=0) paths — both go through _timed_fn.
_STAGE_RETRY = RetryPolicy(base_s=0.05, cap_s=1.0)

DEFAULT_PREFETCH_DEPTH = 2

# Cancellation poll period for blocking queue ops.  Long enough that the
# steady state pays ~zero wakeups, short enough that close() returns fast.
_POLL_S = 0.05

# Thread-join grace at shutdown.  Stages only block in cancellable poll
# loops or in user fns; a user fn that ignores the cancel for longer than
# this is left to finish as a daemon rather than hanging the caller.
_JOIN_S = 5.0

_DONE = object()          # end-of-stream sentinel
_CANCELLED = object()     # internal: a queue op gave up on cancellation


class _Failure:
    """Poison pill carrying a stage's exception down the chain."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


@dataclass
class Stage:
    """One pipeline hop: ``fn(item) -> item`` under a stable ``name``.

    ``workers > 1`` runs the stage on an internal thread pool with a
    bounded in-flight window; results still leave the stage in submission
    order (the per-song engine's old deque window, generalized).  Set
    ``record_spans=False`` when ``fn`` records its own telemetry span
    (avoids double-counting in ``top_spans``).
    """

    name: str
    fn: Callable[[Any], Any]
    workers: int = 1
    record_spans: bool = True


class StageStats:
    """Accounting for one stage (or the source/sink pseudo-stages)."""

    __slots__ = (
        "name", "items", "work_s", "stall_s", "backpressure_s",
        "queue_depth_max",
    )

    def __init__(self, name: str) -> None:
        self.name = name
        self.items = 0
        self.work_s = 0.0
        self.stall_s = 0.0
        self.backpressure_s = 0.0
        self.queue_depth_max = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "stage": self.name,
            "items": self.items,
            "work_s": round(self.work_s, 6),
            "stall_s": round(self.stall_s, 6),
            "backpressure_s": round(self.backpressure_s, 6),
            "queue_depth_max": self.queue_depth_max,
        }


def resolve_prefetch_depth(
    value: Any = None, default: int = DEFAULT_PREFETCH_DEPTH
) -> int:
    """Resolve a ``--prefetch-depth`` value: explicit argument wins, then
    ``$MUSICAAL_PREFETCH_DEPTH``, then the default.  0 = no overlap."""
    if value is None:
        raw = os.environ.get("MUSICAAL_PREFETCH_DEPTH", "").strip()
        if not raw:
            return default
        value = raw
    try:
        depth = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"prefetch depth must be an integer >= 0, got {value!r}"
        ) from None
    if depth < 0:
        raise ValueError(f"prefetch depth must be >= 0, got {depth}")
    return depth


class PrefetchPipeline:
    """Run ``source → stages… → consumer`` with ``depth`` items per hop.

    One-shot: build, iterate :meth:`run`, read :meth:`summary`.  The
    consumer sees results strictly in source order regardless of depth or
    per-stage worker count.
    """

    def __init__(
        self,
        stages: Sequence[Stage],
        depth: int = DEFAULT_PREFETCH_DEPTH,
        name: str = "pipeline",
        sink_name: str = "compute",
    ) -> None:
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        for stage in stages:
            if stage.workers < 1:
                raise ValueError(
                    f"stage {stage.name!r}: workers must be >= 1"
                )
        self.stages = list(stages)
        self.depth = depth
        self.name = name
        self._cancel = threading.Event()
        self._threads: List[threading.Thread] = []
        self._queues: List[queue.Queue] = []
        self._source_stats = StageStats("source")
        self._stage_stats = [StageStats(s.name) for s in self.stages]
        self._sink_stats = StageStats(sink_name)
        self._published = False

    # ------------------------------------------------------- queue helpers

    def _put(self, q: queue.Queue, item: Any, stats: StageStats = None) -> bool:
        """Blocking put that respects cancellation; waiting time counts as
        the producing stage's backpressure.  Returns False on cancel."""
        t0 = time.perf_counter()
        while not self._cancel.is_set():
            try:
                q.put(item, timeout=_POLL_S)
            except queue.Full:
                continue
            if stats is not None:
                stats.backpressure_s += time.perf_counter() - t0
            return True
        return False

    def _get(self, q: queue.Queue, stats: StageStats = None) -> Any:
        """Blocking get that respects cancellation; waiting time counts as
        the consuming stage's input stall.  Returns ``_CANCELLED`` on
        cancel."""
        t0 = time.perf_counter()
        while not self._cancel.is_set():
            if stats is not None:
                stats.queue_depth_max = max(stats.queue_depth_max, q.qsize())
            try:
                item = q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if stats is not None:
                stats.stall_s += time.perf_counter() - t0
            return item
        return _CANCELLED

    # ------------------------------------------------------------- threads

    def _pump(self, source: Iterable[Any], q_out: queue.Queue) -> None:
        """Feed the first queue from the source iterator.  Source read time
        is the pseudo-stage's work (an ingest-bound run shows up here)."""
        stats = self._source_stats
        it = iter(source)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                self._put(q_out, _DONE, stats)
                return
            except BaseException as exc:  # forwarded, re-raised in consumer
                self._put(q_out, _Failure(exc), stats)
                return
            stats.work_s += time.perf_counter() - t0
            stats.items += 1
            if not self._put(q_out, item, stats):
                return

    def _timed_fn(self, stage: Stage, item: Any):
        """Run one stage fn; returns ``(duration_s, result | _Failure)``.

        The watchdog scope around the call is what turns "the bench went
        silent" into ``taxonomy: stage_stall`` naming the exact stage —
        a no-op unless a watchdog is active.
        """
        t0 = time.perf_counter()
        try:
            with watchdog.watch(f"{self.name}.{stage.name}", kind="stage"):
                result = _STAGE_RETRY.call(
                    self._stage_once, stage, item, site="prefetch.stage"
                )
        except BaseException as exc:
            return time.perf_counter() - t0, _Failure(exc)
        return time.perf_counter() - t0, result

    def _stage_once(self, stage: Stage, item: Any) -> Any:
        fault_point("prefetch.stage", stage=stage.name, pipeline=self.name)
        return stage.fn(item)

    def _account(self, stage: Stage, stats: StageStats, dur: float) -> None:
        stats.work_s += dur
        stats.items += 1
        if stage.record_spans:
            get_telemetry().record_span(stage.name, dur, pipeline=self.name)

    def _stage_loop(
        self, stage: Stage, stats: StageStats,
        q_in: queue.Queue, q_out: queue.Queue,
    ) -> None:
        """Coordinator thread for one stage.

        ``workers == 1`` processes inline; ``workers > 1`` keeps a bounded
        window of pool futures and emits results in submission order, so
        downstream ordering never depends on worker scheduling.
        """
        pool = (
            ThreadPoolExecutor(
                max_workers=stage.workers,
                thread_name_prefix=f"{self.name}-{stage.name}",
            )
            if stage.workers > 1 else None
        )
        window: deque = deque()
        window_cap = stage.workers * 2

        def emit(dur: float, result: Any) -> bool:
            """Account + forward one result; False ends the loop (either
            cancellation or a failure that poisons the chain)."""
            self._account(stage, stats, dur)
            if not self._put(q_out, result, stats):
                return False
            return not isinstance(result, _Failure)

        try:
            while True:
                item = self._get(q_in, stats)
                if item is _CANCELLED:
                    return
                if item is _DONE or isinstance(item, _Failure):
                    while window:
                        if not emit(*window.popleft().result()):
                            return
                    self._put(q_out, item, stats)
                    return
                if pool is None:
                    if not emit(*self._timed_fn(stage, item)):
                        return
                else:
                    window.append(pool.submit(self._timed_fn, stage, item))
                    if len(window) >= window_cap:
                        if not emit(*window.popleft().result()):
                            return
        finally:
            if pool is not None:
                pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------ shutdown

    def _shutdown(self) -> None:
        """Cancel, drain, join, publish.  Idempotent; never raises."""
        self._cancel.set()
        for q in self._queues:
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        for thread in self._threads:
            thread.join(timeout=_JOIN_S)
        self._publish()

    def _publish(self) -> None:
        if self._published:
            return
        self._published = True
        tel = get_telemetry()
        summary = self.summary()
        for entry in summary["stages"]:
            prefix = f"{self.name}.{entry['stage']}"
            tel.gauge(f"{prefix}_stall_s", entry["stall_s"])
            if entry["queue_depth_max"]:
                tel.gauge(
                    f"{prefix}_queue_depth_max", entry["queue_depth_max"]
                )
        tel.record_pipeline(self.name, summary)

    def summary(self) -> Dict[str, Any]:
        """JSON-able stats: per-stage stall/work/backpressure seconds and
        queue-depth high-water marks (the manifest ``pipeline`` entry)."""
        stats = [self._source_stats, *self._stage_stats, self._sink_stats]
        return {
            "depth": self.depth,
            "stages": [s.as_dict() for s in stats],
            "max_queue_depth": max(s.queue_depth_max for s in stats),
        }

    # ----------------------------------------------------------------- run

    def run(self, source: Iterable[Any]) -> Iterator[Any]:
        """Yield each source item after it has passed through every stage.

        Results arrive in source order.  A stage/source exception re-raises
        here; closing the generator (break / caller exception) cancels and
        joins the pipeline before control returns.
        """
        if self.depth == 0:
            yield from self._run_inline(source)
            return
        self._queues = [
            queue.Queue(maxsize=self.depth)
            for _ in range(len(self.stages) + 1)
        ]
        self._threads = [
            threading.Thread(
                target=self._pump, args=(source, self._queues[0]),
                name=f"{self.name}-source", daemon=True,
            )
        ]
        for i, stage in enumerate(self.stages):
            self._threads.append(
                threading.Thread(
                    target=self._stage_loop,
                    args=(
                        stage, self._stage_stats[i],
                        self._queues[i], self._queues[i + 1],
                    ),
                    name=f"{self.name}-{stage.name}",
                    daemon=True,
                )
            )
        for thread in self._threads:
            thread.start()
        sink = self._sink_stats
        try:
            while True:
                item = self._get(self._queues[-1], sink)
                if item is _DONE or item is _CANCELLED:
                    return
                if isinstance(item, _Failure):
                    raise item.exc
                sink.items += 1
                t0 = time.perf_counter()
                yield item
                sink.work_s += time.perf_counter() - t0
        finally:
            self._shutdown()

    def _run_inline(self, source: Iterable[Any]) -> Iterator[Any]:
        """depth=0: same stages, same accounting, no threads, no overlap."""
        try:
            it = iter(source)
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                self._source_stats.work_s += time.perf_counter() - t0
                self._source_stats.items += 1
                for stage, stats in zip(self.stages, self._stage_stats):
                    dur, item = self._timed_fn(stage, item)
                    self._account(stage, stats, dur)
                    if isinstance(item, _Failure):
                        raise item.exc
                self._sink_stats.items += 1
                t0 = time.perf_counter()
                yield item
                self._sink_stats.work_s += time.perf_counter() - t0
        finally:
            self._publish()
