"""Vectorized keyword-sentiment kernel — the ``--mock`` backend on device.

The reference's mock classifier scans each lyric for five positive and five
negative substrings and labels by the sign of the score
(``scripts/sentiment_classifier.py:66-83``).  Here the scan is a batched
device kernel: lyrics are encoded as a padded uint8 byte matrix, ASCII
lowercasing and all ten substring matches run as fused elementwise/compare
ops over the whole batch — thousands of songs per dispatch instead of one
Python loop iteration per song.

Semantics notes (SURVEY.md §5 contract #5):

* matching is *substring containment*, not word-boundary ("lovely" scores
  as "love" — faithfully reproduced);
* score = (#positive keywords present) − (#negative present), each keyword
  counted once regardless of repeats; label = sign of score;
* lowercasing here is ASCII (A-Z); the reference uses Python ``str.lower``.
  The only divergence is exotic Unicode that lowercases *into* ASCII
  (e.g. ``İ`` → ``i̇``, Kelvin ``K`` → ``k``) — impossible to hit with the
  ASCII-only keyword set unless the uppercase variant splits a keyword,
  which cannot create a new ASCII keyword substring match.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Reference keyword sets (scripts/sentiment_classifier.py:70-71).
POSITIVE_KEYWORDS: Tuple[str, ...] = ("love", "happy", "joy", "sunshine", "smile")
NEGATIVE_KEYWORDS: Tuple[str, ...] = ("cry", "sad", "pain", "lonely", "tears")

MAX_KEYWORD_LEN = max(map(len, POSITIVE_KEYWORDS + NEGATIVE_KEYWORDS))

# Label ids follow utils.labels.LABEL_TO_ID: 0=Positive, 1=Neutral, 2=Negative.
_POSITIVE, _NEUTRAL, _NEGATIVE = 0, 1, 2


def _lower_ascii(x: jax.Array) -> jax.Array:
    return jnp.where((x >= 65) & (x <= 90), x + 32, x)


def _contains(x: jax.Array, keyword: np.ndarray) -> jax.Array:
    """Per-row substring containment of ``keyword`` in byte matrix ``x``.

    Shifted-compare formulation: for an m-byte keyword, AND together m
    shifted equality masks and OR-reduce over positions.  XLA fuses the
    whole thing into one pass over the batch; padding bytes (0) can never
    match because keywords contain no NUL.
    """
    length = x.shape[-1]
    m = int(keyword.shape[0])
    if length < m:
        return jnp.zeros(x.shape[:-1], dtype=bool)
    window = length - m + 1
    acc = x[..., 0:window] == keyword[0]
    for j in range(1, m):
        acc = acc & (x[..., j : window + j] == keyword[j])
    return jnp.any(acc, axis=-1)


@jax.jit
def keyword_scores(byte_matrix: jax.Array) -> jax.Array:
    """Scores for a padded uint8 batch ``[B, L]`` → int32 ``[B]``."""
    x = _lower_ascii(byte_matrix)
    score = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    for kw in POSITIVE_KEYWORDS:
        score = score + _contains(x, np.frombuffer(kw.encode(), dtype=np.uint8)).astype(
            jnp.int32
        )
    for kw in NEGATIVE_KEYWORDS:
        score = score - _contains(x, np.frombuffer(kw.encode(), dtype=np.uint8)).astype(
            jnp.int32
        )
    return score


@jax.jit
def keyword_labels(byte_matrix: jax.Array) -> jax.Array:
    """Label ids (0=Positive, 1=Neutral, 2=Negative) for a padded batch."""
    score = keyword_scores(byte_matrix)
    return jnp.where(score > 0, _POSITIVE, jnp.where(score < 0, _NEGATIVE, _NEUTRAL))


def encode_batch(
    texts: Sequence[str],
    length: int,
) -> Tuple[np.ndarray, List[int]]:
    """Encode stripped lyrics to a padded ``[B, length]`` uint8 matrix.

    Returns the matrix plus the indices of songs whose UTF-8 encoding
    exceeds ``length`` (their windows need the chunked path to preserve
    exact containment semantics).
    """
    batch = np.zeros((len(texts), length), dtype=np.uint8)
    overflow: List[int] = []
    for i, text in enumerate(texts):
        data = text.strip().encode("utf-8", errors="replace")
        if len(data) > length:
            overflow.append(i)
            data = data[:length]
        row = np.frombuffer(data, dtype=np.uint8)
        batch[i, : row.shape[0]] = row
    return batch, overflow


def score_texts(
    texts: Sequence[str],
    length: int = 4096,
) -> np.ndarray:
    """Exact batched scores for arbitrary-length lyrics.

    The batch is padded only to the power-of-two bucket covering its
    longest row (floor 512, cap ``length``): host→device transfer is the
    bottleneck for this kernel, and fixed-``length`` padding would move
    ~4x the bytes for typical lyrics.  Power-of-two buckets keep the jit
    cache to at most four shapes.  Songs above the cap are re-scored over
    overlapping windows (overlap ``MAX_KEYWORD_LEN - 1`` so no match can
    straddle a boundary) — exact for any length.
    """
    encoded = [t.strip().encode("utf-8", errors="replace") for t in texts]
    max_bytes = max((len(d) for d in encoded), default=1)
    from music_analyst_tpu.utils.shapes import round_pow2

    bucket = min(round_pow2(min(max_bytes, length), 512), length)
    batch = np.zeros((len(encoded), bucket), dtype=np.uint8)
    overflow: List[int] = []
    for i, data in enumerate(encoded):
        if len(data) > bucket:
            overflow.append(i)
            data = data[:bucket]
        batch[i, : len(data)] = np.frombuffer(data, dtype=np.uint8)
    scores = np.array(keyword_scores(batch))
    for i in overflow:
        scores[i] = _score_long_text(texts[i].strip(), bucket)
    return scores


def _score_long_text(text: str, length: int) -> int:
    """Windowed exact scoring for a single oversized lyric."""
    data = text.encode("utf-8", errors="replace")
    step = length - (MAX_KEYWORD_LEN - 1)
    windows = [data[start : start + length] for start in range(0, len(data), step)]
    batch = np.zeros((len(windows), length), dtype=np.uint8)
    for i, w in enumerate(windows):
        batch[i, : len(w)] = np.frombuffer(w, dtype=np.uint8)
    x = _lower_ascii(jnp.asarray(batch))
    score = 0
    for kw in POSITIVE_KEYWORDS:
        hit = bool(
            np.asarray(_contains(x, np.frombuffer(kw.encode(), dtype=np.uint8))).any()
        )
        score += int(hit)
    for kw in NEGATIVE_KEYWORDS:
        hit = bool(
            np.asarray(_contains(x, np.frombuffer(kw.encode(), dtype=np.uint8))).any()
        )
        score -= int(hit)
    return score
