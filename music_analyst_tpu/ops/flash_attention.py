"""Blocked online-softmax attention as a Pallas TPU kernel.

The dense formulation (``models/layers.py:dot_product_attention``)
materializes the full ``[B, H, S, KV]`` logit tensor in HBM — fine at the
classifier's seq 128, quadratic-memory at long context.  This kernel never
materializes logits: one query block is staged in VMEM, key/value blocks
stream past it, and the softmax runs online (running max ``m``, running
denominator ``l``, rescaled accumulator) so HBM traffic is O(S·D) instead
of O(S²).

Replaces nothing in the reference (its longest "sequence" concern is
truncating lyrics to 4,000 chars, ``scripts/sentiment_classifier.py:90``);
this is the long-context path SURVEY.md §5 calls out as the TPU-era
requirement, and composes with the ring schedule in
``ops/ring_attention.py`` (each ring hop's local attention is exactly one
of these kernels).

Grid ``(B, H, q_blocks, kv_blocks)``; the kv dimension is innermost and
sequential ("arbitrary"), with the running state in VMEM scratch that
persists across kv steps.  GQA maps query head ``h`` to kv head
``h // group`` in the BlockSpec index map — no ``jnp.repeat`` of K/V.
Masking vocabulary: ``causal`` (with block skipping), per-row ``lengths``
(key padding), and per-token ``segment_ids`` (block-diagonal, for packed
batches) — all composable in one pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.6 renamed TPUCompilerParams -> CompilerParams; same fields.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

NEG_INF = -1e30


def _flash_kernel(
    len_ref,  # SMEM [B] — kv valid length per batch row
    off_ref,  # SMEM [2] — (q_offset, kv_offset) global position offsets
    *refs,    # [qseg, kvseg,] q, k, v, o [, m_out, l_out], scratch...
    causal: bool,
    block_q: int,
    block_kv: int,
    kv_blocks: int,
    scale: float,
    residuals: bool,
    segmented: bool,
):
    if segmented:
        # VMEM [1, bq] / [1, bkv] — per-token segment ids (block-diagonal
        # attention for packed batches, models/distilbert.py).
        qseg_ref, kvseg_ref, *refs = refs
    q_ref, k_ref, v_ref, o_ref, *rest = refs
    if residuals:
        m_out_ref, l_out_ref, acc_ref, m_ref, l_ref = rest
    else:
        acc_ref, m_ref, l_ref = rest
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    kv_len = len_ref[bi]
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if causal:
        # Skip kv blocks whose every (offset-adjusted) position is above
        # the diagonal: they can't contribute to the online softmax.
        run = kv_off + ki * block_kv <= q_off + qi * block_q + block_q - 1
    else:
        run = ki >= 0

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv]

        kv_pos = kv_off + ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        )
        valid = kv_pos < kv_len
        if causal:
            q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, dimension=0
            )
            valid = valid & (kv_pos <= q_pos)
        if segmented:
            qs = qseg_ref[0]                                    # [bq]
            ks = kvseg_ref[0]                                   # [bkv]
            valid = valid & (qs[:, None] == ks[None, :])
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:, :1]                                  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        # Guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1.
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(jnp.minimum(m_prev - m_cur, 0.0))
        l_cur = alpha * l_prev + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        if residuals:
            # Unnormalized accumulator + running stats: hop-combinable
            # (ring attention merges partials across devices).
            o_ref[0, 0] = acc_ref[:].astype(o_ref.dtype)
            m_out_ref[0, 0] = m_ref[:]
            l_out_ref[0, 0] = l_ref[:]
        else:
            denom = jnp.maximum(l_ref[:, :1], 1e-30)
            o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_kv", "interpret",
                     "residuals"),
)
def _flash_call(
    q: jax.Array,       # [B, S, H, D]
    k: jax.Array,       # [B, KV, Hkv, D]
    v: jax.Array,
    lengths: jax.Array,  # [B] int32 — valid kv length per row
    offsets: jax.Array,  # [2] int32 — (q_offset, kv_offset)
    causal: bool,
    block_q: int,
    block_kv: int,
    interpret: bool,
    residuals: bool,
    q_seg: jax.Array | None = None,   # [B, S] int32 segment ids
    kv_seg: jax.Array | None = None,  # [B, KV]
):
    B, S, H, D = q.shape
    KV = k.shape[1]
    Hkv = k.shape[2]
    # Head-major layout so every VMEM block is (1, 1, seq_block, D): the
    # sublane/lane dims are then (seq_block, D), which tile cleanly.
    q = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    group = H // Hkv
    q_blocks = S // block_q
    kv_blocks = KV // block_kv
    scale = D ** -0.5

    segmented = q_seg is not None
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        kv_blocks=kv_blocks,
        scale=scale,
        residuals=residuals,
        segmented=segmented,
    )
    qblock_spec = pl.BlockSpec(
        (1, 1, block_q, D),
        lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kvblock_spec = pl.BlockSpec(
        (1, 1, block_kv, D),
        lambda b, h, qi, ki: (b, h // group, ki, 0),
        memory_space=pltpu.VMEM,
    )
    stat_spec = pl.BlockSpec(
        (1, 1, block_q, 128),
        lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    if residuals:
        out_shape = (
            jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
        )
        out_specs = (qblock_spec, stat_spec, stat_spec)
    else:
        out_shape = jax.ShapeDtypeStruct((B, H, S, D), q.dtype)
        out_specs = qblock_spec
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths, whole [B]
        pl.BlockSpec(memory_space=pltpu.SMEM),  # offsets [2]
    ]
    inputs = [lengths, offsets]
    if segmented:
        in_specs.append(pl.BlockSpec(
            (1, block_q), lambda b, h, qi, ki: (b, qi),
            memory_space=pltpu.VMEM,
        ))
        in_specs.append(pl.BlockSpec(
            (1, block_kv), lambda b, h, qi, ki: (b, ki),
            memory_space=pltpu.VMEM,
        ))
        inputs += [q_seg, kv_seg]
    in_specs += [qblock_spec, kvblock_spec, kvblock_spec]
    inputs += [q, k, v]
    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(B, H, q_blocks, kv_blocks),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    if residuals:
        o, m, l = out
        # o unnormalized [B,H,S,D] f32; stats collapse their broadcast lane.
        return o.transpose(0, 2, 1, 3), m[..., 0], l[..., 0]
    return out.transpose(0, 2, 1, 3)  # back to [B, S, H, D]


def _fit_block(requested: int, seq: int) -> int:
    """Largest tile-aligned divisor of ``seq`` that is ≤ ``requested``.

    Divisibility is required by the kernel's grid, but an over-large
    request (e.g. the default 512 against S=768, or a ring shard that is
    not a power of two) should degrade to a legal smaller block rather
    than raise.  Only multiples of the 8-row TPU sublane tile qualify —
    an unaligned block may not lower on real hardware and a tiny one is a
    silent perf cliff — so genuinely awkward lengths still raise with the
    remedy (sequences ≤ 8 pass through whole; they already fit one tile).
    """
    if seq <= 8:
        return min(requested, seq)
    for cand in range(min(requested, seq) // 8 * 8, 0, -8):
        if seq % cand == 0:
            return cand
    raise ValueError(
        f"no tile-aligned block ≤ {requested} divides sequence length "
        f"{seq}; pad the sequence to a multiple of 8"
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths: jax.Array | None = None,
    causal: bool = False,
    block_q: int = 512,
    block_kv: int = 1024,
    interpret: bool | None = None,
    q_offset: jax.Array | int = 0,
    kv_offset: jax.Array | int = 0,
    return_residuals: bool = False,
    q_segment_ids: jax.Array | None = None,
    kv_segment_ids: jax.Array | None = None,
):
    """Attention over ``[B, S, H, D]`` without materializing logits.

    ``lengths`` masks keys/values past each row's valid length (encoder
    padding); ``causal`` adds the autoregressive mask.  GQA is supported
    when ``k``/``v`` carry fewer heads.  ``block_q``/``block_kv`` are upper
    bounds: each is lowered to the largest divisor of its sequence length
    (tile-aligned when possible), so non-power-of-two shards (e.g. ring
    attention's per-device slices) pick a legal block instead of raising.
    Off-TPU the kernel runs in interpreter mode
    so CPU test meshes exercise the same code path.

    ``q_segment_ids`` ``[B, S]`` / ``kv_segment_ids`` ``[B, KV]`` add
    block-diagonal masking: a query attends only to keys with the SAME
    segment id (packed batches, ``models/distilbert.py:pack_segments``).
    ``kv_segment_ids`` defaults to ``q_segment_ids`` for self-attention.
    Composes with ``lengths``/``causal``; a query whose segment has no
    valid key outputs zeros (guarded denominator), matching the dense
    formulation's uniform-over-masked behavior in effect (neither is ever
    gathered).

    ``q_offset``/``kv_offset`` shift the global positions used by the
    causal/length masks — the hook that lets a sequence-parallel caller
    (ring attention) run this kernel on one K/V shard at a time.  With
    ``return_residuals=True`` the call returns ``(o_unnormalized, m, l)``
    (``[B,S,H,D]`` f32, ``[B,H,S]``, ``[B,H,S]``) for cross-shard online
    combination instead of the normalized output.
    """
    B, S, H, D = q.shape
    KV = k.shape[1]
    block_q = _fit_block(block_q, S)
    block_kv = _fit_block(block_kv, KV)
    if H % k.shape[2]:
        raise ValueError(f"q heads {H} not a multiple of kv heads {k.shape[2]}")
    if lengths is None:
        # Lengths are *global* positions: with a kv_offset the local shard
        # covers [kv_offset, kv_offset + KV).
        lengths = jnp.full((B,), KV, jnp.int32) + jnp.asarray(
            kv_offset, jnp.int32
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offsets = jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(kv_offset, jnp.int32)]
    )
    q_seg = kv_seg = None
    if q_segment_ids is not None:
        if kv_segment_ids is None:
            if KV != S:
                raise ValueError(
                    "kv_segment_ids is required when KV length differs "
                    "from the query length"
                )
            kv_segment_ids = q_segment_ids
        if q_segment_ids.shape != (B, S):
            raise ValueError(
                f"q_segment_ids must be [B, S]={B, S}, "
                f"got {q_segment_ids.shape}"
            )
        if kv_segment_ids.shape != (B, KV):
            raise ValueError(
                f"kv_segment_ids must be [B, KV]={B, KV}, "
                f"got {kv_segment_ids.shape}"
            )
        q_seg = q_segment_ids.astype(jnp.int32)
        kv_seg = kv_segment_ids.astype(jnp.int32)
    elif kv_segment_ids is not None:
        raise ValueError("kv_segment_ids given without q_segment_ids")
    return _flash_call(
        q, k, v, lengths.astype(jnp.int32), offsets, causal, block_q,
        block_kv, interpret, return_residuals, q_seg=q_seg, kv_seg=kv_seg,
    )
