"""Dynamic int8 quantized matmul for inference (w8a8, int32 accumulate).

The roofline suite measures the v5e MXU at ~2.1× bf16 throughput for
int8×int8→int32 chains (``benchmarks/results/roofline.json``), and the
headline DistilBERT forward already runs at ~93% of the bf16 roofline —
so int8 is the remaining large FLOP lever.  This op quantizes on the fly:

* weights: symmetric per-output-channel, ``s_w[c] = max|w[:,c]| / 127`` —
  computed inside the jitted forward from the ordinary float params, so
  the param tree, checkpoint loaders, and sharding rules are untouched;
* activations: symmetric per-token (row-wise) dynamic,
  ``s_x[t] = max|x[t,:]| / 127`` — one outlier token costs only its own
  row's resolution, not the whole batch's (the per-tensor variant loses
  ~all precision on every other row once one activation spikes;
  ``tests/test_quant.py::test_outlier_token_does_not_poison_batch``);
* accumulation in int32 on the MXU, dequant ``acc · s_x[t] · s_w[c]``
  fused into the epilogue by XLA.

Accuracy contract: quantization error is bounded by the symmetric-int8
resolution (~0.8% of the dynamic range per operand); the classifier's
2→3-label thresholding absorbs small logit shifts, and
``tests/test_quant.py`` pins both the op-level error and end-to-end label
agreement.  No reference analogue (the reference's model lives behind an
HTTP API); this is a TPU-hardware play, default OFF (``quant="none"``).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _symmetric_scale(value: jax.Array, axis, keepdims: bool = True):
    amax = jnp.max(jnp.abs(value), axis=axis, keepdims=keepdims)
    return jnp.maximum(amax, 1e-8) / 127.0


def quant_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x @ w`` via dynamic int8: x ``[..., K]`` f32/bf16, w ``[K, N]``.

    Returns f32 ``[..., N]``.
    """
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    s_x = _symmetric_scale(x32, axis=-1)  # [..., 1] per token
    s_w = _symmetric_scale(w32, axis=0)   # [1, N] per channel
    qx = jnp.round(x32 / s_x).astype(jnp.int8)
    qw = jnp.round(w32 / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qx, qw,
        (((qx.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * s_x * s_w.reshape(1, -1)


def quant_batched_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Per-expert ``x[e] @ w[e]``: x ``[E, C, K]``, w ``[E, K, N]`` → f32
    ``[E, C, N]``.

    The MoE expert einsums (``models/moe.py``) are batched matmuls with a
    leading expert axis; scales follow the same symmetric scheme as
    :func:`quant_matmul`, kept **per expert**: activations per ``(e, row)``
    (one hot expert's buffer rows can't poison another's resolution),
    weights per ``(e, out-channel)``.  Accumulation is int32 on the MXU
    with the dequant fused into the epilogue; the expert batch axis maps
    onto dot_general batch dims, so an ``ep``-sharded weight stack shards
    the quantized compute identically to the float path.
    """
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    s_x = _symmetric_scale(x32, axis=-1)  # [E, C, 1] per expert-row
    s_w = _symmetric_scale(w32, axis=1)   # [E, 1, N] per expert-channel
    qx = jnp.round(x32 / s_x).astype(jnp.int8)
    qw = jnp.round(w32 / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qx, qw,
        (((2,), (1,)), ((0,), (0,))),     # contract K, batch over E
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * s_x * s_w


def quant_dense_axis_last(x, kernel, bias=None, out_dtype=None):
    """DenseGeneral(axis=-1): x ``[..., K]``, kernel ``[K, *F]`` → ``[..., *F]``."""
    feat_shape = kernel.shape[1:]
    out = quant_matmul(x, kernel.reshape(kernel.shape[0], -1))
    out = out.reshape(x.shape[:-1] + feat_shape)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


def quant_dense_axis_last2(x, kernel, bias=None, out_dtype=None):
    """DenseGeneral(axis=(-2,-1)): x ``[..., H, D]``, kernel ``[H, D, N]``."""
    H, D, N = kernel.shape
    out = quant_matmul(x.reshape(x.shape[:-2] + (H * D,)), kernel.reshape(H * D, N))
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


# ---------------------------------------------------------------------------
# int8 KV-page quantization (paged decode cache, ops/paged_attention.py)
# ---------------------------------------------------------------------------
#
# KV rows are quantized symmetrically **per (page, row)**: one f32 scale
# covers a single token's (n_kv_heads, head_dim) K or V block.  Per-row
# granularity is what makes incremental decode exact — each new token's
# row is quantized once, in isolation, when it is written, so committing
# a token never re-scales (and never perturbs) any previously-written
# row, and copy-on-write / checkpoint / pin-transfer paths can move
# pages plus their scale rows without ever recomputing anything.  The
# dequant (codes · scale) is fused into the paged-attention kernel's
# KV-load epilogue; the scale layout alongside the pool is
# ``[n_pages + 1, page_size]`` per layer, for K and V each.


def quantize_kv_page(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the trailing ``(n_kv_heads, head_dim)`` axes.

    ``x [..., n_kv, D]`` → ``(codes int8 [..., n_kv, D], scale f32
    [...])`` with ``scale = max(|row|, 1e-8) / 127`` — the same scheme as
    the matmul paths above, at per-token granularity.  Round-trip
    contract (pinned by tests/test_paged_attention.py): quantizing a row
    dequantized to f32 reproduces the codes exactly (the scale
    reconstructs to within 1 ulp and ``127 · 2^-24 ≪ 0.5``); through the
    bf16 compute dtype the reconstruction error reaches ``127 · 2^-8 ≈
    0.5``, so a code can shift by at most ±1 on the first round-trip and
    the result is a fixed point of further round-trips.  The paged
    prefill's recompute-and-rescatter of a boundary page therefore
    perturbs already-written rows by ≤ 1 code once — inside the int8
    path's bounded-error budget (the byte-identity contract covers only
    the unquantized pools).
    """
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=(-2, -1))
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x32 / scale[..., None, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv_page(
    q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16
) -> jax.Array:
    """Inverse of :func:`quantize_kv_page`: ``codes [..., n_kv, D]`` ×
    ``scale [...]`` → ``dtype`` rows (the representation the model's
    attention math runs on everywhere else)."""
    return (q.astype(jnp.float32) * scale[..., None, None]).astype(dtype)


# ---------------------------------------------------------------------------
# Weight-only quantized parameter store (stored int8 / packed int4 weights)
# ---------------------------------------------------------------------------
#
# The dynamic path above re-derives int8 weights from a *float* param tree
# inside every forward — the bf16 tree must still exist on host and in HBM.
# For the 8B decoder that tree is ~16 GB: it neither fits one v5e chip
# (16 GB HBM) nor crosses the ~10 MB/s loopback tunnel in useful time.  The
# weight-only store below quantizes ONCE (on host, at load) and keeps only
# the integer codes + scales resident:
#
# * ``int8``: symmetric per-output-channel, q keeps the float kernel's
#   shape, ``scale[(1,), *feat]`` — the matmul is the existing
#   int8×int8→int32 MXU formulation with the dequant in the epilogue;
# * ``int4``: symmetric per-channel-*group* over the contracted axis
#   (default group 128; falls back to one group when the contraction dim
#   isn't divisible), two codes packed per int8 byte along axis 0
#   (element 2i → low nibble, 2i+1 → high nibble, arithmetic-shift
#   unpack), ``scale[(G,), *feat]`` — grouped int32 dots, per-group
#   dequant, summed over groups.
#
# Activations stay float at the API boundary and are dynamically
# row-quantized inside the op (same rationale as ``quant_matmul``: one
# outlier token costs only its own row).  ``QuantizedParam`` is a
# registered pytree whose scheme metadata is hashable, so quantized trees
# flow through ``jax.jit``, ``jax.eval_shape``, sharding rules
# (``parallel/sharding.py``) and donation exactly like float trees.

WQ_SCHEMES = ("int8", "int4")
WQ_DEFAULT_GROUP = 128

# (path regex, n_contract) — which param-tree leaves are weight-quantized.
# Matmul kernels only: embeddings (gathers, not matmuls), norm scales,
# biases, and the tiny classifier heads stay float.  o_proj contracts its
# leading TWO axes (DenseGeneral(axis=(-2,-1))); everything else one.
WQ_PATH_RULES: Tuple[Tuple[str, int], ...] = (
    (r".*(q_proj|k_proj|v_proj)/kernel$", 1),
    (r".*o_proj/kernel$", 2),
    (r".*(gate_proj|up_proj|down_proj)/kernel$", 1),
    (r".*ffn/(lin1|lin2)/kernel$", 1),
    (r".*lm_head/kernel$", 1),
)


@dataclasses.dataclass
class QuantizedParam:
    """A stored weight-quantized kernel: integer codes + dequant scales.

    ``q``/``scale`` are the data leaves (arrays, shardings, or
    ``ShapeDtypeStruct``s — whatever the surrounding transform carries);
    the scheme metadata is static aux data, so two params quantized the
    same way are structure-equal and jit caches on the metadata.
    """

    q: Any                      # int8 codes ([*shape] or packed [s0/2, ...])
    scale: Any                  # f32 [(1|G,), *shape[n_contract:]]
    scheme: str = "int8"        # "int8" | "int4"
    shape: Tuple[int, ...] = ()  # original float kernel shape
    n_contract: int = 1         # leading axes contracted by the matmul
    group_size: int = 0         # int4 group length over flattened K; 0=int8

    @property
    def feat_shape(self) -> Tuple[int, ...]:
        return self.shape[self.n_contract:]


jax.tree_util.register_dataclass(
    QuantizedParam,
    data_fields=["q", "scale"],
    meta_fields=["scheme", "shape", "n_contract", "group_size"],
)


def _xp(value):
    """numpy for host arrays (no accidental device_put during streaming
    load), jnp for device arrays / tracers."""
    return np if isinstance(value, np.ndarray) else jnp


def wq_group_size(K: int, group_size: int = WQ_DEFAULT_GROUP) -> int:
    """Effective int4 group: the requested size when it divides the
    flattened contraction dim, else one group spanning all of K
    (degrades to per-channel, still valid)."""
    return group_size if group_size > 0 and K % group_size == 0 else K


def quantize_array(
    w,
    scheme: str,
    n_contract: int = 1,
    group_size: int = WQ_DEFAULT_GROUP,
) -> QuantizedParam:
    """Symmetric weight-only quantization of one kernel.

    Works on numpy arrays (host streaming load), jax arrays (quantizing an
    already-materialized tree), and under ``jax.eval_shape`` (abstract
    byte-budget accounting — ``tests/test_8b_lowering.py``).
    """
    if scheme not in WQ_SCHEMES:
        raise ValueError(f"scheme must be one of {WQ_SCHEMES}, got {scheme!r}")
    xp = _xp(w)
    shape = tuple(int(s) for s in w.shape)
    K = int(math.prod(shape[:n_contract]))
    F = int(math.prod(shape[n_contract:]))
    w2 = xp.reshape(xp.asarray(w, dtype=xp.float32), (K, F))
    if scheme == "int8":
        amax = xp.max(xp.abs(w2), axis=0, keepdims=True)         # [1, F]
        scale = xp.maximum(amax, 1e-8) / 127.0
        q = xp.clip(xp.round(w2 / scale), -127, 127).astype(xp.int8)
        return QuantizedParam(
            q=q.reshape(shape),
            scale=scale.reshape((1,) + shape[n_contract:]),
            scheme="int8", shape=shape, n_contract=n_contract, group_size=0,
        )
    if shape[0] % 2:
        raise ValueError(
            f"int4 packing pairs elements along axis 0, which must be even "
            f"(kernel shape {shape})"
        )
    g = wq_group_size(K, group_size)
    G = K // g
    w3 = w2.reshape(G, g, F)
    amax = xp.max(xp.abs(w3), axis=1, keepdims=True)             # [G, 1, F]
    scale = xp.maximum(amax, 1e-8) / 7.0
    q = xp.clip(xp.round(w3 / scale), -7, 7).astype(xp.int8).reshape(shape)
    # Two codes per byte along axis 0: 2i → low nibble, 2i+1 → high.
    lo = q[0::2]
    hi = q[1::2]
    packed = xp.bitwise_or(
        xp.left_shift(hi, 4), xp.bitwise_and(lo, xp.int8(0x0F))
    ).astype(xp.int8)
    return QuantizedParam(
        q=packed,
        scale=scale.reshape((G,) + shape[n_contract:]).astype(xp.float32),
        scheme="int4", shape=shape, n_contract=n_contract, group_size=g,
    )


def _unpack_int4(packed, xp=jnp):
    """Inverse of the axis-0 nibble packing; arithmetic shifts sign-extend."""
    lo = xp.right_shift(xp.left_shift(packed, 4), 4)
    hi = xp.right_shift(packed, 4)
    stacked = xp.stack([lo, hi], axis=1)        # [s0/2, 2, ...]
    return stacked.reshape((packed.shape[0] * 2,) + tuple(packed.shape[1:]))


def dequantize_param(qp: QuantizedParam):
    """Float32 kernel of the original shape — the test oracle, and the
    definition of the 'dequant-transient' bytes the profiling breakdown
    accounts (``profiling/compile.py``)."""
    xp = _xp(qp.q)
    K = int(math.prod(qp.shape[:qp.n_contract]))
    F = int(math.prod(qp.feat_shape))
    if qp.scheme == "int8":
        w2 = qp.q.reshape(K, F).astype(xp.float32) * qp.scale.reshape(1, F)
        return w2.reshape(qp.shape)
    q = _unpack_int4(qp.q, xp).reshape(K, F)
    G = K // qp.group_size
    w3 = q.reshape(G, qp.group_size, F).astype(xp.float32)
    w3 = w3 * qp.scale.reshape(G, 1, F)
    return w3.reshape(qp.shape)


def wq_matmul(x: jax.Array, qp: QuantizedParam) -> jax.Array:
    """``x @ dequant(qp)`` with the dequant fused into the epilogue.

    x ``[..., K]`` float (K = flattened contraction dim); returns f32
    ``[..., F]``.  Activations are dynamically row-quantized to int8 so
    both schemes ride the int8×int8→int32 MXU path.
    """
    K = int(math.prod(qp.shape[:qp.n_contract]))
    F = int(math.prod(qp.feat_shape))
    x32 = x.astype(jnp.float32)
    s_x = _symmetric_scale(x32, axis=-1)                     # [..., 1]
    qx = jnp.round(x32 / s_x).astype(jnp.int8)
    if qp.scheme == "int8":
        acc = jax.lax.dot_general(
            qx, qp.q.reshape(K, F),
            (((qx.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc.astype(jnp.float32) * s_x * qp.scale.reshape(1, F)
    g = qp.group_size
    G = K // g
    lead = x.shape[:-1]
    qw = _unpack_int4(qp.q, jnp).reshape(G, g, F)
    qx3 = qx.reshape((-1, G, g))                             # [T, G, g]
    acc = jax.lax.dot_general(
        qx3, qw,
        (((2,), (1,)), ((1,), (0,))),                        # → [G, T, F]
        preferred_element_type=jnp.int32,
    )
    out = (acc.astype(jnp.float32) * qp.scale.reshape(G, 1, F)).sum(axis=0)
    out = out * s_x.reshape(-1, 1)
    return out.reshape(lead + (F,))


def wq_dense_axis_last(x, qp: QuantizedParam, bias=None, out_dtype=None):
    """DenseGeneral(axis=-1) over a stored-quantized kernel ``[K, *F]``."""
    out = wq_matmul(x, qp).reshape(x.shape[:-1] + qp.feat_shape)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


def wq_dense_axis_last2(x, qp: QuantizedParam, bias=None, out_dtype=None):
    """DenseGeneral(axis=(-2,-1)) over a stored-quantized ``[H, D, N]``."""
    H, D = qp.shape[0], qp.shape[1]
    out = wq_matmul(x.reshape(x.shape[:-2] + (H * D,)), qp)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


def wq_rule_for_path(path: str):
    """``n_contract`` when the "/"-joined tree path names a weight-quantized
    kernel, else ``None``."""
    for pattern, n_contract in WQ_PATH_RULES:
        if re.match(pattern, path):
            return n_contract
    return None


def _tree_path_str(path) -> str:
    parts = []
    for p in path:
        part = getattr(p, "key", None)
        if part is None:
            part = getattr(p, "idx", None)
        if part is None:
            part = getattr(p, "name", None)
        parts.append(str(p if part is None else part))
    return "/".join(parts)


def quantize_tree(
    params, scheme: str, group_size: int = WQ_DEFAULT_GROUP
):
    """Quantize every rule-matched kernel in a param tree.

    Leaves that match no rule pass through untouched; the result is the
    tree the WQ model modules (``models/layers.py``) expect.  Usable on
    host (numpy), on device (jnp), and under ``jax.eval_shape``.
    """
    def _leaf(path, leaf):
        n_contract = wq_rule_for_path(_tree_path_str(path))
        if n_contract is None:
            return leaf
        return quantize_array(leaf, scheme, n_contract, group_size)

    return jax.tree_util.tree_map_with_path(_leaf, params)


def _leaf_nbytes(leaf) -> int:
    return int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize


def param_tree_bytes(tree) -> dict:
    """Byte accounting for a (possibly quantized) param tree.

    ``stored_bytes`` is what actually lives in HBM (codes + scales +
    untouched float leaves); ``dequant_transient_bytes`` is the LARGEST
    would-be float kernel among quantized leaves — the epilogue-fused
    matmul never materializes more than one.  Works on arrays and
    ``ShapeDtypeStruct`` trees alike (the 8B budget test is abstract).
    """
    stored = quantized = float_bytes = 0
    transient = 0
    n_q = n_f = 0
    is_qp = lambda x: isinstance(x, QuantizedParam)  # noqa: E731
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=is_qp):
        if is_qp(leaf):
            n_q += 1
            b = _leaf_nbytes(leaf.q) + _leaf_nbytes(leaf.scale)
            quantized += b
            stored += b
            transient = max(
                transient, int(math.prod(leaf.shape)) * 4
            )
        else:
            n_f += 1
            b = _leaf_nbytes(leaf)
            float_bytes += b
            stored += b
    return {
        "stored_bytes": stored,
        "quantized_bytes": quantized,
        "float_bytes": float_bytes,
        "dequant_transient_bytes": transient,
        "n_quantized_leaves": n_q,
        "n_float_leaves": n_f,
    }
