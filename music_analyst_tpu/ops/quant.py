"""Dynamic int8 quantized matmul for inference (w8a8, int32 accumulate).

The roofline suite measures the v5e MXU at ~2.1× bf16 throughput for
int8×int8→int32 chains (``benchmarks/results/roofline.json``), and the
headline DistilBERT forward already runs at ~93% of the bf16 roofline —
so int8 is the remaining large FLOP lever.  This op quantizes on the fly:

* weights: symmetric per-output-channel, ``s_w[c] = max|w[:,c]| / 127`` —
  computed inside the jitted forward from the ordinary float params, so
  the param tree, checkpoint loaders, and sharding rules are untouched;
* activations: symmetric per-token (row-wise) dynamic,
  ``s_x[t] = max|x[t,:]| / 127`` — one outlier token costs only its own
  row's resolution, not the whole batch's (the per-tensor variant loses
  ~all precision on every other row once one activation spikes;
  ``tests/test_quant.py::test_outlier_token_does_not_poison_batch``);
* accumulation in int32 on the MXU, dequant ``acc · s_x[t] · s_w[c]``
  fused into the epilogue by XLA.

Accuracy contract: quantization error is bounded by the symmetric-int8
resolution (~0.8% of the dynamic range per operand); the classifier's
2→3-label thresholding absorbs small logit shifts, and
``tests/test_quant.py`` pins both the op-level error and end-to-end label
agreement.  No reference analogue (the reference's model lives behind an
HTTP API); this is a TPU-hardware play, default OFF (``quant="none"``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _symmetric_scale(value: jax.Array, axis, keepdims: bool = True):
    amax = jnp.max(jnp.abs(value), axis=axis, keepdims=keepdims)
    return jnp.maximum(amax, 1e-8) / 127.0


def quant_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x @ w`` via dynamic int8: x ``[..., K]`` f32/bf16, w ``[K, N]``.

    Returns f32 ``[..., N]``.
    """
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    s_x = _symmetric_scale(x32, axis=-1)  # [..., 1] per token
    s_w = _symmetric_scale(w32, axis=0)   # [1, N] per channel
    qx = jnp.round(x32 / s_x).astype(jnp.int8)
    qw = jnp.round(w32 / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qx, qw,
        (((qx.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * s_x * s_w.reshape(1, -1)


def quant_batched_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Per-expert ``x[e] @ w[e]``: x ``[E, C, K]``, w ``[E, K, N]`` → f32
    ``[E, C, N]``.

    The MoE expert einsums (``models/moe.py``) are batched matmuls with a
    leading expert axis; scales follow the same symmetric scheme as
    :func:`quant_matmul`, kept **per expert**: activations per ``(e, row)``
    (one hot expert's buffer rows can't poison another's resolution),
    weights per ``(e, out-channel)``.  Accumulation is int32 on the MXU
    with the dequant fused into the epilogue; the expert batch axis maps
    onto dot_general batch dims, so an ``ep``-sharded weight stack shards
    the quantized compute identically to the float path.
    """
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    s_x = _symmetric_scale(x32, axis=-1)  # [E, C, 1] per expert-row
    s_w = _symmetric_scale(w32, axis=1)   # [E, 1, N] per expert-channel
    qx = jnp.round(x32 / s_x).astype(jnp.int8)
    qw = jnp.round(w32 / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qx, qw,
        (((2,), (1,)), ((0,), (0,))),     # contract K, batch over E
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * s_x * s_w


def quant_dense_axis_last(x, kernel, bias=None, out_dtype=None):
    """DenseGeneral(axis=-1): x ``[..., K]``, kernel ``[K, *F]`` → ``[..., *F]``."""
    feat_shape = kernel.shape[1:]
    out = quant_matmul(x, kernel.reshape(kernel.shape[0], -1))
    out = out.reshape(x.shape[:-1] + feat_shape)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)


def quant_dense_axis_last2(x, kernel, bias=None, out_dtype=None):
    """DenseGeneral(axis=(-2,-1)): x ``[..., H, D]``, kernel ``[H, D, N]``."""
    H, D, N = kernel.shape
    out = quant_matmul(x.reshape(x.shape[:-2] + (H * D,)), kernel.reshape(H * D, N))
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype or x.dtype)
