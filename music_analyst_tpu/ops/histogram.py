"""Dense token-id histograms with a single ``psum`` reduction.

This op replaces the reference's entire aggregation machinery: per-rank
string hash tables (``src/parallel_spotify.c:38-175``), the serialized
Send/Recv wire protocol (``:396-432``), and the rank-0 sequential merge
(``:1011-1025``).  With ids dense on the host side (``data/vocab.py``), the
per-chip histogram is one scatter-add and the cross-chip merge is one
all-reduce over ICI — O(vocab) bytes in a single collective instead of
O(entries) point-to-point string messages.

Design note — why there is no Pallas histogram kernel: scatter-add over a
large vocabulary is sort-shaped, and XLA's TPU lowering of ``.at[].add``
already emits the sort-based segmented reduction that suits the hardware
(SURVEY.md §7 step 3 says "Pallas scatter-add if profiling demands" — it
doesn't: the wordcount path is host-ingest-bound, see ``engines/sweep``
timings).  A hand kernel would have to one-hot compare each id block
against the vocab (O(N·V) VPU work) — strictly worse than XLA's O(N log N).
The Pallas budget went to the ops where explicit locality wins:
``ops/flash_attention.py`` and ``ops/pallas_keyword.py``.
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache, partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from music_analyst_tpu.profiling.collectives import record_collective
from music_analyst_tpu.profiling.compile import profiled_jit
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.utils.jax_compat import shard_map
from music_analyst_tpu.utils.shapes import round_pow2

PAD_ID = -1


def _token_histogram(ids: jax.Array, vocab_size: int) -> jax.Array:
    """Count id occurrences; ``PAD_ID`` (any negative id) is ignored.

    One fused masked scatter-add; int32 counts (the per-word corpus bound is
    well under 2^31 even for the 1M-song dataset).
    """
    valid = ids >= 0
    clipped = jnp.where(valid, ids, 0)
    return jnp.zeros((vocab_size,), jnp.int32).at[clipped].add(
        valid.astype(jnp.int32), mode="drop"
    )


token_histogram = profiled_jit(
    _token_histogram, name="token_histogram",
    static_argnames=("vocab_size",),
)


def shard_pad(values: np.ndarray, shards: int, pad_value: int) -> np.ndarray:
    """Right-pad a flat array so it splits evenly into ``shards`` pieces."""
    n = values.shape[0]
    padded_len = max(1, -(-n // shards)) * shards
    if padded_len == n:
        return values
    out = np.full((padded_len,), pad_value, dtype=values.dtype)
    out[:n] = values
    return out


# Shared power-of-two shape policy (utils/shapes.py).
_bucket = round_pow2


def _bucket_linear(n: int, step: int) -> int:
    """Round up to a multiple of ``step``: bounded shape count with far
    less padding than power-of-two buckets (padding is transferred to the
    device, and host→device bandwidth is the wordcount bottleneck)."""
    return max(step, -(-n // step) * step)


# --- compiled-collective cache -------------------------------------------
#
# The shard_map callables below are built once per (mesh, axis[, vocab]) and
# memoized: constructing ``jax.jit(shard_map(lambda ...))`` inside every
# call would miss jit's own cache on every invocation (fresh lambda
# identity) and re-trace — which made sweep wall-times compilation-bound
# rather than scaling-meaningful.  ``Mesh`` is hashable by (devices, axis
# names), so it is a sound cache key; the handful of meshes a process ever
# builds bounds the cache.

@lru_cache(maxsize=None)
def _psum_ids_histogram(mesh: Mesh, axis: str, padded_vocab: int):
    def local(x):
        return jax.lax.psum(token_histogram(x, padded_vocab), axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P()),
        name="psum_ids_histogram",
    )


@lru_cache(maxsize=None)
def _psum_rows(mesh: Mesh, axis: str):
    def local(h):
        return jax.lax.psum(h[0], axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis, None), out_specs=P()),
        name="psum_rows",
    )


@lru_cache(maxsize=None)
def _psum_scalar(mesh: Mesh, axis: str):
    def local(x):
        return jax.lax.psum(jnp.sum(x), axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P()),
        name="psum_scalar",
    )


def sharded_histogram(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> jax.Array:
    """Global histogram of ``ids`` sharded over ``axis`` of ``mesh``.

    Each device scatter-adds its shard into a local dense vector, then one
    ``psum`` over ``axis`` produces the replicated global histogram — the
    TPU-native equivalent of the reference's hash-table shuffle + merge
    (SURVEY.md §2.4 key insight).

    Both the id-array length and the vocab size are bucketed to powers of
    two (padding ids are ignored, excess vocab slots read zero and are
    sliced off), so different corpora reuse the same compiled program.
    """
    ids = np.asarray(ids, dtype=np.int32)
    bucket_len = _bucket_linear(ids.shape[0], 1 << 22)
    padded = np.full((bucket_len,), PAD_ID, dtype=np.int32)
    padded[: ids.shape[0]] = ids
    padded = shard_pad(padded, mesh.shape[axis], PAD_ID)
    padded_vocab = _bucket(vocab_size, 1 << 10)
    # Each device all-reduces its padded_vocab-wide int32 histogram.
    record_collective(
        "histogram.device_ids", "psum",
        payload_bytes=padded_vocab * 4, n_devices=mesh.shape[axis],
        axis=axis,
    )
    fault_point("collective.psum", op="histogram.device_ids")
    return _psum_ids_histogram(mesh, axis, padded_vocab)(padded)[:vocab_size]


@dataclasses.dataclass(frozen=True)
class HistogramTimings:
    """Per-shard measured compute for the host-local histogram.

    ``count_seconds[i]`` is shard *i*'s own counting wall-clock — the honest
    analogue of each MPI rank timing its local count loop
    (``src/parallel_spotify.c:850-851,1000``); they genuinely differ across
    shards.  ``merge_seconds`` is the lock-stepped collective (every chip
    spends it together — one SPMD program).
    """

    count_seconds: Tuple[float, ...]
    merge_seconds: float

    def per_chip_seconds(self) -> List[float]:
        return [s + self.merge_seconds for s in self.count_seconds]


def sharded_histogram_hostlocal_timed(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> Tuple[np.ndarray, HistogramTimings]:
    """Histogram with host-local counting and a device ``psum`` merge.

    The locality structure of a multi-host deployment (and of the
    reference): each shard's ids are counted where they were ingested and
    only dense count vectors cross to the device for the collective merge.
    Per-shard transfer is O(vocab), not O(tokens) — the right trade when
    the token matrix has no other reason to be device-resident (the
    ``sharded_histogram`` ids-on-device path serves the joint pipeline,
    where it does).

    Returns the counts plus measured :class:`HistogramTimings` (each
    shard's count phase timed individually — the per-rank timing column the
    metrics writer reports).
    """
    ids = np.asarray(ids, dtype=np.int32)
    shards = mesh.shape[axis]
    padded_vocab = _bucket(vocab_size, 1 << 10)
    chunks = np.array_split(ids, shards)
    local = np.zeros((shards, padded_vocab), dtype=np.int32)
    count_seconds = []
    for i, chunk in enumerate(chunks):
        t0 = time.perf_counter()
        valid = chunk[chunk >= 0]
        if valid.size:
            local[i] = np.bincount(valid, minlength=padded_vocab)
        count_seconds.append(time.perf_counter() - t0)
    record_collective(
        "histogram.hostlocal_merge", "psum",
        payload_bytes=padded_vocab * 4, n_devices=shards, axis=axis,
    )
    t0 = time.perf_counter()
    fault_point("collective.psum", op="histogram.hostlocal_merge")
    # np.asarray IS the sync point (axon tunnel gotcha — see engine note).
    merged = np.asarray(_psum_rows(mesh, axis)(local))[:vocab_size]
    merge_seconds = time.perf_counter() - t0
    return merged, HistogramTimings(tuple(count_seconds), merge_seconds)


def sharded_histogram_hostlocal(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> np.ndarray:
    """:func:`sharded_histogram_hostlocal_timed` without the timings."""
    counts, _ = sharded_histogram_hostlocal_timed(ids, vocab_size, mesh, axis)
    return counts


# --- chunked streaming device path ----------------------------------------
#
# ``sharded_histogram`` device-puts the whole id stream at once: simple,
# but peak host+device memory is O(corpus).  The streaming path below
# instead walks fixed-size song-aligned chunks through the shared
# ``runtime/prefetch.py`` pipeline — pad (host) → H2D → accumulate into a
# per-chip dense histogram — and pays the single ``psum`` only once at the
# end.  Chunk lengths are power-of-two bucketed, so every chunk reuses ONE
# compiled accumulate program, and the H2D of chunk k+1 overlaps the
# scatter-add of chunk k.  Peak memory is O(chunk · depth), independent of
# corpus size — the property the million-song north star needs.

_AUTO_STREAM_MIN_TOKENS = 1 << 22   # below this, chunking is pure overhead
_AUTO_CHUNK_TARGET_TOKENS = 1 << 21  # ~8 MiB of int32 ids per chunk
_STREAM_CHUNK_FLOOR = 1 << 12


def resolve_chunk_songs(
    chunk_songs, song_count: int, token_count: int
) -> int:
    """Resolve a ``--chunk-songs`` value to songs per chunk (0 = off).

    Explicit ``0`` disables streaming; an explicit positive value is
    clamped to the corpus.  ``None``/``"auto"`` streams only when the
    corpus is big enough for chunking to pay (small corpora keep the
    single-put paths and their per-shard timing semantics), sizing chunks
    so each carries ~``_AUTO_CHUNK_TARGET_TOKENS`` ids.
    """
    if chunk_songs is not None and chunk_songs != "auto":
        n = int(chunk_songs)
        if n < 0:
            raise ValueError(f"chunk-songs must be >= 0, got {n}")
        return 0 if n == 0 else min(n, max(1, song_count))
    if token_count < _AUTO_STREAM_MIN_TOKENS or song_count <= 1:
        return 0
    avg_tokens = max(1.0, token_count / song_count)
    return max(1, min(song_count, int(_AUTO_CHUNK_TARGET_TOKENS / avg_tokens)))


@lru_cache(maxsize=None)
def _stream_accum(mesh: Mesh, axis: str, padded_vocab: int):
    """One streaming step: add a chunk's per-shard histogram into the
    running per-chip accumulator.  No collective here — chips stay
    independent until the final ``_psum_rows`` merge."""

    def local(hist, ids):
        return hist + _token_histogram(ids, padded_vocab)[None, :]

    return profiled_jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(axis, None), P(axis)), out_specs=P(axis, None),
        ),
        name="stream_accum_histogram",
    )


def sharded_histogram_streaming(
    ids: np.ndarray,
    offsets: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
    chunk_songs: int = 0,
    prefetch_depth=None,
) -> np.ndarray:
    """Global histogram via bounded chunks overlapped with H2D transfer.

    ``offsets`` (int64 ``[songs+1]``, from ``IngestResult``) keeps chunks
    song-aligned, so ``--chunk-songs`` means what it says.  Identical
    counts to :func:`sharded_histogram` at every chunk size — padding ids
    are ``PAD_ID`` and the scatter-add drops them.
    """
    from music_analyst_tpu.runtime.prefetch import (
        PrefetchPipeline, Stage, resolve_prefetch_depth,
    )
    from music_analyst_tpu.telemetry import get_telemetry

    ids = np.asarray(ids) if ids.dtype == np.int32 else np.asarray(
        ids, dtype=np.int32
    )
    offsets = np.asarray(offsets, dtype=np.int64)
    song_count = offsets.shape[0] - 1
    if chunk_songs <= 0:
        raise ValueError("sharded_histogram_streaming needs chunk_songs > 0")
    if song_count <= 0 or ids.shape[0] == 0:
        return np.zeros((vocab_size,), dtype=np.int32)
    shards = mesh.shape[axis]
    padded_vocab = _bucket(vocab_size, 1 << 10)
    bounds = list(range(0, song_count, chunk_songs)) + [song_count]
    token_bounds = [int(offsets[b]) for b in bounds]
    max_chunk_tokens = max(
        e - s for s, e in zip(token_bounds, token_bounds[1:])
    )
    # One compiled program for every chunk: pow2-bucket the chunk length,
    # then round up so it splits evenly over the shards.
    bucket_len = _bucket(max(1, max_chunk_tokens), _STREAM_CHUNK_FLOOR)
    bucket_len = -(-bucket_len // shards) * shards
    chunk_sharding = NamedSharding(mesh, P(axis))
    hist_sharding = NamedSharding(mesh, P(axis, None))
    accum = _stream_accum(mesh, axis, padded_vocab)

    def _pad(span):
        start, end = span
        chunk = np.full((bucket_len,), PAD_ID, dtype=np.int32)
        chunk[: end - start] = ids[start:end]
        return chunk

    def _h2d(chunk):
        return jax.device_put(chunk, chunk_sharding)

    hist = jax.device_put(
        np.zeros((shards, padded_vocab), dtype=np.int32), hist_sharding
    )
    n_chunks = len(token_bounds) - 1
    depth = resolve_prefetch_depth(prefetch_depth)
    pipe = PrefetchPipeline(
        stages=[Stage("chunk_pad", _pad), Stage("h2d", _h2d)],
        depth=depth,
        name="stream_histogram",
        sink_name="accumulate",
    )
    for dev_chunk in pipe.run(zip(token_bounds, token_bounds[1:])):
        hist = accum(hist, dev_chunk)
    tel = get_telemetry()
    tel.count("histogram.stream_chunks", n_chunks)
    tel.count("histogram.stream_h2d_bytes", n_chunks * bucket_len * 4)
    record_collective(
        "histogram.stream_merge", "psum",
        payload_bytes=padded_vocab * 4, n_devices=shards, axis=axis,
    )
    fault_point("collective.psum", op="histogram.stream_merge")
    # np.asarray IS the sync point (axon tunnel gotcha — see engine note).
    return np.asarray(_psum_rows(mesh, axis)(hist))[:vocab_size]


def sharded_total(values: np.ndarray, mesh: Mesh, axis: str = "dp") -> int:
    """``psum`` of per-shard scalar contributions.

    The analogue of the reference's grand-total reduction
    (``MPI_Reduce(SUM)``, ``src/parallel_spotify.c:1004-1005``); padding
    contributes zeros.
    """
    padded = shard_pad(np.asarray(values, dtype=np.int64), mesh.shape[axis], 0)
    record_collective(
        "histogram.scalar_total", "psum",
        payload_bytes=8, n_devices=mesh.shape[axis], axis=axis,
    )
    fault_point("collective.psum", op="histogram.scalar_total")
    return int(_psum_scalar(mesh, axis)(padded))
