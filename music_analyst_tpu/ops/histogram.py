"""Dense token-id histograms with a single ``psum`` reduction.

This op replaces the reference's entire aggregation machinery: per-rank
string hash tables (``src/parallel_spotify.c:38-175``), the serialized
Send/Recv wire protocol (``:396-432``), and the rank-0 sequential merge
(``:1011-1025``).  With ids dense on the host side (``data/vocab.py``), the
per-chip histogram is one scatter-add and the cross-chip merge is one
all-reduce over ICI — O(vocab) bytes in a single collective instead of
O(entries) point-to-point string messages.

Design note — why there is no Pallas histogram kernel: scatter-add over a
large vocabulary is sort-shaped, and XLA's TPU lowering of ``.at[].add``
already emits the sort-based segmented reduction that suits the hardware
(SURVEY.md §7 step 3 says "Pallas scatter-add if profiling demands" — it
doesn't: the wordcount path is host-ingest-bound, see ``engines/sweep``
timings).  A hand kernel would have to one-hot compare each id block
against the vocab (O(N·V) VPU work) — strictly worse than XLA's O(N log N).
The Pallas budget went to the ops where explicit locality wins:
``ops/flash_attention.py`` and ``ops/pallas_keyword.py``.
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache, partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from music_analyst_tpu.profiling.collectives import record_collective
from music_analyst_tpu.profiling.compile import profiled_jit
from music_analyst_tpu.utils.jax_compat import shard_map
from music_analyst_tpu.utils.shapes import round_pow2

PAD_ID = -1


def _token_histogram(ids: jax.Array, vocab_size: int) -> jax.Array:
    """Count id occurrences; ``PAD_ID`` (any negative id) is ignored.

    One fused masked scatter-add; int32 counts (the per-word corpus bound is
    well under 2^31 even for the 1M-song dataset).
    """
    valid = ids >= 0
    clipped = jnp.where(valid, ids, 0)
    return jnp.zeros((vocab_size,), jnp.int32).at[clipped].add(
        valid.astype(jnp.int32), mode="drop"
    )


token_histogram = profiled_jit(
    _token_histogram, name="token_histogram",
    static_argnames=("vocab_size",),
)


def shard_pad(values: np.ndarray, shards: int, pad_value: int) -> np.ndarray:
    """Right-pad a flat array so it splits evenly into ``shards`` pieces."""
    n = values.shape[0]
    padded_len = max(1, -(-n // shards)) * shards
    if padded_len == n:
        return values
    out = np.full((padded_len,), pad_value, dtype=values.dtype)
    out[:n] = values
    return out


# Shared power-of-two shape policy (utils/shapes.py).
_bucket = round_pow2


def _bucket_linear(n: int, step: int) -> int:
    """Round up to a multiple of ``step``: bounded shape count with far
    less padding than power-of-two buckets (padding is transferred to the
    device, and host→device bandwidth is the wordcount bottleneck)."""
    return max(step, -(-n // step) * step)


# --- compiled-collective cache -------------------------------------------
#
# The shard_map callables below are built once per (mesh, axis[, vocab]) and
# memoized: constructing ``jax.jit(shard_map(lambda ...))`` inside every
# call would miss jit's own cache on every invocation (fresh lambda
# identity) and re-trace — which made sweep wall-times compilation-bound
# rather than scaling-meaningful.  ``Mesh`` is hashable by (devices, axis
# names), so it is a sound cache key; the handful of meshes a process ever
# builds bounds the cache.

@lru_cache(maxsize=None)
def _psum_ids_histogram(mesh: Mesh, axis: str, padded_vocab: int):
    def local(x):
        return jax.lax.psum(token_histogram(x, padded_vocab), axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P()),
        name="psum_ids_histogram",
    )


@lru_cache(maxsize=None)
def _psum_rows(mesh: Mesh, axis: str):
    def local(h):
        return jax.lax.psum(h[0], axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis, None), out_specs=P()),
        name="psum_rows",
    )


@lru_cache(maxsize=None)
def _psum_scalar(mesh: Mesh, axis: str):
    def local(x):
        return jax.lax.psum(jnp.sum(x), axis)

    return profiled_jit(
        shard_map(local, mesh=mesh, in_specs=P(axis), out_specs=P()),
        name="psum_scalar",
    )


def sharded_histogram(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> jax.Array:
    """Global histogram of ``ids`` sharded over ``axis`` of ``mesh``.

    Each device scatter-adds its shard into a local dense vector, then one
    ``psum`` over ``axis`` produces the replicated global histogram — the
    TPU-native equivalent of the reference's hash-table shuffle + merge
    (SURVEY.md §2.4 key insight).

    Both the id-array length and the vocab size are bucketed to powers of
    two (padding ids are ignored, excess vocab slots read zero and are
    sliced off), so different corpora reuse the same compiled program.
    """
    ids = np.asarray(ids, dtype=np.int32)
    bucket_len = _bucket_linear(ids.shape[0], 1 << 22)
    padded = np.full((bucket_len,), PAD_ID, dtype=np.int32)
    padded[: ids.shape[0]] = ids
    padded = shard_pad(padded, mesh.shape[axis], PAD_ID)
    padded_vocab = _bucket(vocab_size, 1 << 10)
    # Each device all-reduces its padded_vocab-wide int32 histogram.
    record_collective(
        "histogram.device_ids", "psum",
        payload_bytes=padded_vocab * 4, n_devices=mesh.shape[axis],
        axis=axis,
    )
    return _psum_ids_histogram(mesh, axis, padded_vocab)(padded)[:vocab_size]


@dataclasses.dataclass(frozen=True)
class HistogramTimings:
    """Per-shard measured compute for the host-local histogram.

    ``count_seconds[i]`` is shard *i*'s own counting wall-clock — the honest
    analogue of each MPI rank timing its local count loop
    (``src/parallel_spotify.c:850-851,1000``); they genuinely differ across
    shards.  ``merge_seconds`` is the lock-stepped collective (every chip
    spends it together — one SPMD program).
    """

    count_seconds: Tuple[float, ...]
    merge_seconds: float

    def per_chip_seconds(self) -> List[float]:
        return [s + self.merge_seconds for s in self.count_seconds]


def sharded_histogram_hostlocal_timed(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> Tuple[np.ndarray, HistogramTimings]:
    """Histogram with host-local counting and a device ``psum`` merge.

    The locality structure of a multi-host deployment (and of the
    reference): each shard's ids are counted where they were ingested and
    only dense count vectors cross to the device for the collective merge.
    Per-shard transfer is O(vocab), not O(tokens) — the right trade when
    the token matrix has no other reason to be device-resident (the
    ``sharded_histogram`` ids-on-device path serves the joint pipeline,
    where it does).

    Returns the counts plus measured :class:`HistogramTimings` (each
    shard's count phase timed individually — the per-rank timing column the
    metrics writer reports).
    """
    ids = np.asarray(ids, dtype=np.int32)
    shards = mesh.shape[axis]
    padded_vocab = _bucket(vocab_size, 1 << 10)
    chunks = np.array_split(ids, shards)
    local = np.zeros((shards, padded_vocab), dtype=np.int32)
    count_seconds = []
    for i, chunk in enumerate(chunks):
        t0 = time.perf_counter()
        valid = chunk[chunk >= 0]
        if valid.size:
            local[i] = np.bincount(valid, minlength=padded_vocab)
        count_seconds.append(time.perf_counter() - t0)
    record_collective(
        "histogram.hostlocal_merge", "psum",
        payload_bytes=padded_vocab * 4, n_devices=shards, axis=axis,
    )
    t0 = time.perf_counter()
    # np.asarray IS the sync point (axon tunnel gotcha — see engine note).
    merged = np.asarray(_psum_rows(mesh, axis)(local))[:vocab_size]
    merge_seconds = time.perf_counter() - t0
    return merged, HistogramTimings(tuple(count_seconds), merge_seconds)


def sharded_histogram_hostlocal(
    ids: np.ndarray,
    vocab_size: int,
    mesh: Mesh,
    axis: str = "dp",
) -> np.ndarray:
    """:func:`sharded_histogram_hostlocal_timed` without the timings."""
    counts, _ = sharded_histogram_hostlocal_timed(ids, vocab_size, mesh, axis)
    return counts


def sharded_total(values: np.ndarray, mesh: Mesh, axis: str = "dp") -> int:
    """``psum`` of per-shard scalar contributions.

    The analogue of the reference's grand-total reduction
    (``MPI_Reduce(SUM)``, ``src/parallel_spotify.c:1004-1005``); padding
    contributes zeros.
    """
    padded = shard_pad(np.asarray(values, dtype=np.int64), mesh.shape[axis], 0)
    record_collective(
        "histogram.scalar_total", "psum",
        payload_bytes=8, n_devices=mesh.shape[axis], axis=axis,
    )
    return int(_psum_scalar(mesh, axis)(padded))
