"""Device-side compute kernels (JAX/XLA, with Pallas variants for hot ops)."""

from music_analyst_tpu.ops.histogram import (
    sharded_histogram,
    token_histogram,
)
