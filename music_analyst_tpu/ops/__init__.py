"""Device-side compute kernels (JAX/XLA, with Pallas variants for hot ops)."""

from music_analyst_tpu.ops.histogram import (
    sharded_histogram,
    sharded_histogram_hostlocal,
    sharded_histogram_hostlocal_timed,
    token_histogram,
)
from music_analyst_tpu.ops.quant import quant_matmul
