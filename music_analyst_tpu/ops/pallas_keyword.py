"""Pallas TPU kernel for the keyword-sentiment scan.

Semantics source: the reference's ``--mock`` heuristic
(``scripts/sentiment_classifier.py:66-83``), via the shared helpers in
``ops/keyword_sentiment.py``.

The XLA formulation (``ops/keyword_sentiment.py``) emits ~10 shifted
compare/AND/OR chains over the byte matrix; XLA fuses them, but each
keyword's chain re-reads the block from HBM unless the fusion heuristics
cooperate.  This kernel makes the locality explicit: one row-block of
lyrics bytes is staged into VMEM once, lowercased once, and all ten
keyword scans plus the score combine run out of that single staging —
one HBM pass total, VPU-only work.

Grid: one program per row block (rows sized to the VMEM budget); the full
byte length ``L`` (multiple of 128 lanes) sits in the lane dimension.
Output is the int32 score broadcast across a 128-lane row (TPU-friendly 2D
output); the host wrapper slices lane 0.

Measured on v5e (8192×2048 bytes): 33.4k songs/s vs 36.6k for the XLA
formulation — XLA's fusion already keeps this op in one HBM pass, so the
kernel is kept as the validated hand-scheduled alternative (and the
template for ops XLA fuses less well), not as the default path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from music_analyst_tpu.ops.keyword_sentiment import (
    NEGATIVE_KEYWORDS,
    POSITIVE_KEYWORDS,
    _contains,
    _lower_ascii,
)

def _tile_rows(length: int) -> int:
    """Rows per grid step, sized to the ~16 MB VMEM budget.

    Mosaic's allocator reports ~70 bytes of scoped VMEM per input lyric
    byte at this kernel's live-range (widened i32 copy + the shifted
    compare masks kept live across the keyword chains).  Keep the sublane
    count a multiple of 32 (int8 tiling) with a floor of 32 rows.
    """
    budget = 12 * 1024 * 1024
    rows = budget // (length * 70)
    rows = max(32, min(256, (rows // 32) * 32))
    return rows


def _keyword_arrays():
    pos = [np.frombuffer(k.encode(), dtype=np.uint8) for k in POSITIVE_KEYWORDS]
    neg = [np.frombuffer(k.encode(), dtype=np.uint8) for k in NEGATIVE_KEYWORDS]
    return pos, neg


def _scan_kernel(x_ref, out_ref):
    # Mosaic vector arithmetic needs >= 16-bit lanes; widen the bytes once,
    # then reuse the XLA formulation's lowercase/containment helpers so the
    # matching semantics live in exactly one place.
    x = _lower_ascii(x_ref[:].astype(jnp.int32))   # [TILE_B, L]
    score = jnp.zeros((x.shape[0],), jnp.int32)
    pos, neg = _keyword_arrays()
    for sign, keywords in ((1, pos), (-1, neg)):
        for kw in keywords:
            hit = _contains(x, kw.astype(np.int32))
            score = score + sign * hit.astype(jnp.int32)
    out_ref[:] = jnp.broadcast_to(score[:, None], (x.shape[0], 128))


@functools.partial(jax.jit, static_argnames=("interpret", "tile_b"))
def _pallas_scores(
    batch: jax.Array, tile_b: int, interpret: bool = False
) -> jax.Array:
    B, L = batch.shape
    grid = (B // tile_b,)
    return pl.pallas_call(
        _scan_kernel,
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.int32),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (tile_b, L),
                    lambda i: (i, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (tile_b, 128),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        interpret=interpret,
    )(batch)


def keyword_scores_pallas(batch: np.ndarray) -> np.ndarray:
    """Scores for a padded uint8 batch ``[B, L]``; pads B to the tile size.

    ``L`` must be a multiple of 128 (the encoder's window sizes are).
    Falls back to interpreter mode off-TPU so tests exercise the same
    kernel logic on the CPU mesh.
    """
    B, L = batch.shape
    if L % 128 != 0:
        raise ValueError(f"byte length {L} must be a multiple of 128")
    tile_b = _tile_rows(L)
    padded_b = -(-B // tile_b) * tile_b
    if padded_b != B:
        batch = np.pad(batch, ((0, padded_b - B), (0, 0)))
    interpret = jax.default_backend() != "tpu"
    out = _pallas_scores(jnp.asarray(batch), tile_b, interpret=interpret)
    return np.asarray(out[:B, 0])
