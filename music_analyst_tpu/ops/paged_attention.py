"""Fused Pallas paged-attention decode kernel over the shared page pool.

``ops/kv_pages.py`` originally ran decode by materializing a contiguous
``[n_slots, max_total]`` copy of every slot's KV through the page table
(gather), running dense attention over the copy, and scattering the
touched pages back — three extra HBM passes over the whole resident KV
per decode dispatch, measured at ~25% decode overhead vs the monolithic
slot runtime on decode-heavy no-prefix workloads (PERFORMANCE.md).  This
module removes the copy: one fused kernel walks the ``(n_slots,
pages_per_slot)`` int32 page table *inside* the program, streams KV
pages through VMEM, and reduces — gather + QK + softmax + V in a single
``pallas_call``, with the page pool bound as an ``ANY``-space operand so
no contiguous view is ever materialized.

Two kernel bodies, chosen statically by backend:

* **exact batched body** (interpret mode / the CPU-emulated test mesh):
  one program over the whole batch; the in-kernel take-gather feeds the
  *verbatim* ops of the dense reference
  (``models/layers.dot_product_attention`` over the gathered view) —
  same ``repeat``-broadcast GQA, same einsum subscripts, same cast/scale
  order — so interpret-mode lowering is **bitwise** identical to the
  retired gather path.  (A no-repeat grouped contraction is
  mathematically equal but reassociates the head broadcast, and a
  1-ulp logit difference flips greedy argmax near-ties; the streaming
  TPU body keeps the grouped form since on-chip it IS the lowering.)
* **streaming body** (real TPU): grid over slots; each program walks its
  table row, DMAs one page at a time into VMEM scratch
  (``pltpu.make_async_copy``), and folds it into an online-softmax
  accumulator (running max / normalizer / weighted-V, masked lanes
  contribute exact zeros) — O(page) VMEM regardless of context length.

int8 KV pages (``ops/quant.quantize_kv_page``): both bodies accept
optional per-(page, row) f32 scale pools and fuse the dequant into the
KV-load epilogue — codes go ``int8 → f32 × scale → bf16`` right after
the gather/DMA, before QK.  The fp16/bf16 path stays byte-identical to
the retired gather runtime; int8 carries a bounded-error contract
instead (``tests/test_paged_attention.py``).

:class:`PagedAttnView` is the cache-shaped adapter: a registered
dataclass carrying (pool, scales, table, write offsets) that duck-types
``models/layers.KVCache`` — ``update`` writes the new token's KV row
directly into its physical page (quantizing per-row for int8) and
``attend`` invokes the kernel — so the paged decode runtime passes it
through the unmodified model stack and the whole decode span runs with
no per-dispatch gather/pad/scatter.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Masked logit value.  The exact body uses finfo.min to match the dense
# reference bitwise; the streaming body's running max starts here and
# masked lanes are zeroed explicitly, so the sentinel never reaches exp.
_NEG_INF = -1e30


def _geometry(q, key_pages, table, mask):
    n, q_len, H, D = q.shape
    if q_len != 1:
        raise ValueError(
            f"paged_attention is a decode kernel (q_len == 1), got {q_len}"
        )
    P, n_kv = key_pages.shape[1], key_pages.shape[2]
    if key_pages.shape[3] != D:
        raise ValueError(
            f"head_dim mismatch: q has {D}, pages have {key_pages.shape[3]}"
        )
    if H % n_kv:
        raise ValueError(f"n_heads ({H}) not divisible by n_kv ({n_kv})")
    pps = table.shape[1]
    total = mask.shape[-1]
    if total > pps * P:
        raise ValueError(
            f"mask width ({total}) exceeds slot span ({pps * P})"
        )
    return n, H, n_kv, D, P, pps, total


def _dequant(codes, scale, dtype):
    """int8 codes → compute dtype, scale broadcast over (n_kv, head_dim)."""
    return (codes.astype(jnp.float32) * scale[..., None, None]).astype(dtype)


def _exact_body(n, H, n_kv, D, P, pps, total, quantized, dtype):
    """One program, whole batch: in-kernel gather + the dense reference.

    Bitwise-identical to dense attention over the gathered contiguous
    view (tests/test_paged_attention.py pins this at page sizes 8 and
    16): after the gather, the ops ARE ``dot_product_attention``'s —
    ``repeat``-broadcast GQA, the same einsum subscripts, fp32 cast
    before the ``D**-0.5`` scale, ``finfo.min`` masking, softmax cast
    back to ``q.dtype``.  Any algebraic shortcut here (e.g. contracting
    groups without the repeat) reassociates multiply-adds, and a 1-ulp
    logit difference flips greedy argmax near-ties — the byte-identity
    contract forbids it.
    """
    span = pps * P
    G = H // n_kv
    att_scale = D ** -0.5

    def body(table_ref, mask_ref, q_ref, kp_ref, vp_ref, *rest):
        if quantized:
            ks_ref, vs_ref, o_ref = rest
        else:
            (o_ref,) = rest
        k = jnp.take(kp_ref[:], table_ref[:], axis=0)  # [n, pps, P, kv, D]
        v = jnp.take(vp_ref[:], table_ref[:], axis=0)
        if quantized:
            sk = jnp.take(ks_ref[:], table_ref[:], axis=0)  # [n, pps, P]
            sv = jnp.take(vs_ref[:], table_ref[:], axis=0)
            k = _dequant(k, sk, dtype)
            v = _dequant(v, sv, dtype)
        k = k.reshape(n, span, n_kv, D)[:, :total]
        v = v.reshape(n, span, n_kv, D)[:, :total]
        q = q_ref[:]
        if n_kv != H:
            k = jnp.repeat(k, G, axis=2)
            v = jnp.repeat(v, G, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        s = s * att_scale
        s = jnp.where(
            mask_ref[:][:, None, None, :total], s, jnp.finfo(jnp.float32).min
        )
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o_ref[:] = jnp.einsum("bhqk,bkhd->bqhd", p, v)

    return body


def _stream_body(n, H, n_kv, D, P, pps, total, quantized, dtype):
    """Per-slot program: DMA one page at a time, online-softmax fold.

    The page walk is a static unroll over the slot's table row; each
    page is copied pool → VMEM scratch with ``make_async_copy`` (the
    dequant epilogue runs on the scratch block for int8), contributes a
    ``[H, P]`` logit tile, and folds into the running (max, normalizer,
    weighted-V) accumulator.  Masked lanes are zeroed *after* the exp,
    so fully-masked pages (the slack tail past ``total``, a free slot's
    trash pages) contribute exactly nothing.
    """
    G = H // n_kv
    att_scale = D ** -0.5

    def body(table_ref, mask_ref, q_ref, kp_ref, vp_ref, *rest):
        if quantized:
            (ks_ref, vs_ref, o_ref,
             kbuf, vbuf, ksbuf, vsbuf, sem) = rest
        else:
            o_ref, kbuf, vbuf, sem = rest
        q = q_ref[0, 0]                                    # [H, D]
        qg = q.reshape(n_kv, G, D)
        m = jnp.full((H, 1), _NEG_INF, jnp.float32)        # running max
        l = jnp.zeros((H, 1), jnp.float32)                 # normalizer
        acc = jnp.zeros((H, D), jnp.float32)               # weighted V
        for lp in range(pps):
            phys = table_ref[0, lp]
            cp = pltpu.make_async_copy(kp_ref.at[phys], kbuf, sem)
            cp.start()
            cp.wait()
            cp = pltpu.make_async_copy(vp_ref.at[phys], vbuf, sem)
            cp.start()
            cp.wait()
            if quantized:
                cp = pltpu.make_async_copy(ks_ref.at[phys], ksbuf, sem)
                cp.start()
                cp.wait()
                cp = pltpu.make_async_copy(vs_ref.at[phys], vsbuf, sem)
                cp.start()
                cp.wait()
                k = _dequant(kbuf[:], ksbuf[:], dtype)     # [P, n_kv, D]
                v = _dequant(vbuf[:], vsbuf[:], dtype)
            else:
                k = kbuf[:]
                v = vbuf[:]
            valid = mask_ref[0, lp * P:(lp + 1) * P]       # [P]
            s = jnp.einsum("hgd,phd->hgp", qg, k).astype(jnp.float32)
            s = s.reshape(H, P) * att_scale
            s = jnp.where(valid[None, :], s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(valid[None, :], p, 0.0)          # exact zeros
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            pv = jnp.einsum(
                "hgp,phd->hgd",
                p.reshape(n_kv, G, P),
                v.astype(jnp.float32),
            )
            acc = acc * corr + pv.reshape(H, D)
            m = m_new
        l = jnp.where(l == 0.0, 1.0, l)                    # all-masked rows
        o_ref[0, 0] = (acc / l).astype(dtype)

    return body


def paged_attention(
    q: jax.Array,
    key_pages: jax.Array,
    value_pages: jax.Array,
    table: jax.Array,
    mask: jax.Array,
    *,
    key_scale: Optional[jax.Array] = None,
    value_scale: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    stream: Optional[bool] = None,
) -> jax.Array:
    """Fused paged decode attention: gather + QK + softmax + V, one call.

    Args:
      q: ``[n_slots, 1, n_heads, head_dim]`` decode queries.
      key_pages / value_pages: the physical pool,
        ``[n_pages + 1, page_size, n_kv_heads, head_dim]`` (bf16/fp16, or
        int8 codes when scales are passed; the +1 row is the trash page).
      table: ``[n_slots, pages_per_slot]`` int32 physical page indices.
      mask: ``[n_slots, total]`` bool — True at attendable positions
        (``total`` fixes the softmax width, exactly as the retired
        gathered view's ``[:, :total]`` slice did).
      key_scale / value_scale: optional ``[n_pages + 1, page_size]`` f32
        per-(page, row) symmetric dequant scales; passing them selects
        the int8 path with dequant fused after the KV load.
      interpret: run the Pallas interpreter (defaults to "not on TPU" —
        the CPU-emulated test mesh always interprets).
      stream: pick the page-streaming online-softmax body (defaults to
        the exact batched body under interpret, streaming on TPU; tests
        force ``stream=True`` under interpret to cover the TPU body).

    Returns ``[n_slots, 1, n_heads, head_dim]`` in ``q.dtype``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if stream is None:
        stream = not interpret
    quantized = key_scale is not None
    if quantized != (value_scale is not None):
        raise ValueError("key_scale and value_scale must be passed together")
    n, H, n_kv, D, P, pps, total = _geometry(q, key_pages, table, mask)
    span = pps * P
    if stream and total < span:
        # The streaming body walks whole pages; pad the mask so the
        # slack tail past ``total`` is just more masked lanes.
        mask = jnp.pad(mask, ((0, 0), (0, span - total)))
    dtype = q.dtype
    operands = [table, mask, q, key_pages, value_pages]
    pool_specs = [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
    if quantized:
        operands += [key_scale, value_scale]
        pool_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
    if not stream:
        body = _exact_body(n, H, n_kv, D, P, pps, total, quantized, dtype)
        return pl.pallas_call(
            body,
            out_shape=jax.ShapeDtypeStruct((n, 1, H, D), dtype),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),   # table
                pl.BlockSpec(memory_space=pltpu.VMEM),   # mask
                pl.BlockSpec(memory_space=pltpu.VMEM),   # q
                *pool_specs,
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(*operands)
    body = _stream_body(n, H, n_kv, D, P, pps, total, quantized, dtype)
    mask_w = mask.shape[-1]
    scratch = [
        pltpu.VMEM((P, n_kv, D), key_pages.dtype),
        pltpu.VMEM((P, n_kv, D), value_pages.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((P,), key_scale.dtype),
            pltpu.VMEM((P,), value_scale.dtype),
        ]
    scratch.append(pltpu.SemaphoreType.DMA)
    return pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((n, 1, H, D), dtype),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, pps), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, mask_w), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, H, D), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            *pool_specs,
        ],
        out_specs=pl.BlockSpec(
            (1, 1, H, D), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)


def paged_attention_reference(
    q, key_pages, value_pages, table, mask, key_scale=None, value_scale=None
):
    """Naive f32 oracle: gather pool rows through the table, dequantize,
    broadcast KV heads over query groups, full-precision softmax.  The
    property tests (``tests/test_paged_attention.py``) compare both
    kernel bodies against this across page sizes, odd valid lengths,
    and trash-page table rows."""
    n, H, n_kv, D, P, pps, total = _geometry(q, key_pages, table, mask)
    span = pps * P
    k = jnp.take(key_pages, table, axis=0)
    v = jnp.take(value_pages, table, axis=0)
    if key_scale is not None:
        k = _dequant(k, jnp.take(key_scale, table, axis=0), jnp.float32)
        v = _dequant(v, jnp.take(value_scale, table, axis=0), jnp.float32)
    k = k.reshape(n, span, n_kv, D)[:, :total].astype(jnp.float32)
    v = v.reshape(n, span, n_kv, D)[:, :total].astype(jnp.float32)
    group = H // n_kv
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    q32 = q.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k) * (D ** -0.5)
    logits = jnp.where(
        mask[:, None, None, :], logits, jnp.finfo(jnp.float32).min
    )
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@dataclasses.dataclass
class PagedAttnView:
    """KVCache-shaped adapter binding one decode step to the page pool.

    Carries the physical pool (codes + scales for int8), the page table,
    and per-slot write offsets; duck-types ``models/layers.KVCache`` so
    the unmodified model stack drives the fused kernel: ``update`` lands
    the step's new KV row directly in its physical page (``off // P``
    within the slot's row, quantized per-row for int8) and ``attend``
    runs :func:`paged_attention` — the pool IS the cache, so the decode
    scan carries it and the runtime never gathers or scatters a view.
    """

    keys: jax.Array                      # [n_pages + 1, P, n_kv, D]
    values: jax.Array
    key_scale: Optional[jax.Array]       # [n_pages + 1, P] f32, int8 only
    value_scale: Optional[jax.Array]
    table: jax.Array                     # [n_slots, pages_per_slot] int32
    length: jax.Array                    # [n_slots] int32 write offsets
    page_size: int = 16
    total: int = 0

    def update(self, k_new: jax.Array, v_new: jax.Array) -> "PagedAttnView":
        if k_new.shape[1] != 1:
            raise ValueError(
                "PagedAttnView writes one decode token per step "
                f"(got {k_new.shape[1]}); chunked prefill stays on the "
                "gather/scatter path (ops/kv_pages.py)"
            )
        P = self.page_size
        rows = jnp.arange(self.table.shape[0])
        off = self.length
        lp = off // P
        r = off % P
        # Free slots' rows all point at the trash page; their duplicate
        # writes race benignly (the page is never read through an active
        # mask).  Decode offsets sit at or past prompt_region, so lp
        # lands in the decode pages and shared prompt pages are never
        # written (the invariant the retired scatter clamped for).
        phys = self.table[rows, lp]
        if self.key_scale is None:
            keys = self.keys.at[phys, r].set(
                k_new[:, 0].astype(self.keys.dtype)
            )
            values = self.values.at[phys, r].set(
                v_new[:, 0].astype(self.values.dtype)
            )
            key_scale = value_scale = None
        else:
            from music_analyst_tpu.ops.quant import quantize_kv_page

            qk, sk = quantize_kv_page(k_new[:, 0])
            qv, sv = quantize_kv_page(v_new[:, 0])
            keys = self.keys.at[phys, r].set(qk)
            values = self.values.at[phys, r].set(qv)
            key_scale = self.key_scale.at[phys, r].set(sk)
            value_scale = self.value_scale.at[phys, r].set(sv)
        return dataclasses.replace(
            self, keys=keys, values=values,
            key_scale=key_scale, value_scale=value_scale, length=off + 1,
        )

    def attend(self, q: jax.Array, mask: jax.Array) -> jax.Array:
        """Decode attention for ``q [n, 1, H, D]`` under ``mask
        [n, 1, 1, total]`` — the fused kernel, no materialized view."""
        return paged_attention(
            q, self.keys, self.values, self.table, mask[:, 0, 0, :],
            key_scale=self.key_scale, value_scale=self.value_scale,
        )


jax.tree_util.register_dataclass(
    PagedAttnView,
    data_fields=[
        "keys", "values", "key_scale", "value_scale", "table", "length"
    ],
    meta_fields=["page_size", "total"],
)
