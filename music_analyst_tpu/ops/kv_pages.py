"""Prefix-shared paged KV cache for the continuous decode runtime.

``ops/kv_slots.py`` gave each slot a monolithic KV region sized for
``max_total``, so the dominant generation workload — llama zero-shot
classification, which prepends the *same* prompt template to every song —
re-prefills and re-stores near-identical KV bytes for every request.
This module splits the cache into a fixed device-resident pool of
pow2-sized **pages**; a slot's KV buffer is now a *view* gathered through
an int32 page table, so two sequences with a common token prefix can map
the same physical pages and a prefix hit turns most of chunked prefill
into a page-table update plus a short suffix prefill.

Device half (this file, compiled): **five fixed-shape programs** via
:func:`profiled_jit` — the zero-retrace discipline of ``kv_slots`` with
the page table as a traced operand, so the programs never retrace as
pages are shared, copied, and recycled:

* **paged prefill chunk** — gather one slot's pages into a contiguous
  ``[1, max_total]`` view, run the *identical* chunk-prefill math as the
  monolithic runtime, scatter the touched pages back.  The view is
  byte-for-byte the monolithic slot buffer, so every attention reduction
  sees the same values at the same indices — continuous greedy tokens
  stay byte-identical to ``kv_slots`` and static ``generate_batch``.
* **paged decode step** — gather all slots' views through the full
  ``[n_slots, pages_per_slot]`` table, run the identical ``decode_span``
  scan, scatter back only each slot's *decode* pages (never below
  ``prompt_region``, so shared prompt pages are never written by decode).
* **paged verify block** — score a ``[n_slots, K]`` drafted token block
  (speculative decoding) in one dispatch over the gathered views,
  scattering back decode pages only — the paged twin of
  ``slots.verify``.
* **page free** — zero a mask of physical pages (failure-path hard
  isolation, the paged analogue of ``slots.free``).
* **page copy** — one page ``src → dst`` (copy-on-write for the
  partially-filled boundary page of a prefix hit).

Host half (pure Python, no jax): :class:`PagePool` (free list +
per-page refcounts: ``slot_refs`` = slots currently mapping the page,
``in_tree`` = the radix index holds it) and :class:`RadixIndex` (a radix
tree over page-granular token runs: match walks full-page children then
takes the longest-common-prefix partial; insert happens at
prefill-complete; a refcount-aware LRU evicts cold *leaves* only, never
a pinned page).  Both are deliberately jax-free so
``tests/test_kv_pages.py`` can property-test them as plain data
structures.

Why sharing preserves byte-identity: K/V bytes at position ``p`` depend
only on tokens ``[0..p]`` (causal masking, chunk-alignment invariance —
the property the chunked-prefill-vs-static tests already pin), so a
matched page holds exactly the bytes a fresh prefill would produce.  The
boundary chunk that straddles the shared/private line is *recomputed*:
rows below the shared length write back identical bytes (idempotent),
rows at or above it carry request-specific bytes and land only in the
copy-on-write / fresh pages the host mapped for them.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from music_analyst_tpu.models.layers import KVCache
from music_analyst_tpu.ops.paged_attention import PagedAttnView
from music_analyst_tpu.ops.quant import quantize_kv_page
from music_analyst_tpu.profiling.compile import profiled_jit

KV_QUANT_SCHEMES = ("none", "int8")


def _is_pow2(n: int) -> bool:
    return n >= 1 and not (n & (n - 1))


@dataclasses.dataclass
class QuantizedKVPages:
    """int8 page pool: codes + per-(page, row) f32 dequant scales.

    The quantized twin of the per-layer ``KVCache`` pool — same
    ``[n_pages + 1, page_size, n_kv, head_dim]`` geometry with int8
    codes, plus ``[n_pages + 1, page_size]`` scale planes for K and V
    (``ops/quant.quantize_kv_page``).  A registered pytree whose leaves
    ride along wherever the float pool's did, so page copy, free,
    checkpointing, and pin transfers move scales with their pages for
    free — the scheduler never special-cases quantization.
    """

    keys: jax.Array          # int8 [n_pages + 1, P, n_kv, D]
    values: jax.Array
    key_scale: jax.Array     # f32 [n_pages + 1, P]
    value_scale: jax.Array
    length: jax.Array        # int32 [n_slots] write offsets (bookkeeping)


jax.tree_util.register_dataclass(
    QuantizedKVPages,
    data_fields=["keys", "values", "key_scale", "value_scale", "length"],
    meta_fields=[],
)


@dataclasses.dataclass(frozen=True)
class PagePlan:
    """Static geometry of one paged runtime (compile-shape contract)."""

    n_slots: int        # pow2 — rows in the page table
    prefill_chunk: int  # tokens written per prefill dispatch
    prompt_region: int  # buffer rows for the prompt (multiple of chunk & page)
    max_new: int        # decode rows per slot (largest per-request budget)
    decode_span: int    # greedy steps per decode dispatch
    page_size: int      # pow2 — tokens per physical KV page
    n_pages: int        # allocatable pool size (excludes the trash page)

    def __post_init__(self):
        if not _is_pow2(self.n_slots):
            raise ValueError(f"n_slots must be a power of two, got {self.n_slots}")
        if not _is_pow2(self.page_size):
            raise ValueError(
                f"page_size must be a power of two, got {self.page_size}"
            )
        if self.prompt_region % self.prefill_chunk:
            raise ValueError(
                f"prompt_region ({self.prompt_region}) must be a multiple of "
                f"prefill_chunk ({self.prefill_chunk})"
            )
        if self.prompt_region % self.page_size:
            raise ValueError(
                f"prompt_region ({self.prompt_region}) must be a multiple of "
                f"page_size ({self.page_size})"
            )
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.decode_span < 1:
            raise ValueError(f"decode_span must be >= 1, got {self.decode_span}")
        floor = max(self.n_slots, self.pages_per_slot)
        if self.n_pages < floor:
            raise ValueError(
                f"n_pages ({self.n_pages}) must be >= "
                f"max(n_slots, pages_per_slot) = {floor} — the pool must hold "
                "one page per slot and one full resident sequence"
            )

    @property
    def max_total(self) -> int:
        return self.prompt_region + self.max_new

    @property
    def prompt_pages(self) -> int:
        return self.prompt_region // self.page_size

    @property
    def decode_pages(self) -> int:
        return -(-self.max_new // self.page_size)

    @property
    def pages_per_slot(self) -> int:
        return self.prompt_pages + self.decode_pages

    @property
    def slot_span(self) -> int:
        """Gathered-view width: ``pages_per_slot * page_size`` rows — the
        model only ever sees the first ``max_total`` of them."""
        return self.pages_per_slot * self.page_size

    @property
    def trash_page(self) -> int:
        """Physical index of the write sink for free slots' table rows.

        The decode program writes a row for *every* slot (fixed shape); a
        freed slot's stale table row could otherwise scribble on pages
        that have since been recycled to another sequence.  Free rows
        point every entry here instead.  Never allocated, never read
        through an active mask."""
        return self.n_pages


class PagedDecodeRuntime:
    """Five-program continuous decode over a shared page pool.

    Holds no request state — the page table, refcounts, and the radix
    tree live in the host scheduler; this class owns only the compiled
    programs and the geometry they were traced for.  The page table /
    page row is a *traced* int32 operand, so table churn (sharing, CoW,
    eviction, slot reuse) never retraces.
    """

    def __init__(self, model, config, plan: PagePlan, eos_id: int,
                 mesh=None, kv_quant: str = "none") -> None:
        self.model = model
        self.config = config
        self.plan = plan
        self.eos_id = int(eos_id)
        # Mesh-aware mode (see SlotDecodeRuntime): the page pool's head
        # axis shards over tp per DECODE_KV_RULES; the page table stays a
        # replicated traced operand, so gather/scatter indices are shared
        # by every chip and only head-local bytes move.
        self.mesh = mesh
        if kv_quant not in KV_QUANT_SCHEMES:
            raise ValueError(
                f"kv_quant must be one of {KV_QUANT_SCHEMES}, got {kv_quant!r}"
            )
        self.kv_quant = kv_quant
        quantized = kv_quant == "int8"
        # The dtype KV rows dequantize to (and the unquantized pool's
        # storage dtype): the model's activation dtype.
        compute_dtype = jnp.bfloat16
        self._compute_dtype = compute_dtype
        if plan.max_total > config.max_seq_len:
            raise ValueError(
                f"prompt_region + max_new ({plan.max_total}) exceeds the "
                f"model's max_seq_len ({config.max_seq_len})"
            )
        R = plan.prompt_region
        C = plan.prefill_chunk
        P = plan.page_size
        total = plan.max_total
        span = plan.slot_span
        pps = plan.pages_per_slot
        eos = jnp.asarray(self.eos_id, jnp.int32)
        # Pages a chunk write can straddle: C tokens starting at a multiple
        # of C touch at most one leading partial page + the full pages.
        # (Decode and verify no longer scatter — their writes land in the
        # pool row-by-row through the kernel-backed view.)
        n_wp_prefill = (C - 1) // P + 2

        def _view(c, row, length) -> KVCache:
            """Contiguous [B, max_total] view of the rows behind ``row``.

            ``row`` is ``[pps]`` (prefill, B=1) or ``[n_slots, pps]``
            (decode).  The view is sliced to exactly ``max_total`` rows so
            every downstream op — masks, softmax widths, reductions — is
            bit-identical to the monolithic runtime's buffer.  Prefill is
            the only remaining consumer (decode and verify read the pool
            through the fused kernel); for int8 the gathered codes
            dequantize here, so the chunk-prefill math runs on the same
            bf16 rows the kernel's load epilogue reconstructs.
            """
            keys = jnp.take(c.keys, row, axis=0)
            values = jnp.take(c.values, row, axis=0)
            if quantized:
                ks = jnp.take(c.key_scale, row, axis=0)[..., None, None]
                vs = jnp.take(c.value_scale, row, axis=0)[..., None, None]
                keys = (keys.astype(jnp.float32) * ks).astype(compute_dtype)
                values = (values.astype(jnp.float32) * vs).astype(
                    compute_dtype
                )
            if row.ndim == 1:
                shape = (1, span) + keys.shape[-2:]
            else:
                shape = (row.shape[0], span) + keys.shape[-2:]
            keys = keys.reshape(shape)[:, :total]
            values = values.reshape(shape)[:, :total]
            return KVCache(keys, values, length)

        def _attn_view(c, page_table, length) -> PagedAttnView:
            """The kernel-backed cache for decode/verify: binds the pool
            (+ scales), the table, and per-slot write offsets — no
            gathered copy."""
            return PagedAttnView(
                keys=c.keys, values=c.values,
                key_scale=c.key_scale if quantized else None,
                value_scale=c.value_scale if quantized else None,
                table=page_table, length=length,
                page_size=P, total=total,
            )

        def _repack(v: PagedAttnView, length):
            """Pool state back out of a scanned view (decode/verify write
            pages in place through the view, so the view IS the new
            pool)."""
            if quantized:
                return QuantizedKVPages(
                    v.keys, v.values, v.key_scale, v.value_scale, length
                )
            return KVCache(v.keys, v.values, length)

        def _pages(arr):
            """[B, max_total] view back to per-page layout [B, pps, P, ...],
            zero-padding the slack tail rows (>= max_total) — those rows
            are never attended, and deterministic zeros beat stale bytes."""
            pad = [(0, 0)] * arr.ndim
            pad[1] = (0, span - total)
            padded = jnp.pad(arr, pad)
            return padded.reshape(
                (arr.shape[0], pps, P) + arr.shape[2:]
            )

        def _prefill_chunk(params, caches, page_row, slot, chunk_ids, start,
                           length_after, last_index):
            """Write ``prefill_chunk`` prompt tokens through one slot's pages.

            Identical math to ``slots.prefill`` over the gathered view;
            the only paged part is the gather in and the per-page scatter
            out.  ``page_row``/``slot``/``start``/``length_after``/
            ``last_index`` are traced, so one program serves every slot,
            every page mapping, every chunk offset, every prompt length.
            The write-back covers every page the chunk touches; pages
            below a prefix hit's copy-on-write boundary only ever receive
            recomputed bytes identical to what they hold (see module
            docstring), so the scatter is idempotent there.
            """
            view = [_view(c, page_row, start) for c in caches]
            positions = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
            q_pos = positions[:, :, None]
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, :]
            mask = (kv_pos <= q_pos)[:, None, :, :]
            logits, view = self.model.apply(
                {"params": params}, chunk_ids[None, :], positions, mask, view,
                last_position=last_index[None],
            )
            first = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)[0]
            lp0 = start // P
            new_caches = []
            for c, v in zip(caches, view):
                vk = _pages(v.keys)[0]    # [pps, P, n_kv, D]
                vv = _pages(v.values)[0]
                keys, values = c.keys, c.values
                if quantized:
                    key_scale, value_scale = c.key_scale, c.value_scale
                for j in range(n_wp_prefill):
                    lp = jnp.clip(lp0 + j, 0, pps - 1)
                    phys = page_row[lp]
                    pk = jax.lax.dynamic_slice_in_dim(vk, lp, 1, axis=0)
                    pv = jax.lax.dynamic_slice_in_dim(vv, lp, 1, axis=0)
                    if quantized:
                        # Quantize the page on the way out: per-row
                        # symmetric int8 + scale.  Rows the chunk only
                        # re-gathered (below ``start`` on the boundary
                        # page) round-trip through the bf16 view to
                        # within ±1 code, then sit at a fixed point of
                        # further rescatters — see
                        # ops/quant.quantize_kv_page.
                        pk, psk = quantize_kv_page(pk)
                        pv, psv = quantize_kv_page(pv)
                        key_scale = jax.lax.dynamic_update_slice(
                            key_scale, psk, (phys, 0)
                        )
                        value_scale = jax.lax.dynamic_update_slice(
                            value_scale, psv, (phys, 0)
                        )
                    keys = jax.lax.dynamic_update_slice(
                        keys, pk, (phys,) + (0,) * (keys.ndim - 1)
                    )
                    values = jax.lax.dynamic_update_slice(
                        values, pv, (phys,) + (0,) * (values.ndim - 1)
                    )
                length = c.length.at[slot].set(length_after)
                if quantized:
                    new_caches.append(QuantizedKVPages(
                        keys, values, key_scale, value_scale, length
                    ))
                else:
                    new_caches.append(KVCache(keys, values, length))
            return new_caches, first

        def _decode_step(params, caches, page_table, tokens, prompt_lens,
                         steps, budgets, done, active):
            """``decode_span`` greedy steps over all slots in one dispatch.

            The scan body is the same 1-wide step as ``slots.decode``,
            but the cache it threads is a :class:`PagedAttnView`: each
            step writes its new KV row straight into its physical page
            and attends through the fused kernel, so the scan carries
            the page *pool* itself — no gathered copy in, no page
            scatter out.  Write offsets sit at ``R + steps < total``, so
            every write lands in the slot's decode pages and shared
            prompt pages are never touched; free slots' table rows point
            at the trash page, which is never read through an active
            mask (and a free slot's own masked read of it is discarded
            by the ``adv`` select).
            """
            views = [_attn_view(c, page_table, c.length) for c in caches]
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, None, :]

            def body(carry, _):
                tokens, steps, done, views = carry
                adv = active & (steps < budgets)
                offsets = jnp.minimum(R + steps, total - 1)
                views_in = [
                    dataclasses.replace(v, length=offsets) for v in views
                ]
                pos = prompt_lens + steps
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                decode_part = (kv_pos >= R) & (
                    kv_pos - R <= steps[:, None, None, None]
                )
                step_mask = prompt_part | decode_part
                lg, views_out = self.model.apply(
                    {"params": params}, tokens[:, None], pos[:, None],
                    step_mask, views_in,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                new_done = done | (tokens == eos)
                nxt = jnp.where(new_done, eos, nxt)
                out_tokens = jnp.where(adv, nxt, tokens)
                out_steps = jnp.where(adv, steps + 1, steps)
                out_done = jnp.where(adv, new_done, done)
                return (out_tokens, out_steps, out_done, views_out), tokens

            (tokens, steps, done, views), emitted = jax.lax.scan(
                body, (tokens, steps, done, views),
                None, length=plan.decode_span,
            )
            new_caches = [
                _repack(v, c.length) for c, v in zip(caches, views)
            ]
            return new_caches, tokens, steps, done, emitted

        def _verify_block(params, caches, page_table, tokens_blk, prompt_lens,
                          steps):
            """Score a ``[n_slots, K]`` drafted block in one dispatch.

            Identical semantics to ``slots.verify`` (column 0 = carry,
            columns 1.. = drafts; returns the greedy argmax after
            consuming each prefix — see ``kv_slots``): a teacher-forced
            scan of the *same* 1-wide kernel-backed step body as
            ``pages.decode``, because byte-identity demands the logits
            and written KV rows be bit-identical to plain decode (a
            K-wide scoring pass reduces in a different order and flips
            argmax near-ties).  Rejected drafts' rows stay in the decode
            pages but are never attended — the masks derive from the
            host-committed ``steps``, exactly as on the scatter path
            this replaces; shared prompt pages are never written (write
            offsets ``>= R``).
            """
            views = [_attn_view(c, page_table, c.length) for c in caches]
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, None, :]

            def body(carry, tok):
                views, steps = carry
                offsets = jnp.minimum(R + steps, total - 1)
                views_in = [
                    dataclasses.replace(v, length=offsets) for v in views
                ]
                pos = prompt_lens + steps
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                decode_part = (kv_pos >= R) & (
                    kv_pos - R <= steps[:, None, None, None]
                )
                step_mask = prompt_part | decode_part
                lg, views_out = self.model.apply(
                    {"params": params}, tok[:, None], pos[:, None],
                    step_mask, views_in,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (views_out, steps + 1), nxt

            (views, _), preds = jax.lax.scan(
                body, (views, steps), tokens_blk.T,
            )
            preds = preds.T                           # [n, K]
            new_caches = [
                _repack(v, c.length) for c, v in zip(caches, views)
            ]
            return new_caches, preds

        def _free_pages(caches, page_mask, slot_mask):
            """Zero a mask of physical pages and reset masked slots'
            lengths — the failure-path hard isolation.  Normal completion
            is host-only (unpin + table row → trash): the prefill/decode
            masks and write offsets already keep stale pages unreachable.
            For int8 the scale rows zero with their pages (a zero scale
            dequantizes zero codes to exact zeros).
            """
            row = page_mask[:, None, None, None]
            new_caches = []
            for c in caches:
                keys = jnp.where(row, jnp.zeros((), c.keys.dtype), c.keys)
                values = jnp.where(
                    row, jnp.zeros((), c.values.dtype), c.values
                )
                length = jnp.where(slot_mask, 0, c.length)
                if quantized:
                    srow = page_mask[:, None]
                    new_caches.append(QuantizedKVPages(
                        keys, values,
                        jnp.where(srow, 0.0, c.key_scale),
                        jnp.where(srow, 0.0, c.value_scale),
                        length,
                    ))
                else:
                    new_caches.append(KVCache(keys, values, length))
            return new_caches

        def _copy_page(caches, src, dst):
            """Copy one physical page ``src → dst`` across every layer —
            the copy-on-write for a prefix hit's partially-filled boundary
            page: the new occupant overwrites its suffix rows in the copy
            while the original keeps serving other sequences.  int8 pages
            carry their scale rows along."""

            def move(buf):
                page = jax.lax.dynamic_slice_in_dim(buf, src, 1, axis=0)
                return jax.lax.dynamic_update_slice(
                    buf, page, (dst,) + (0,) * (buf.ndim - 1)
                )

            new_caches = []
            for c in caches:
                if quantized:
                    new_caches.append(QuantizedKVPages(
                        move(c.keys), move(c.values),
                        move(c.key_scale), move(c.value_scale), c.length,
                    ))
                else:
                    new_caches.append(
                        KVCache(move(c.keys), move(c.values), c.length)
                    )
            return new_caches

        self.prefill_chunk = profiled_jit(_prefill_chunk, name="pages.prefill")
        self.decode_step = profiled_jit(_decode_step, name="pages.decode")
        self.verify_block = profiled_jit(_verify_block, name="pages.verify")
        self.free_pages = profiled_jit(_free_pages, name="pages.free")
        self.copy_page = profiled_jit(_copy_page, name="pages.copy")

    # ---------------------------------------------------------------- state

    def init_caches(self, dtype=jnp.bfloat16) -> List[Any]:
        """Fresh page pool: ``[n_pages + 1, page_size, n_kv, head_dim]``
        per layer (the +1 row is the trash page) with the monolithic
        runtime's per-slot write-offset ``length`` kept for bookkeeping.
        ``kv_quant="int8"`` pools store int8 codes plus per-(page, row)
        f32 scale planes (:class:`QuantizedKVPages`)."""
        cfg = self.config
        head_dim = cfg.dim // cfg.n_heads
        plan = self.plan
        shape = (plan.n_pages + 1, plan.page_size, cfg.n_kv_heads, head_dim)
        if self.kv_quant == "int8":
            sshape = (plan.n_pages + 1, plan.page_size)
            caches = [
                QuantizedKVPages(
                    keys=jnp.zeros(shape, jnp.int8),
                    values=jnp.zeros(shape, jnp.int8),
                    key_scale=jnp.zeros(sshape, jnp.float32),
                    value_scale=jnp.zeros(sshape, jnp.float32),
                    length=jnp.zeros((plan.n_slots,), jnp.int32),
                )
                for _ in range(cfg.n_layers)
            ]
        else:
            caches = [
                KVCache(
                    keys=jnp.zeros(shape, dtype),
                    values=jnp.zeros(shape, dtype),
                    length=jnp.zeros((plan.n_slots,), jnp.int32),
                )
                for _ in range(cfg.n_layers)
            ]
        if self.mesh is not None:
            from music_analyst_tpu.parallel.sharding import shard_kv_caches

            caches = shard_kv_caches(caches, self.mesh, cfg.n_kv_heads)
        return caches

    def kv_token_bytes(self, dtype=jnp.bfloat16) -> int:
        """HBM bytes one cached token costs across all layers (K + V).

        Quantization-aware: under ``kv_quant="int8"`` a token stores
        int8 codes plus its share of the per-(page, row) f32 scales —
        one 4-byte scale per token for K and one for V, per layer."""
        cfg = self.config
        head_dim = cfg.dim // cfg.n_heads
        if self.kv_quant == "int8":
            return 2 * cfg.n_layers * (cfg.n_kv_heads * head_dim + 4)
        itemsize = jnp.zeros((), dtype).dtype.itemsize
        return 2 * cfg.n_layers * cfg.n_kv_heads * head_dim * itemsize

    def kv_token_bytes_unquantized(self, dtype=jnp.bfloat16) -> int:
        """What the same token would cost without KV quantization — the
        baseline for the manifest's ``kv_quant.bytes_saved``."""
        cfg = self.config
        head_dim = cfg.dim // cfg.n_heads
        itemsize = jnp.zeros((), dtype).dtype.itemsize
        return 2 * cfg.n_layers * cfg.n_kv_heads * head_dim * itemsize

    def page_bytes(self, dtype=jnp.bfloat16) -> int:
        return self.plan.page_size * self.kv_token_bytes(dtype)

    def pool_bytes(self, dtype=jnp.bfloat16) -> int:
        """Whole-pool HBM footprint across layers (incl. the trash page)."""
        return (self.plan.n_pages + 1) * self.page_bytes(dtype)

    def compiled_variants(self) -> int:
        """Total compiled-program count across the five programs — the
        zero-retrace assertion reads this before/after page-table churn."""
        return sum(
            fn._cache_size()
            for fn in (self.prefill_chunk, self.decode_step, self.verify_block,
                       self.free_pages, self.copy_page)
        )

    def prompt_chunks(self, n_tokens: int) -> Sequence[int]:
        """Chunk start offsets covering a prompt of ``n_tokens`` tokens."""
        n = max(1, min(int(n_tokens), self.plan.prompt_region))
        C = self.plan.prefill_chunk
        return range(0, ((n + C - 1) // C) * C, C)


# ====================================================================== host
# Pure-Python page accounting + radix tree (no jax imports at runtime) —
# the scheduler drives these; tests/test_kv_pages.py property-tests them.


class PagePool:
    """Free list + refcounts over the physical pages of one pool.

    A page is *free* iff no slot maps it (``slot_refs == 0``) and the
    radix index doesn't hold it (``in_tree`` false).  ``alloc`` hands out
    free pages (unpinned — the caller pins them as it maps them);
    releasing the last reference returns the page to the free list.
    """

    def __init__(self, n_pages: int) -> None:
        self.n_pages = int(n_pages)
        self.slot_refs = [0] * self.n_pages
        self.in_tree = [False] * self.n_pages
        # Pop from the tail → pages are handed out in ascending order
        # (deterministic layouts; nice for debugging dumps).
        self._free = list(range(self.n_pages - 1, -1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, k: int) -> Optional[List[int]]:
        if k > len(self._free):
            return None
        return [self._free.pop() for _ in range(k)]

    def pin(self, phys: int) -> None:
        self.slot_refs[phys] += 1

    def unpin(self, phys: int) -> None:
        refs = self.slot_refs[phys] - 1
        if refs < 0:
            raise ValueError(f"unpin of unpinned page {phys}")
        self.slot_refs[phys] = refs
        self._maybe_free(phys)

    def pin_row(self, pages: Sequence[int]) -> None:
        """Pin every page of one table row — a checkpoint taking its own
        reference so the row survives the slot's release (and the zeroing
        failure path, which only touches fully-unreferenced pages)."""
        for phys in pages:
            self.pin(phys)

    def unpin_row(self, pages: Sequence[int]) -> None:
        """Release one reference from every page of a table row."""
        for phys in pages:
            self.unpin(phys)

    def tree_add(self, phys: int) -> None:
        if self.in_tree[phys]:
            raise ValueError(f"page {phys} already in the radix index")
        self.in_tree[phys] = True

    def tree_drop(self, phys: int) -> None:
        if not self.in_tree[phys]:
            raise ValueError(f"page {phys} not in the radix index")
        self.in_tree[phys] = False
        self._maybe_free(phys)

    def _maybe_free(self, phys: int) -> None:
        if self.slot_refs[phys] == 0 and not self.in_tree[phys]:
            self._free.append(phys)

    def check(self) -> None:
        """Invariant audit (tests): the free list is exactly the
        unreferenced pages, with no duplicates."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages in the free list")
        for p in range(self.n_pages):
            should_be_free = self.slot_refs[p] == 0 and not self.in_tree[p]
            if should_be_free != (p in free):
                raise AssertionError(
                    f"page {p}: refs={self.slot_refs[p]} "
                    f"in_tree={self.in_tree[p]} free={p in free}"
                )


class _RadixNode:
    __slots__ = ("tokens", "phys", "children", "parent", "last_used")

    def __init__(self, tokens: Tuple[int, ...], phys: Optional[int],
                 parent: Optional["_RadixNode"]) -> None:
        self.tokens = tokens          # the page's *valid* tokens
        self.phys = phys              # physical page (None only at root)
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.parent = parent
        self.last_used = 0

    @property
    def n_valid(self) -> int:
        return len(self.tokens)


@dataclasses.dataclass
class PrefixMatch:
    """Result of a radix lookup for one prompt."""

    pages: List[int]          # full shared pages, in slot-local order
    full_tokens: int          # len(pages) * page_size
    partial_phys: Optional[int]  # boundary page to copy-on-write (or None)
    partial_tokens: int       # tokens matched inside the boundary page

    @property
    def tokens(self) -> int:
        return self.full_tokens + self.partial_tokens


class RadixIndex:
    """Radix tree over page-granular token runs.

    Nodes are pages: a child is keyed by its page's valid-token tuple
    (full pages have exactly ``page_size`` tokens; a leaf may be partial).
    Only full pages extend the path — a partial page can't be followed by
    an aligned successor.  ``match`` walks exact full-page children, then
    takes the longest-common-prefix partial at the frontier; ``insert``
    adds the pages of a completed prefill (the pool takes an ``in_tree``
    reference per adopted page); ``evict`` drops least-recently-used
    *leaves* whose pages no slot maps — a pinned page is never evicted.
    """

    def __init__(self, page_size: int) -> None:
        if not _is_pow2(page_size):
            raise ValueError(f"page_size must be a power of two, got {page_size}")
        self.page_size = int(page_size)
        self.root = _RadixNode((), None, None)
        self._clock = 0

    def _touch(self, node: _RadixNode) -> None:
        self._clock += 1
        while node is not None and node is not self.root:
            node.last_used = self._clock
            node = node.parent

    def match(self, ids: Sequence[int]) -> PrefixMatch:
        """Longest cached prefix of ``ids``: whole pages while they match
        exactly, then the best partial page at the frontier.  Never
        returns more than ``len(ids)`` tokens (so a fully-cached prompt
        still re-runs its final chunk for the first-token logits)."""
        ids = [int(t) for t in ids]
        P = self.page_size
        node = self.root
        pages: List[int] = []
        i = 0
        while len(ids) - i >= P:
            child = node.children.get(tuple(ids[i:i + P]))
            if child is None or child.n_valid != P:
                break
            pages.append(child.phys)
            node = child
            i += P
        best: Optional[_RadixNode] = None
        best_k = 0
        remaining = ids[i:]
        if remaining:
            for child in node.children.values():
                k = 0
                for a, b in zip(child.tokens, remaining):
                    if a != b:
                        break
                    k += 1
                if k > best_k:
                    best, best_k = child, k
        if pages or best is not None:
            self._touch(best if best is not None else node)
        if node is not self.root:
            self._touch(node)
        return PrefixMatch(
            pages=pages,
            full_tokens=i,
            partial_phys=best.phys if best is not None else None,
            partial_tokens=best_k,
        )

    def insert(self, ids: Sequence[int], phys_pages: Sequence[int],
               pool: PagePool) -> int:
        """Adopt the pages of one completed prefill into the tree.

        ``ids`` are the prompt's real tokens (length ``plen``);
        ``phys_pages`` is the slot's table row.  Pages already present
        (same valid-token run at the same depth) are left alone — the
        slot's private duplicate simply isn't adopted and frees on
        completion.  Returns the number of pages adopted."""
        ids = [int(t) for t in ids]
        P = self.page_size
        n_full, rem = divmod(len(ids), P)
        node = self.root
        adopted = 0
        for pi in range(n_full):
            seg = tuple(ids[pi * P:(pi + 1) * P])
            child = node.children.get(seg)
            if child is None:
                child = _RadixNode(seg, int(phys_pages[pi]), node)
                node.children[seg] = child
                pool.tree_add(child.phys)
                adopted += 1
            node = child
        if rem:
            seg = tuple(ids[n_full * P:n_full * P + rem])
            if seg not in node.children:
                child = _RadixNode(seg, int(phys_pages[n_full]), node)
                node.children[seg] = child
                pool.tree_add(child.phys)
                adopted += 1
        if node is not self.root or adopted:
            self._touch(node)
        return adopted

    def _leaves(self) -> List[_RadixNode]:
        out: List[_RadixNode] = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                out.append(node)
        return out

    def evict(self, pool: PagePool, need: int) -> int:
        """Free at least ``need`` pages by dropping cold unpinned leaves
        (LRU by ``last_used``); evicting a leaf may expose its parent as
        the next candidate.  Returns how many pages were actually freed —
        fewer than ``need`` iff everything left is pinned."""
        freed = 0
        while freed < need:
            candidates = [
                leaf for leaf in self._leaves()
                if pool.slot_refs[leaf.phys] == 0
            ]
            if not candidates:
                break
            victim = min(candidates, key=lambda n: n.last_used)
            del victim.parent.children[victim.tokens]
            pool.tree_drop(victim.phys)
            freed += 1
        return freed

    def page_count(self) -> int:
        n = 0
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n

    # Occupancy accessors (engine ledger): tree size without exposing
    # internals.  Nodes are pages, so node_count == page_count; kept as
    # a named alias because the ledger reports both dimensions.
    def node_count(self) -> int:
        return self.page_count()

    def token_count(self) -> int:
        """Valid tokens held by the tree — the pinned KV the index keeps
        resident on behalf of future prefix hits."""
        n = 0
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            n += node.n_valid
            stack.extend(node.children.values())
        return n
