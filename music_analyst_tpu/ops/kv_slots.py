"""Slot-indexed KV cache runtime for continuous-batching decode.

The static generation path (``models/llama.py:_generate_scan``) pads every
prompt to the batch's longest and decodes all rows to the batch's largest
token budget — a late arrival waits for the whole batch.  This module is
the device half of the continuous-batching runtime: ``n_slots`` (pow2)
independent sequences live side by side in one slot-indexed KV cache, and
a handful of **fixed-shape compiled programs** move them forward.  Slots
are claimed and freed by the host scheduler (``serving/decode_loop.py``)
between dispatches; no program ever retraces as requests come and go:

* **chunked prefill** — a prompt is written into a free slot's cache in
  fixed-size token chunks (one compiled program reused for every prompt
  length, bounding the latency spike a long prompt injects between decode
  steps);
* **decode step** — ``decode_span`` greedy steps over *all* slots in one
  dispatch, with per-slot positions and an active-mask; inactive slots are
  masked out of attention and their outputs discarded;
* **slot free** — a slot's cache rows and lengths are zeroed.  Normal
  completion frees host-side only (the prefill/decode masks and write
  offsets already guarantee a new occupant never attends stale KV); this
  program is the failure-path hard isolation — after a poisoned request
  nothing about the slot's contents is trusted.
* **slot snapshot / restore** — copy one slot's KV rows into stand-alone
  device buffers and write them back into any (possibly different) free
  slot.  This is the monolithic backend's O(1) preempt-resume: a
  checkpointed victim re-enters decode without re-running a single
  prefill chunk (the paged backend gets the same for free — its
  checkpoint is a pinned page-table row).

Bit-exactness contract: the cache layout deliberately mirrors the static
path's slot/position split — the prompt occupies buffer rows
``[0, prompt_region)`` and decode token ``t`` sits at *buffer slot*
``prompt_region + t`` while carrying *RoPE position* ``prompt_len + t``,
with the identical ``prompt_part | decode_part`` mask.  When
``prompt_region`` equals the static path's padded prompt width (and so
``max_total`` equals its KV width), every per-row attention reduction sees
the same values at the same buffer indices, making continuous greedy
tokens byte-identical to ``generate_batch`` (asserted by
``tests/test_continuous.py`` and the ``continuous`` bench suite).

All three programs go through :func:`profiled_jit`, so the recompile
detector (``profiling.recompiles``) is the zero-retrace witness.

This monolithic per-slot layout is the ``page_size=0`` escape hatch of
the serving stack: the default backend is the prefix-shared *paged*
runtime (``ops/kv_pages.py``), which keeps the same scheduler, the same
bit-exactness contract, and the same fixed-program discipline but stores
KV in a pooled page table so requests sharing a prompt prefix share
physical pages.  Since ISSUE 18 the paged decode path reads the pool
through a fused Pallas kernel (``ops/paged_attention.py``) that walks
the page table in place — the gather/scatter materialization that once
made decode-heavy no-overlap workloads a reason to pin this backend is
retired, and the ``continuous`` suite's kernel A/B measures paged
against this cache directly.  ``--page-size 0`` remains supported as
the A/B baseline and as the fallback if the kernel path ever needs to
be ruled out.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from music_analyst_tpu.models.layers import KVCache
from music_analyst_tpu.profiling.compile import profiled_jit


@dataclasses.dataclass(frozen=True)
class SlotPlan:
    """Static geometry of one slot runtime (compile-shape contract)."""

    n_slots: int        # pow2 — rows in the slot cache
    prefill_chunk: int  # tokens written per prefill dispatch
    prompt_region: int  # buffer rows reserved for the prompt (multiple of chunk)
    max_new: int        # decode rows per slot (largest per-request budget)
    decode_span: int    # greedy steps per decode dispatch

    def __post_init__(self):
        if self.n_slots < 1 or (self.n_slots & (self.n_slots - 1)):
            raise ValueError(f"n_slots must be a power of two, got {self.n_slots}")
        if self.prompt_region % self.prefill_chunk:
            raise ValueError(
                f"prompt_region ({self.prompt_region}) must be a multiple of "
                f"prefill_chunk ({self.prefill_chunk})"
            )
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.decode_span < 1:
            raise ValueError(f"decode_span must be >= 1, got {self.decode_span}")

    @property
    def max_total(self) -> int:
        return self.prompt_region + self.max_new


class SlotDecodeRuntime:
    """Three-program continuous decode over a slot-indexed KV cache.

    Holds no request state — slots, budgets, and arrival order live in the
    host scheduler; this class owns only the compiled programs and the
    geometry they were traced for.  ``params`` is an explicit argument to
    every program so residency reloads / weight-quantized trees flow
    through without retracing.
    """

    def __init__(self, model, config, plan: SlotPlan, eos_id: int,
                 mesh=None) -> None:
        self.model = model
        self.config = config
        self.plan = plan
        self.eos_id = int(eos_id)
        # Mesh-aware mode: params arrive already placed by the classifier's
        # TP_RULES and the cache is placed by DECODE_KV_RULES (head axis
        # over tp), so the three programs lower once per geometry with
        # GSPMD-propagated shardings — same zero-retrace discipline, same
        # bytes (tp just splits the head loop the reductions never cross).
        self.mesh = mesh
        if plan.max_total > config.max_seq_len:
            raise ValueError(
                f"prompt_region + max_new ({plan.max_total}) exceeds the "
                f"model's max_seq_len ({config.max_seq_len})"
            )
        R = plan.prompt_region
        C = plan.prefill_chunk
        total = plan.max_total
        eos = jnp.asarray(self.eos_id, jnp.int32)

        def _prefill_chunk(params, caches, slot, chunk_ids, start, length_after,
                           last_index):
            """Write ``prefill_chunk`` prompt tokens into one slot's cache.

            ``slot``/``start``/``length_after``/``last_index`` are traced
            int32 scalars, so one compiled program serves every slot, every
            chunk offset, and every prompt length.  ``last_index`` is the
            chunk-local index of the prompt's final token (only meaningful
            on the last chunk; earlier chunks return a throwaway token).
            """
            # Batch-1 view of the slot's rows, scalar length = this chunk's
            # write offset — KVCache.update then lands the chunk at
            # positions [start, start + C).
            view = [
                KVCache(
                    jax.lax.dynamic_slice_in_dim(c.keys, slot, 1, axis=0),
                    jax.lax.dynamic_slice_in_dim(c.values, slot, 1, axis=0),
                    start,
                )
                for c in caches
            ]
            positions = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
            # Causal over the global offsets: a real prompt token at global
            # position p attends exactly [0, p] — chunk padding (tokens past
            # the prompt's end) sits at positions > p and is causally
            # unreachable, so no explicit padding mask is needed.
            q_pos = positions[:, :, None]                     # [1, C, 1]
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, :]
            mask = (kv_pos <= q_pos)[:, None, :, :]           # [1, 1, C, total]
            logits, view = self.model.apply(
                {"params": params}, chunk_ids[None, :], positions, mask, view,
                last_position=last_index[None],
            )
            first = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)[0]
            new_caches = []
            for c, v in zip(caches, view):
                keys = jax.lax.dynamic_update_slice(
                    c.keys, v.keys, (slot, 0, 0, 0)
                )
                values = jax.lax.dynamic_update_slice(
                    c.values, v.values, (slot, 0, 0, 0)
                )
                new_caches.append(
                    KVCache(keys, values, c.length.at[slot].set(length_after))
                )
            return new_caches, first

        def _decode_step(params, caches, tokens, prompt_lens, steps, budgets,
                         done, active):
            """``decode_span`` greedy steps over all slots in one dispatch.

            Mirrors ``_generate_scan``'s per-row semantics exactly: token
            ``t`` occupies buffer slot ``R + t`` with RoPE position
            ``prompt_len + t`` under the ``prompt_part | decode_part`` mask,
            and rows that already emitted EOS keep emitting EOS.  A slot
            advances only while ``active`` and under budget; frozen/free
            rows still write (fixed shape) but only into their own dead
            tail, which the masks — and the zeroing free program — keep
            unreachable.
            """
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, None, :]

            def body(carry, _):
                tokens, steps, done, caches = carry
                adv = active & (steps < budgets)
                # Clamp the write offset so a frozen row's dead-tail write
                # can only land on its own last (already-consumed) row.
                offsets = jnp.minimum(R + steps, total - 1)
                caches_in = [
                    KVCache(c.keys, c.values, offsets) for c in caches
                ]
                pos = prompt_lens + steps                     # [n_slots]
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                decode_part = (kv_pos >= R) & (
                    kv_pos - R <= steps[:, None, None, None]
                )
                step_mask = prompt_part | decode_part
                lg, caches_out = self.model.apply(
                    {"params": params}, tokens[:, None], pos[:, None],
                    step_mask, caches_in,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                new_done = done | (tokens == eos)
                nxt = jnp.where(new_done, eos, nxt)
                out_tokens = jnp.where(adv, nxt, tokens)
                out_steps = jnp.where(adv, steps + 1, steps)
                out_done = jnp.where(adv, new_done, done)
                return (out_tokens, out_steps, out_done, caches_out), tokens

            (tokens, steps, done, caches), emitted = jax.lax.scan(
                body, (tokens, steps, done, caches),
                None, length=plan.decode_span,
            )
            return caches, tokens, steps, done, emitted  # emitted [span, n]

        def _verify_block(params, caches, tokens_blk, prompt_lens, steps):
            """Score a ``[n_slots, K]`` drafted block in one dispatch.

            Column 0 of ``tokens_blk`` is each slot's pending carry token
            and columns ``1..K-1`` are host-proposed drafts.  Returns the
            greedy argmax after consuming ``tokens_blk[:, :t+1]`` for every
            ``t`` — the host compares drafts against these predictions to
            find the longest accepted prefix (``serving/decode_loop.py``).

            The block is executed as a teacher-forced scan of the *same*
            1-wide step body as ``_decode_step`` (drafted tokens in place
            of argmax feedback).  Byte-identity demands this: a K-wide
            parallel scoring pass reduces its attention and KV projections
            in a different summation order, and the last-bit bf16/fp32
            differences in the written KV rows (and the logits) flip
            greedy argmax near-ties — observed on CPU with tiny models.
            Scanning keeps every logit and every committed KV row
            bit-identical to plain decode while still amortising K tokens
            into ONE dispatch (one host round trip, one program).

            Rejected-suffix rows are written but never read: each step's
            mask exposes rows ``<= R + steps + t`` only, and the next
            dispatch — verify or plain — starts at most ``K-1`` rows back
            and overwrites them before exposing them.  Host state
            (budgets, EOS latch, active gating) stays host-side;
            non-participating slots' writes land in their own dead tail.
            """
            kv_pos = jnp.arange(total, dtype=jnp.int32)[None, None, None, :]

            def body(carry, tok):
                caches, steps = carry
                offsets = jnp.minimum(R + steps, total - 1)
                caches_in = [
                    KVCache(c.keys, c.values, offsets) for c in caches
                ]
                pos = prompt_lens + steps                 # [n_slots]
                prompt_part = kv_pos < prompt_lens[:, None, None, None]
                decode_part = (kv_pos >= R) & (
                    kv_pos - R <= steps[:, None, None, None]
                )
                step_mask = prompt_part | decode_part
                lg, caches_out = self.model.apply(
                    {"params": params}, tok[:, None], pos[:, None],
                    step_mask, caches_in,
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (caches_out, steps + 1), nxt

            (caches, _), preds = jax.lax.scan(
                body, (caches, steps), tokens_blk.T,
            )
            return caches, preds.T                        # [n, K]

        def _snapshot_slot(caches, slot):
            """Copy one slot's KV rows (every layer) and its write offset
            into stand-alone device buffers — the checkpoint half of O(1)
            preempt-resume (``serving/decode_loop.py``).

            ``slot`` is a traced int32 scalar, so one compiled program
            snapshots any slot.  The stacked ``[n_layers, max_total, ...]``
            result lives on device until restored (or dropped), never
            crossing to the host: checkpointing costs one device-side copy,
            not a readback.
            """
            keys = jnp.stack([
                jax.lax.dynamic_slice_in_dim(c.keys, slot, 1, axis=0)[0]
                for c in caches
            ])
            values = jnp.stack([
                jax.lax.dynamic_slice_in_dim(c.values, slot, 1, axis=0)[0]
                for c in caches
            ])
            length = jax.lax.dynamic_slice_in_dim(
                caches[0].length, slot, 1, axis=0
            )[0]
            return keys, values, length

        def _restore_slot(caches, keys, values, slot, length):
            """Write a snapshot back into (any) slot's rows — the restore
            half of O(1) resume.  The buffer layout is identical across
            slots and RoPE is already baked into the stored K/V bytes, so
            a snapshot taken from one slot index replays byte-identically
            from another.
            """
            new_caches = []
            for li, c in enumerate(caches):
                k = jax.lax.dynamic_update_slice(
                    c.keys, keys[li][None], (slot, 0, 0, 0)
                )
                v = jax.lax.dynamic_update_slice(
                    c.values, values[li][None], (slot, 0, 0, 0)
                )
                new_caches.append(
                    KVCache(k, v, c.length.at[slot].set(length))
                )
            return new_caches

        def _free_slots(caches, free_mask):
            """Zero freed slots' KV rows and reset their write offsets.

            The masks already make a freed slot's stale KV unreachable, so
            the scheduler only runs this on failure paths (poisoned
            request, persistent decode error), where the invariants behind
            that argument are themselves suspect.
            """
            row = free_mask[:, None, None, None]
            return [
                KVCache(
                    jnp.where(row, jnp.zeros((), c.keys.dtype), c.keys),
                    jnp.where(row, jnp.zeros((), c.values.dtype), c.values),
                    jnp.where(free_mask, 0, c.length),
                )
                for c in caches
            ]

        self.prefill_chunk = profiled_jit(_prefill_chunk, name="slots.prefill")
        self.decode_step = profiled_jit(_decode_step, name="slots.decode")
        self.verify_block = profiled_jit(_verify_block, name="slots.verify")
        self.free_slots = profiled_jit(_free_slots, name="slots.free")
        self.snapshot_slot = profiled_jit(_snapshot_slot, name="slots.snapshot")
        self.restore_slot = profiled_jit(_restore_slot, name="slots.restore")

    # ---------------------------------------------------------------- state

    def init_caches(self, dtype=jnp.bfloat16) -> List[KVCache]:
        """Fresh all-slots cache: ``[n_slots, max_total, n_kv, head_dim]``
        per layer with a per-slot (vector) write-offset ``length``."""
        cfg = self.config
        head_dim = cfg.dim // cfg.n_heads
        plan = self.plan
        caches = [
            KVCache(
                keys=jnp.zeros(
                    (plan.n_slots, plan.max_total, cfg.n_kv_heads, head_dim),
                    dtype,
                ),
                values=jnp.zeros(
                    (plan.n_slots, plan.max_total, cfg.n_kv_heads, head_dim),
                    dtype,
                ),
                length=jnp.zeros((plan.n_slots,), jnp.int32),
            )
            for _ in range(cfg.n_layers)
        ]
        if self.mesh is not None:
            from music_analyst_tpu.parallel.sharding import shard_kv_caches

            caches = shard_kv_caches(caches, self.mesh, cfg.n_kv_heads)
        return caches

    def kv_bytes(self, dtype=jnp.bfloat16) -> int:
        """Resident KV bytes of the monolithic all-slots cache — the
        engine ledger's occupancy counterpart of the paged runtime's
        ``pool_bytes`` (keys + values across every layer and slot)."""
        cfg = self.config
        head_dim = cfg.dim // cfg.n_heads
        itemsize = jnp.zeros((), dtype).dtype.itemsize
        return (
            cfg.n_layers * 2 * self.plan.n_slots * self.plan.max_total
            * cfg.n_kv_heads * head_dim * itemsize
        )

    def compiled_variants(self) -> int:
        """Total compiled-program count across the six programs — the
        zero-retrace assertion reads this before/after a workload."""
        return sum(
            fn._cache_size()
            for fn in (self.prefill_chunk, self.decode_step, self.verify_block,
                       self.free_slots, self.snapshot_slot, self.restore_slot)
        )

    def prompt_chunks(self, n_tokens: int) -> Sequence[int]:
        """Chunk start offsets covering a prompt of ``n_tokens`` tokens."""
        n = max(1, min(int(n_tokens), self.plan.prompt_region))
        C = self.plan.prefill_chunk
        return range(0, ((n + C - 1) // C) * C, C)
