"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Long-context support is first-class in this framework even though the
reference's longest "sequence" is a 4,000-char prompt truncation
(``scripts/sentiment_classifier.py:90``): lyrics corpora batch into long
packed sequences, and the decoder family must scale past a single chip's
HBM.

Design (blockwise/flash formulation, cf. PAPERS.md ring-attention entry):
queries stay resident; K/V blocks rotate around the ring via ``ppermute``
while each device accumulates its queries' attention with an online-softmax
(running max / normalizer / weighted accumulator).  After ``sp`` steps every
query has seen every key with only neighbor ICI traffic — no all-gather of
the full sequence anywhere.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from music_analyst_tpu.utils.jax_compat import pcast, shard_map

_NEG_INF = -1e30


def _block_attn_update(q, k_blk, v_blk, q_pos, kv_pos, causal, m, l, o,
                       q_seg=None, kv_seg=None):
    """One online-softmax accumulation step against a K/V block."""
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
    )
    allowed = None
    if causal:
        allowed = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
    if q_seg is not None:
        # Block-diagonal over packed documents: same-segment pairs only.
        seg_ok = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        allowed = seg_ok if allowed is None else (allowed & seg_ok)
    if allowed is not None:
        logits = jnp.where(allowed, logits, _NEG_INF)
    block_max = jnp.max(logits, axis=-1)                      # [B,H,Q]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])                    # [B,H,Q,K]
    if allowed is not None:
        # _NEG_INF is finite, so a fully-masked row's exp() is 1, not 0 —
        # re-zero the masked probabilities explicitly.
        p = jnp.where(allowed, p, 0.0)
    new_l = l * correction + p.sum(axis=-1)
    new_o = o * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
    )
    return new_m, new_l, new_o


def ring_attention_local(q, k, v, segment_ids=None, *, axis_name: str,
                         causal: bool = False, use_flash: bool = False):
    """Per-device body; call under ``shard_map`` with sequence sharded.

    Shapes per device: ``q,k,v [B, S/n, H, D]``.  Returns ``[B, S/n, H, D]``.

    ``use_flash=True`` computes each hop's local attention with the Pallas
    blocked kernel (``ops/flash_attention.py``) via its offset + residual
    hooks, then merges the per-hop ``(o, m, l)`` partials with the same
    online-softmax algebra — VMEM-blocked compute inside each hop, ICI
    ``ppermute`` between hops.

    ``segment_ids`` ``[B, S/n]`` (sequence-sharded like ``q``) restricts
    attention to same-segment pairs — the packed-documents long-context
    pattern.  The local segment shard rotates around the ring with its
    K/V block, so cross-device segment boundaries mask exactly.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape
    # GQA: the blocks that ROTATE stay at their compact n_kv_heads size
    # (ring ICI traffic is the scarce resource); the dense path broadcasts
    # to the query-head count only transiently inside each hop, and the
    # flash kernel maps query head -> kv head in its index map.
    group = H // k.shape[2]
    q_pos = idx * S_loc + jnp.arange(S_loc)

    m = jnp.full((B, H, S_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S_loc), jnp.float32)
    o = jnp.zeros((B, H, S_loc, D), jnp.float32)
    # The accumulators become device-varying inside the ring loop; mark the
    # initial values as varying over the axis so the carry types line up.
    m, l, o = (pcast(x, (axis_name,), to="varying") for x in (m, l, o))

    segmented = segment_ids is not None
    # This device's own (query-side) segment shard never rotates; only the
    # kv-side copy travels around the ring in the carry.
    q_seg_loc = segment_ids.astype(jnp.int32) if segmented else None

    def body(step, carry):
        # The segment shard joins the carry ONLY when segmented (the bool
        # is trace-static): unsegmented calls keep the original 5-tuple
        # and pay zero extra ppermute traffic.
        if segmented:
            k_blk, v_blk, seg_blk, m, l, o = carry
        else:
            k_blk, v_blk, m, l, o = carry
            seg_blk = None
        # After `step` rotations (each device passes K/V to the next ring
        # neighbor), this device holds the block originally owned by
        # idx - step.
        owner = (idx - step) % n
        if use_flash:
            from music_analyst_tpu.ops.flash_attention import flash_attention

            o_i, m_i, l_i = flash_attention(
                q, k_blk, v_blk, causal=causal,
                q_offset=idx * S_loc, kv_offset=owner * S_loc,
                return_residuals=True,
                q_segment_ids=q_seg_loc,
                kv_segment_ids=seg_blk,
            )
            o_i = jnp.transpose(o_i, (0, 2, 1, 3))     # [B,H,Q,D]
            m_new = jnp.maximum(m, m_i)
            c_prev = jnp.exp(m - m_new)
            c_hop = jnp.exp(jnp.where(m_i > _NEG_INF / 2, m_i - m_new,
                                      -jnp.inf))
            l = l * c_prev + l_i * c_hop
            o = o * c_prev[..., None] + o_i * c_hop[..., None]
            m = m_new
        else:
            kv_pos = owner * S_loc + jnp.arange(S_loc)
            if group > 1:
                k_use = jnp.repeat(k_blk, group, axis=2)
                v_use = jnp.repeat(v_blk, group, axis=2)
            else:
                k_use, v_use = k_blk, v_blk
            m, l, o = _block_attn_update(
                q, k_use, v_use, q_pos, kv_pos, causal, m, l, o,
                q_seg=q_seg_loc,
                kv_seg=seg_blk,
            )
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if segmented:
            # The segment shard travels WITH its K/V block so cross-device
            # segment boundaries mask exactly on every hop.
            seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
            return k_blk, v_blk, seg_blk, m, l, o
        return k_blk, v_blk, m, l, o

    if segmented:
        init = (k, v, q_seg_loc, m, l, o)
    else:
        init = (k, v, m, l, o)
    *_, m, l, o = jax.lax.fori_loop(0, n, body, init)
    out = o / jnp.maximum(l, 1e-30)[..., None]                # [B,H,Q,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # [B,Q,H,D]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    use_flash: bool = False,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Sequence-parallel attention: ``[B, S, H, D]`` sharded on S over ``axis``.

    ``segment_ids`` ``[B, S]`` adds block-diagonal masking over packed
    documents; the ids shard over ``axis`` with the sequence and rotate
    with the K/V blocks, so segments spanning device boundaries mask
    exactly (composable with ``causal``).
    """
    body = partial(ring_attention_local, axis_name=axis, causal=causal,
                   use_flash=use_flash)
    n_in = 3 if segment_ids is None else 4
    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, axis),) * n_in,
            out_specs=P(None, axis),
            # pallas_call outputs carry no varying-mesh-axis annotation;
            # skip the vma check on the flash path.
            check_vma=not use_flash,
        )
    )
    if segment_ids is None:
        return fn(q, k, v)
    return fn(q, k, v, segment_ids)
