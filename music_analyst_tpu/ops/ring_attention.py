"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Long-context support is first-class in this framework even though the
reference's longest "sequence" is a 4,000-char prompt truncation
(``scripts/sentiment_classifier.py:90``): lyrics corpora batch into long
packed sequences, and the decoder family must scale past a single chip's
HBM.

Design (blockwise/flash formulation, cf. PAPERS.md ring-attention entry):
queries stay resident; K/V blocks rotate around the ring via ``ppermute``
while each device accumulates its queries' attention with an online-softmax
(running max / normalizer / weighted accumulator).  After ``sp`` steps every
query has seen every key with only neighbor ICI traffic — no all-gather of
the full sequence anywhere.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn_update(q, k_blk, v_blk, q_pos, kv_pos, causal, m, l, o):
    """One online-softmax accumulation step against a K/V block."""
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
    )
    if causal:
        allowed = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        logits = jnp.where(allowed, logits, _NEG_INF)
    block_max = jnp.max(logits, axis=-1)                      # [B,H,Q]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])                    # [B,H,Q,K]
    if causal:
        p = jnp.where(allowed, p, 0.0)
    new_l = l * correction + p.sum(axis=-1)
    new_o = o * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
    )
    return new_m, new_l, new_o


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False,
                         use_flash: bool = False):
    """Per-device body; call under ``shard_map`` with sequence sharded.

    Shapes per device: ``q,k,v [B, S/n, H, D]``.  Returns ``[B, S/n, H, D]``.

    ``use_flash=True`` computes each hop's local attention with the Pallas
    blocked kernel (``ops/flash_attention.py``) via its offset + residual
    hooks, then merges the per-hop ``(o, m, l)`` partials with the same
    online-softmax algebra — VMEM-blocked compute inside each hop, ICI
    ``ppermute`` between hops.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape
    # GQA: the blocks that ROTATE stay at their compact n_kv_heads size
    # (ring ICI traffic is the scarce resource); the dense path broadcasts
    # to the query-head count only transiently inside each hop, and the
    # flash kernel maps query head -> kv head in its index map.
    group = H // k.shape[2]
    q_pos = idx * S_loc + jnp.arange(S_loc)

    m = jnp.full((B, H, S_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S_loc), jnp.float32)
    o = jnp.zeros((B, H, S_loc, D), jnp.float32)
    # The accumulators become device-varying inside the ring loop; mark the
    # initial values as varying over the axis so the carry types line up.
    m, l, o = (jax.lax.pcast(x, (axis_name,), to="varying") for x in (m, l, o))

    def body(step, carry):
        k_blk, v_blk, m, l, o = carry
        # After `step` rotations (each device passes K/V to the next ring
        # neighbor), this device holds the block originally owned by
        # idx - step.
        owner = (idx - step) % n
        if use_flash:
            from music_analyst_tpu.ops.flash_attention import flash_attention

            o_i, m_i, l_i = flash_attention(
                q, k_blk, v_blk, causal=causal,
                q_offset=idx * S_loc, kv_offset=owner * S_loc,
                return_residuals=True,
            )
            o_i = jnp.transpose(o_i, (0, 2, 1, 3))     # [B,H,Q,D]
            m_new = jnp.maximum(m, m_i)
            c_prev = jnp.exp(m - m_new)
            c_hop = jnp.exp(jnp.where(m_i > _NEG_INF / 2, m_i - m_new,
                                      -jnp.inf))
            l = l * c_prev + l_i * c_hop
            o = o * c_prev[..., None] + o_i * c_hop[..., None]
            m = m_new
        else:
            kv_pos = owner * S_loc + jnp.arange(S_loc)
            if group > 1:
                k_use = jnp.repeat(k_blk, group, axis=2)
                v_use = jnp.repeat(v_blk, group, axis=2)
            else:
                k_use, v_use = k_blk, v_blk
            m, l, o = _block_attn_update(
                q, k_use, v_use, q_pos, kv_pos, causal, m, l, o
            )
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    _, _, m, l, o = jax.lax.fori_loop(0, n, body, (k, v, m, l, o))
    out = o / jnp.maximum(l, 1e-30)[..., None]                # [B,H,Q,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # [B,Q,H,D]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    use_flash: bool = False,
) -> jax.Array:
    """Sequence-parallel attention: ``[B, S, H, D]`` sharded on S over ``axis``."""
    fn = jax.jit(
        jax.shard_map(
            partial(ring_attention_local, axis_name=axis, causal=causal,
                    use_flash=use_flash),
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
            # pallas_call outputs carry no varying-mesh-axis annotation;
            # skip the vma check on the flash path.
            check_vma=not use_flash,
        )
    )
    return fn(q, k, v)
