"""Pipeline parallelism: GPipe-style microbatching over the ``pp`` mesh axis.

No reference analogue (the reference's only axis is data parallelism,
SURVEY.md §2.4); included so every classic parallelism axis is first-class.

Mechanics: decoder blocks are stacked ``[n_stages, layers_per_stage, ...]``
with the stage axis sharded over ``pp`` — each device owns one stage.
Inside ``shard_map`` a ``lax.scan`` runs ``n_micro + n_stages - 1`` ticks;
each tick every device ppermutes its previous activation to the next ring
neighbor, stage 0 injects the next microbatch, every stage applies its
layers (a ``lax.scan`` over the stage's stacked layer params), and the last
stage records finished microbatches.  Autodiff through scan + ppermute
yields the standard GPipe backward schedule for free — no hand-written
backward pass.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from music_analyst_tpu.profiling.collectives import record_collective
from music_analyst_tpu.utils.jax_compat import pcast, shard_map


def stack_layer_params(params: dict, n_stages: int, prefix: str = "layer_"):
    """``{layer_0: t0, layer_1: t1, ...}`` → stacked ``[n_stages, k, ...]``.

    Returns ``(stacked_tree, n_layers)``; layer order is preserved, layers
    are split contiguously (layers ``[s*k, (s+1)*k)`` form stage ``s``).
    """
    layer_keys = sorted(
        (k for k in params if k.startswith(prefix)),
        key=lambda k: int(k[len(prefix):]),
    )
    n_layers = len(layer_keys)
    if n_layers % n_stages != 0:
        raise ValueError(
            f"{n_layers} layers do not split into {n_stages} stages"
        )
    trees = [params[k] for k in layer_keys]
    stacked_flat = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, n_layers // n_stages) + leaves[0].shape
        ),
        *trees,
    )
    return stacked_flat, n_layers


def unstack_layer_params(stacked, prefix: str = "layer_") -> dict:
    """Inverse of :func:`stack_layer_params` (host-side, for tests)."""
    leaves = jax.tree_util.tree_leaves(stacked)
    n_stages, k = leaves[0].shape[:2]
    out = {}
    for s in range(n_stages):
        for j in range(k):
            out[f"{prefix}{s * k + j}"] = jax.tree_util.tree_map(
                lambda leaf: leaf[s, j], stacked
            )
    return out


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    microbatches: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
):
    """Run the microbatch pipeline; returns outputs shaped like the input.

    ``stage_fn(stage_params, x)`` applies one stage (its ``[k, ...]``
    stacked layers) to activations ``x``; ``microbatches`` is
    ``[n_micro, mb, ...]`` and is replicated (stage 0 injects from it).
    """
    n_stages = mesh.shape[axis]
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked params have {lead} stages but mesh axis "
            f"{axis!r} has {n_stages} devices"
        )

    def body(stage_params, mb):
        # stage_params leaves arrive as [1, k, ...] (this device's stage).
        stage_params = jax.tree_util.tree_map(
            lambda leaf: leaf[0], stage_params
        )
        idx = jax.lax.axis_index(axis)
        n = jax.lax.psum(1, axis)
        n_micro = mb.shape[0]
        ticks = n_micro + n - 1
        state = jnp.zeros_like(mb[0])
        state = pcast(state, (axis,), to="varying")
        outputs = jnp.zeros_like(mb)
        outputs = pcast(outputs, (axis,), to="varying")
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(carry, t):
            state, outputs = carry
            incoming = jax.lax.ppermute(state, axis, perm)
            inject = mb[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(idx == 0, inject, incoming)
            y = stage_fn(stage_params, x_in)
            mb_idx = t - (n - 1)
            is_last = idx == n - 1
            write = is_last & (mb_idx >= 0)
            slot = jnp.clip(mb_idx, 0, n_micro - 1)
            outputs = outputs.at[slot].set(
                jnp.where(write, y, outputs[slot])
            )
            return (y, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(ticks)
        )
        # Only the last stage holds real outputs; broadcast them to all.
        outputs = jax.lax.psum(
            jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        return outputs

    # Analytic wire accounting: one activation ppermute per tick (ticks =
    # n_micro + n_stages - 1), then the final psum that broadcasts the
    # last stage's [n_micro, mb, ...] outputs to every device.
    n_micro = microbatches.shape[0]
    act_bytes = int(
        np.prod(microbatches.shape[1:]) * microbatches.dtype.itemsize
    )
    record_collective(
        "pipeline.activation_shift", "ppermute",
        payload_bytes=act_bytes, n_devices=n_stages, axis=axis,
        count=n_micro + n_stages - 1,
    )
    record_collective(
        "pipeline.output_broadcast", "psum",
        payload_bytes=n_micro * act_bytes, n_devices=n_stages, axis=axis,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
    )(stacked_params, microbatches)
