"""Mesh construction, sharding rules, and collectives.

This package is the TPU-native replacement for the reference's MPI backend
(SURVEY.md §2.4): where the reference uses ``MPI_Init/Bcast/Barrier/Reduce``
and a string-keyed ``Send/Recv`` shuffle over MPICH, this layer builds a
``jax.sharding.Mesh`` over the available chips and lets XLA insert ICI/DCN
collectives (``psum``/``pmax``/``pmin``/``ppermute``) from sharding
annotations.
"""

from music_analyst_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    data_parallel_mesh,
    factor_devices,
)
