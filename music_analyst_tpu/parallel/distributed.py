"""Multi-controller (one JAX process per host) distributed analysis.

The TPU-pod analogue of the reference's N-rank MPI run
(``src/parallel_spotify.c:725-730``): each *process* ingests a disjoint
record range of the dataset, local vocabularies merge through the
coordinator (``MPI_Send``/``Recv`` string shuffle → one
:func:`multihost.allgather_bytes` + :func:`multihost.broadcast_bytes`
round, ``:396-432,1011-1025``), and the dense count vectors merge with a
single ``psum`` across every device of every process — the collective
rides the ICI/DCN fabric XLA targets, no hand-written wire protocol.

Single-process calls degrade to the plain engine path, so this module is
safe to call unconditionally.  Exercised for real (two JAX processes over
Gloo CPU collectives) by ``tests/test_multiprocess.py``.
"""

from __future__ import annotations

import functools
import json
import os
import tempfile
from typing import List, Tuple

import numpy as np

from music_analyst_tpu.data.csv_io import (
    iter_csv_records_exact,
    sort_count_entries,
    write_count_csv,
)
from music_analyst_tpu.data.ingest import IngestResult, ingest_dataset
from music_analyst_tpu.parallel import multihost


def _my_record_range(data: bytes) -> Tuple[bytes, int]:
    """This process's contiguous slice of the dataset's data records.

    Returns a reconstructed mini-dataset (header + owned records — records
    keep their terminator bytes, so concatenation is byte-faithful) plus
    the number of owned records.  Contiguous ranges, like the reference's
    per-rank byte slices, but record-exact.
    """
    records = list(iter_csv_records_exact(data))
    if not records:
        return b"", 0
    header, body = records[0], records[1:]
    n_procs = multihost.process_count()
    share = -(-len(body) // n_procs) if body else 0
    p = multihost.process_index()
    mine = body[p * share : (p + 1) * share]
    return header + b"".join(mine), len(mine)


def _merge_vocabs(local_tokens: List[str]) -> List[str]:
    """Global vocabulary, identical on every process.

    All-gather each process's token list, merge on the coordinator in
    process order (first occurrence wins, preserving the deterministic
    insertion-order ids the exports rely on), broadcast the merged list.
    """
    gathered = multihost.allgather_bytes(
        json.dumps(local_tokens).encode("utf-8")
    )
    merged_payload = None
    if multihost.is_coordinator():
        seen = {}
        for payload in gathered:
            for tok in json.loads(payload.decode("utf-8")):
                if tok not in seen:
                    seen[tok] = len(seen)
        merged_payload = json.dumps(list(seen)).encode("utf-8")
    return json.loads(multihost.broadcast_bytes(merged_payload).decode("utf-8"))


@functools.lru_cache(maxsize=1)
def _global_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), ("dp",))


def _psum_across_processes(local_counts: np.ndarray) -> np.ndarray:
    """One psum over every device of every process → replicated global sum.

    The global mesh spans all processes' devices and XLA's collective does
    the merge — replacing the reference's serialized rank→0 Send/Recv
    accumulation (``src/parallel_spotify.c:1002-1025``).  The compiled
    program is the histogram op's memoized rows-psum (one trace per mesh,
    not per call).
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from music_analyst_tpu.ops.histogram import _psum_rows

    n_local = len(jax.local_devices())
    mesh = _global_mesh()
    # Rows = local devices; row 0 carries the counts, the rest zeros (the
    # ingest is per-process, so there is nothing to split further without
    # re-chunking — the psum result is identical either way).
    rows = np.zeros((n_local, local_counts.shape[0]), local_counts.dtype)
    rows[0] = local_counts
    garr = multihost_utils.host_local_array_to_global_array(rows, mesh, P("dp"))
    out = _psum_rows(mesh, "dp")(garr)
    return np.asarray(jax.device_get(out.addressable_data(0)))


def distributed_wordcount(
    dataset_path: str,
    output_dir: str = "output",
) -> dict:
    """Word/artist counts with per-process ingest + collective merge.

    Every process returns the totals; only the coordinator writes
    ``word_counts.csv``/``top_artists.csv`` (byte-identical to a
    single-process run over the same dataset — asserted by
    ``tests/test_multiprocess.py``).
    """
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    my_slice, _ = _my_record_range(data)
    # Each process runs the full multithreaded C++ ingest on its slice
    # (written to a scratch file — the native scanner is file-based);
    # the pure-Python oracle is the fallback, as everywhere else.
    with tempfile.NamedTemporaryFile(suffix=".csv") as tmp:
        tmp.write(my_slice)
        tmp.flush()
        corpus: IngestResult = ingest_dataset(tmp.name)

    word_tokens = _merge_vocabs(corpus.word_vocab.tokens)
    artist_tokens = _merge_vocabs(corpus.artist_vocab.tokens)

    def global_counts(local_ids, local_tokens, merged_tokens):
        index = {tok: i for i, tok in enumerate(merged_tokens)}
        remap = np.asarray(
            [index[tok] for tok in local_tokens], dtype=np.int64
        )
        counts = np.zeros((max(1, len(merged_tokens)),), dtype=np.int64)
        valid = local_ids[local_ids >= 0]
        if valid.size:
            np.add.at(counts, remap[valid], 1)
        return _psum_across_processes(counts)

    word_counts = global_counts(
        corpus.word_ids, corpus.word_vocab.tokens, word_tokens
    )
    artist_counts = global_counts(
        corpus.artist_ids, corpus.artist_vocab.tokens, artist_tokens
    )
    totals = _psum_across_processes(
        np.asarray([corpus.song_count, corpus.token_count], dtype=np.int64)
    )

    result = {
        "processes": multihost.process_count(),
        "total_songs": int(totals[0]),
        "total_words": int(totals[1]),
    }
    if multihost.is_coordinator():
        os.makedirs(output_dir, exist_ok=True)
        word_entries = sort_count_entries(
            (tok, int(n))
            for tok, n in zip(word_tokens, word_counts)
            if n
        )
        artist_entries = sort_count_entries(
            (tok, int(n))
            for tok, n in zip(artist_tokens, artist_counts)
            if n
        )
        write_count_csv(
            os.path.join(output_dir, "word_counts.csv"), "word", word_entries
        )
        write_count_csv(
            os.path.join(output_dir, "top_artists.csv"), "artist",
            artist_entries,
        )
    multihost.barrier("distributed_wordcount_export")
    return result
