"""Multi-controller (one JAX process per host) distributed analysis.

The TPU-pod analogue of the reference's N-rank MPI run
(``src/parallel_spotify.c:725-730``): each *process* ingests a disjoint
record range of the dataset, local vocabularies merge through the
coordinator (``MPI_Send``/``Recv`` string shuffle → one
:func:`multihost.allgather_bytes` + :func:`multihost.broadcast_bytes`
round, ``:396-432,1011-1025``), and the dense count vectors merge with a
single ``psum`` across every device of every process — the collective
rides the ICI/DCN fabric XLA targets, no hand-written wire protocol.

Single-process calls degrade to the plain engine path, so this module is
safe to call unconditionally.  Exercised for real (two JAX processes over
Gloo CPU collectives) by ``tests/test_multiprocess.py``.
"""

from __future__ import annotations

import functools
import json
import os
import tempfile
from typing import List, Tuple

import numpy as np

from music_analyst_tpu.data.csv_io import (
    iter_csv_records_exact,
    sort_count_entries,
    write_count_csv,
)
from music_analyst_tpu.data.ingest import IngestResult, ingest_dataset
from music_analyst_tpu.parallel import multihost


def _my_record_range(dataset_path: str) -> Tuple[bytes, int]:
    """This process's contiguous slice of the dataset's data records.

    Returns a reconstructed mini-dataset (header + owned records — records
    keep their terminator bytes, so concatenation is byte-faithful) plus
    the number of owned records.  Contiguous ranges, like the reference's
    per-rank byte slices, but record-exact.

    Partitioning runs the native parallel boundary scan
    (``native/ingest.cpp:man_record_ranges``): every process still maps
    the whole file once (the quote-parity scan needs all bytes, so
    per-process memory stays O(file)), but that pass runs at memory
    bandwidth across threads, and only this process's slice is then
    re-read and parsed — unlike the whole-file per-byte Python parse the
    fallback below does.
    The two paths may split blank/``\\r\\n`` filler records differently,
    but every data record lands in exactly one slice either way, which is
    all the collective merge needs.
    """
    from music_analyst_tpu.data import native

    n_procs = multihost.process_count()
    p = multihost.process_index()
    use_native = native.available()
    if n_procs > 1:
        # The two partitioners may split blank/\r\n filler records
        # differently, so ALL processes must use the same one — a mixed
        # run (the .so built on one host, failed on another) would let a
        # record land in two slices or none.  all_agree is a collective:
        # every process calls it, whatever its local availability.
        agreed = multihost.all_agree(use_native)
        use_native = use_native and agreed
    if use_native:
        header_end, begin, end, n = native.record_range(
            dataset_path, n_procs, p
        )
        with open(dataset_path, "rb") as fh:
            header = fh.read(header_end)
            fh.seek(begin)
            body = fh.read(end - begin)
        return (header + body if header else b""), n
    with open(dataset_path, "rb") as fh:
        data = fh.read()
    records = list(iter_csv_records_exact(data))
    if not records:
        return b"", 0
    header, body = records[0], records[1:]
    share = -(-len(body) // n_procs) if body else 0
    mine = body[p * share : (p + 1) * share]
    return header + b"".join(mine), len(mine)


def _merge_vocabs(local_tokens: List[str]) -> List[str]:
    """Global vocabulary, identical on every process.

    All-gather each process's token list, merge on the coordinator in
    process order (first occurrence wins, preserving the deterministic
    insertion-order ids the exports rely on), broadcast the merged list.
    """
    gathered = multihost.allgather_bytes(
        json.dumps(local_tokens).encode("utf-8")
    )
    merged_payload = None
    if multihost.is_coordinator():
        seen = {}
        for payload in gathered:
            for tok in json.loads(payload.decode("utf-8")):
                if tok not in seen:
                    seen[tok] = len(seen)
        merged_payload = json.dumps(list(seen)).encode("utf-8")
    return json.loads(multihost.broadcast_bytes(merged_payload).decode("utf-8"))


@functools.lru_cache(maxsize=1)
def _global_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), ("dp",))


def _psum_across_processes(local_counts: np.ndarray) -> np.ndarray:
    """One psum over every device of every process → replicated global sum.

    The global mesh spans all processes' devices and XLA's collective does
    the merge — replacing the reference's serialized rank→0 Send/Recv
    accumulation (``src/parallel_spotify.c:1002-1025``).  The compiled
    program is the histogram op's memoized rows-psum (one trace per mesh,
    not per call).
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from music_analyst_tpu.ops.histogram import _psum_rows

    n_local = len(jax.local_devices())
    mesh = _global_mesh()
    # Rows = local devices; row 0 carries the counts, the rest zeros (the
    # ingest is per-process, so there is nothing to split further without
    # re-chunking — the psum result is identical either way).
    rows = np.zeros((n_local, local_counts.shape[0]), local_counts.dtype)
    rows[0] = local_counts
    garr = multihost_utils.host_local_array_to_global_array(rows, mesh, P("dp"))
    out = _psum_rows(mesh, "dp")(garr)
    return np.asarray(jax.device_get(out.addressable_data(0)))


def distributed_wordcount(
    dataset_path: str,
    output_dir: str = "output",
) -> dict:
    """Word/artist counts with per-process ingest + collective merge.

    Every process returns the totals; only the coordinator writes
    ``word_counts.csv``/``top_artists.csv`` (byte-identical to a
    single-process run over the same dataset — asserted by
    ``tests/test_multiprocess.py``) plus ``performance_metrics.json``
    whose min/avg/max spread comes from each process's own measured
    compute time — the collective analogue of the reference's six
    ``MPI_Reduce`` timing calls (``src/parallel_spotify.c:1077-1082``).
    """
    import time

    t_start = time.perf_counter()
    my_slice, _ = _my_record_range(dataset_path)
    # Each process runs the full multithreaded C++ ingest on its slice
    # (written to a scratch file — the native scanner is file-based);
    # the pure-Python oracle is the fallback, as everywhere else.
    with tempfile.NamedTemporaryFile(suffix=".csv") as tmp:
        tmp.write(my_slice)
        tmp.flush()
        corpus: IngestResult = ingest_dataset(tmp.name)

    word_tokens = _merge_vocabs(corpus.word_vocab.tokens)
    artist_tokens = _merge_vocabs(corpus.artist_vocab.tokens)

    def global_counts(local_ids, local_tokens, merged_tokens):
        index = {tok: i for i, tok in enumerate(merged_tokens)}
        remap = np.asarray(
            [index[tok] for tok in local_tokens], dtype=np.int64
        )
        counts = np.zeros((max(1, len(merged_tokens)),), dtype=np.int64)
        valid = local_ids[local_ids >= 0]
        if valid.size:
            np.add.at(counts, remap[valid], 1)
        return _psum_across_processes(counts)

    word_counts = global_counts(
        corpus.word_ids, corpus.word_vocab.tokens, word_tokens
    )
    artist_counts = global_counts(
        corpus.artist_ids, corpus.artist_vocab.tokens, artist_tokens
    )
    totals = _psum_across_processes(
        np.asarray([corpus.song_count, corpus.token_count], dtype=np.int64)
    )

    # Per-process compute time: partition + ingest + vocab merge + count
    # psums, measured by each process's own clock, then allgathered so the
    # coordinator sees the real spread — the reference's MPI_Reduce
    # min/avg/max over per-rank timings (src/parallel_spotify.c:1077-1082).
    my_compute = time.perf_counter() - t_start
    per_process = [
        float(json.loads(payload.decode("utf-8")))
        for payload in multihost.allgather_bytes(
            json.dumps(my_compute).encode("utf-8")
        )
    ]
    # Timestamp AFTER the allgather: the coordinator's wait for slower
    # processes is skew, not export work, and must not inflate total_time.
    t_gathered = time.perf_counter()

    result = {
        "processes": multihost.process_count(),
        "total_songs": int(totals[0]),
        "total_words": int(totals[1]),
    }
    if multihost.is_coordinator():
        from music_analyst_tpu.metrics.perf import (
            TimeStats,
            write_performance_metrics,
        )

        os.makedirs(output_dir, exist_ok=True)
        word_entries = sort_count_entries(
            (tok, int(n))
            for tok, n in zip(word_tokens, word_counts)
            if n
        )
        artist_entries = sort_count_entries(
            (tok, int(n))
            for tok, n in zip(artist_tokens, artist_counts)
            if n
        )
        write_count_csv(
            os.path.join(output_dir, "word_counts.csv"), "word", word_entries
        )
        write_count_csv(
            os.path.join(output_dir, "top_artists.csv"), "artist",
            artist_entries,
        )
        export_seconds = time.perf_counter() - t_gathered
        write_performance_metrics(
            os.path.join(output_dir, "performance_metrics.json"),
            processes=multihost.process_count(),
            total_songs=result["total_songs"],
            total_words=result["total_words"],
            compute_time=TimeStats.from_samples(per_process),
            # total = own compute + the coordinator's aggregation/export
            # tail every process waits out at the final barrier (reference
            # semantics: compute + aggregation).
            total_time=TimeStats.from_samples(
                [c + export_seconds for c in per_process]
            ),
            per_chip=[
                {
                    "process": i,
                    "compute_seconds": round(seconds, 9),
                }
                for i, seconds in enumerate(per_process)
            ],
            device_platform="multi-controller",
        )
    multihost.barrier("distributed_wordcount_export")
    return result
