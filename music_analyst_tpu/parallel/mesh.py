"""Device-mesh construction for single-host and multi-host runs.

The reference's world model is ``mpirun -np N`` over homogeneous ranks with
rank 0 as master (``src/parallel_spotify.c:725-730``).  Here the analogue is
a named ``jax.sharding.Mesh`` whose axes carry semantic names:

* ``dp`` — data parallel (batch / corpus shards; the reference's byte-range
  partitioning axis),
* ``tp`` — tensor parallel (model weight shards; no reference analogue —
  needed for the large-LM sentiment config),
* ``sp`` — sequence/context parallel (ring attention over long sequences),
* ``ep`` — expert parallel (MoE layers; optional, folds into ``tp``
  by default),
* ``pp`` — pipeline parallel (layer stages; optional).

Axis layout convention: ``dp`` is the outermost (slowest-varying, may ride
DCN across hosts); ``tp``/``sp`` are innermost so their collectives ride ICI
(scaling-book recipe: keep the chatty axes on the fastest interconnect).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named axis→size assignment; product must equal the device count."""

    axes: Tuple[Tuple[str, int], ...]

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(size for _, size in self.axes)

    def size(self) -> int:
        return math.prod(self.shape)


def factor_devices(
    n_devices: int,
    axis_names: Sequence[str] = ("dp", "tp", "sp"),
    fixed: Optional[Dict[str, int]] = None,
) -> MeshSpec:
    """Factor ``n_devices`` across named axes, largest factors first.

    Greedy: honor ``fixed`` sizes first, then peel the largest power-of-two
    (or remaining prime) factors onto the remaining axes left-to-right, so
    the first axis (usually ``dp``) gets the most devices.  Always returns a
    spec whose product is exactly ``n_devices``.
    """
    fixed = dict(fixed or {})
    remaining = n_devices
    for name, size in fixed.items():
        if remaining % size != 0:
            raise ValueError(
                f"fixed axis {name}={size} does not divide {remaining}"
            )
        remaining //= size
    free_axes = [a for a in axis_names if a not in fixed]
    # Split the remaining device count into len(free_axes) near-even
    # divisor factors, then hand the largest factor to the earliest free
    # axis (dp first) so the batch axis carries the most devices.
    factors: List[int] = []
    for i in range(len(free_axes)):
        slots_left = len(free_axes) - i
        if slots_left == 1:
            factors.append(remaining)
            remaining = 1
            break
        target = max(1, round(remaining ** (1.0 / slots_left)))
        best = 1
        for cand in range(target, 0, -1):
            if remaining % cand == 0:
                best = cand
                break
        for cand in range(target + 1, remaining + 1):
            if remaining % cand == 0:
                if abs(cand - target) < abs(best - target):
                    best = cand
                break
        factors.append(best)
        remaining //= best
    sizes: Dict[str, int] = dict(fixed)
    for name, factor in zip(free_axes, sorted(factors, reverse=True)):
        sizes[name] = factor
    return MeshSpec(tuple((name, sizes[name]) for name in axis_names))


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = ("dp",),
) -> Mesh:
    """Build a mesh from a spec (or a 1-D mesh over all devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if spec is None:
        spec = MeshSpec(((axis_names[0], len(devs)),) if len(axis_names) == 1
                        else tuple(factor_devices(len(devs), axis_names).axes))
    if spec.size() != len(devs):
        raise ValueError(
            f"mesh spec {spec.axes} needs {spec.size()} devices, have {len(devs)}"
        )
    mesh_devices = np.asarray(devs).reshape(spec.shape)
    return Mesh(mesh_devices, spec.names)


def data_parallel_mesh(
    n_devices: Optional[int] = None, axis: str = "dp"
) -> Mesh:
    """1-D data-parallel mesh — the reference's only parallelism strategy."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return build_mesh(MeshSpec(((axis, len(devs)),)), devices=devs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))
