"""Parameter partition rules: param-tree paths → ``PartitionSpec``.

The reference has no tensor parallelism at all (SURVEY.md §2.4: DP via
MPI byte-range sharding is its only axis); TP exists here for the
Llama-family sentiment config the north star requires.

The tensor-parallel layout follows the Megatron/scaling-book recipe: QKV
projections split the *head* axis over ``tp`` and the output projection
splits the *input* head axis (one all-reduce per attention block); MLP
up/gate split the hidden axis, down splits the input axis (one all-reduce
per MLP); embeddings and the LM head split the vocab axis.  Norm scales and
biases replicate.  XLA inserts the psums from these shardings — there is no
hand-written collective in the model code.
"""

from __future__ import annotations

import re
from typing import List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins.  Paths are "/"-joined param tree
# keys, e.g. "encoder/layer_0/attention/q_proj/kernel".
TP_RULES: List[Tuple[str, P]] = [
    # attention: kernel [dim, heads, head_dim] — shard heads
    (r".*(q_proj|k_proj|v_proj)/kernel$", P(None, "tp", None)),
    # attention bias [heads, head_dim] (BERT family) — shard heads to match
    (r".*(q_proj|k_proj|v_proj)/bias$", P("tp", None)),
    # output proj: kernel [heads, head_dim, dim] — shard input heads
    (r".*o_proj/kernel$", P("tp", None, None)),
    # gated MLP: [dim, hidden] / [hidden, dim]
    (r".*(gate_proj|up_proj)/kernel$", P(None, "tp")),
    (r".*down_proj/kernel$", P("tp", None)),
    # MoE expert stacks: [E, dim, hidden] / [E, hidden, dim] — expert axis
    # over ep, hidden over tp; router replicated (matches no rule)
    (r".*(gate_experts|up_experts)$", P("ep", None, "tp")),
    (r".*down_experts$", P("ep", "tp", None)),
    # BERT-style MLP
    (r".*ffn/lin1/kernel$", P(None, "tp")),
    (r".*ffn/lin2/kernel$", P("tp", None)),
    (r".*ffn/lin1/bias$", P("tp")),
    # vocab-sharded embedding + LM head
    (r".*(word_embeddings|tok_embeddings)/embedding$", P("tp", None)),
    (r".*lm_head/kernel$", P(None, "tp")),
]


def spec_for_path(path: str, rules=None) -> P:
    for pattern, spec in rules or TP_RULES:
        if re.match(pattern, path):
            return spec
    return P()  # replicate


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def partition_specs(params, rules=None):
    """PartitionSpec pytree matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: spec_for_path(_path_str(path), rules), params
    )


def prune_spec(spec: P, axis_names) -> P:
    """Drop axes absent from the mesh (so the same rules serve a dp-only
    mesh, a dp×tp mesh, etc.).  The single definition used by
    ``shard_params`` and by abstract-lowering tests, so test placement
    can't silently diverge from production placement."""
    return P(*(a if a in axis_names else None for a in spec))


def shard_params(params, mesh: Mesh, rules=None, drop_unused_axes: bool = True):
    """Place a param tree on ``mesh`` according to the rules.

    Axes named in a rule but absent from the mesh are dropped from the
    spec via :func:`prune_spec`.
    """
    axis_names = set(mesh.axis_names)

    def _place(path, leaf):
        spec = spec_for_path(_path_str(path), rules)
        if drop_unused_axes:
            spec = prune_spec(spec, axis_names)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(_place, params)
