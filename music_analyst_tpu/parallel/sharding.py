"""Parameter partition rules: param-tree paths → ``PartitionSpec``.

The reference has no tensor parallelism at all (SURVEY.md §2.4: DP via
MPI byte-range sharding is its only axis); TP exists here for the
Llama-family sentiment config the north star requires.

The tensor-parallel layout follows the Megatron/scaling-book recipe: QKV
projections split the *head* axis over ``tp`` and the output projection
splits the *input* head axis (one all-reduce per attention block); MLP
up/gate split the hidden axis, down splits the input axis (one all-reduce
per MLP); embeddings and the LM head split the vocab axis.  Norm scales and
biases replicate.  XLA inserts the psums from these shardings — there is no
hand-written collective in the model code.
"""

from __future__ import annotations

import re
from typing import List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins.  Paths are "/"-joined param tree
# keys, e.g. "encoder/layer_0/attention/q_proj/kernel".
TP_RULES: List[Tuple[str, P]] = [
    # attention: kernel [dim, heads, head_dim] — shard heads
    (r".*(q_proj|k_proj|v_proj)/kernel$", P(None, "tp", None)),
    # attention bias [heads, head_dim] (BERT family) — shard heads to match
    (r".*(q_proj|k_proj|v_proj)/bias$", P("tp", None)),
    # output proj: kernel [heads, head_dim, dim] — shard input heads
    (r".*o_proj/kernel$", P("tp", None, None)),
    # gated MLP: [dim, hidden] / [hidden, dim]
    (r".*(gate_proj|up_proj)/kernel$", P(None, "tp")),
    (r".*down_proj/kernel$", P("tp", None)),
    # MoE expert stacks: [E, dim, hidden] / [E, hidden, dim] — expert axis
    # over ep, hidden over tp; router replicated (matches no rule)
    (r".*(gate_experts|up_experts)$", P("ep", None, "tp")),
    (r".*down_experts$", P("ep", "tp", None)),
    # BERT-style MLP
    (r".*ffn/lin1/kernel$", P(None, "tp")),
    (r".*ffn/lin2/kernel$", P("tp", None)),
    (r".*ffn/lin1/bias$", P("tp")),
    # vocab-sharded embedding + LM head
    (r".*(word_embeddings|tok_embeddings)/embedding$", P("tp", None)),
    (r".*lm_head/kernel$", P(None, "tp")),
]


# Decode-runtime KV layout: logical axis name → mesh axis (the
# ``DEFAULT_RULES`` dict shape of megatron-style jax stacks).  Both KV
# layouts the serving stack compiles — the monolithic slot cache
# ``[n_slots, max_total, n_kv_heads, head_dim]`` and the paged pool
# ``[n_pages + 1, page_size, n_kv_heads, head_dim]`` — put the KV-head
# axis third, matching the q/k/v projections' head sharding above, so
# per-head attention never crosses the tp axis and the only decode-path
# collective stays the o_proj all-reduce the param rules already imply.
DECODE_KV_RULES = {
    "slots": None,      # slot / physical-page axis: every chip sees all slots
    "pages": None,
    "tokens": None,     # sequence axis: attention reduces over it per head
    "kv_heads": "tp",   # shard heads with the projections that feed them
    "head_dim": None,
    "lengths": None,    # per-slot write offsets: tiny, replicated
}


def kv_cache_spec(mesh: Mesh, n_kv_heads: int) -> Tuple[P, P]:
    """(keys/values spec, length spec) for a decode KV cache on ``mesh``.

    The head axis shards over ``tp`` only when the mesh has a tp axis
    that divides ``n_kv_heads`` — otherwise the cache replicates, so a
    dp-only mesh (or a tp size the head count can't split) degrades to
    the single-chip layout instead of failing placement.
    """
    head_axis = DECODE_KV_RULES["kv_heads"]
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = axis_sizes.get(head_axis, 1)
    if tp > 1 and n_kv_heads % tp == 0:
        kv = P(None, None, head_axis, None)
    else:
        kv = P()
    return kv, P()


def shard_kv_caches(caches, mesh: Mesh, n_kv_heads: int):
    """Place freshly-initialized decode KV caches on ``mesh`` per
    :data:`DECODE_KV_RULES` (keys/values head-sharded over tp, lengths
    replicated).  ``caches`` is the per-layer list of ``KVCache`` the
    runtimes' ``init_caches`` builds; the dataclass is rebuilt leaf by
    leaf so donated-buffer identity is preserved elsewhere."""
    import dataclasses

    kv_spec, len_spec = kv_cache_spec(mesh, n_kv_heads)
    kv_sh = NamedSharding(mesh, kv_spec)
    len_sh = NamedSharding(mesh, len_spec)
    out = []
    for c in caches:
        extra = {}
        if getattr(c, "key_scale", None) is not None:
            # int8 paged pools (ops/kv_pages.QuantizedKVPages) carry
            # per-(page, row) scale planes: no head axis, so they
            # replicate like the lengths.
            extra = dict(
                key_scale=jax.device_put(c.key_scale, len_sh),
                value_scale=jax.device_put(c.value_scale, len_sh),
            )
        out.append(
            dataclasses.replace(
                c,
                keys=jax.device_put(c.keys, kv_sh),
                values=jax.device_put(c.values, kv_sh),
                length=jax.device_put(c.length, len_sh),
                **extra,
            )
        )
    return out


def spec_for_path(path: str, rules=None) -> P:
    for pattern, spec in rules or TP_RULES:
        if re.match(pattern, path):
            return spec
    return P()  # replicate


def _path_str(path) -> str:
    parts = []
    for p in path:
        part = getattr(p, "key", None)
        if part is None:
            part = getattr(p, "idx", None)
        if part is None:
            # register_dataclass fields flatten with GetAttrKey(.name)
            part = getattr(p, "name", None)
        parts.append(str(p if part is None else part))
    return "/".join(parts)


def _is_quantized(leaf) -> bool:
    from music_analyst_tpu.ops.quant import QuantizedParam

    return isinstance(leaf, QuantizedParam)


def _quantized_specs(qp, base: P):
    """Spec-holding QuantizedParam for a stored-quantized kernel.

    ``q`` keeps the float kernel's rule (same rank — int4 halves axis 0
    but keeps head/hidden divisibility, e.g. 8B o_proj heads 32→16 still
    split by tp=4); ``scale`` replicates its leading group axis and
    inherits the kernel's *feature*-axis placement so the epilogue
    multiply needs no resharding.  Meta fields are preserved, so the spec
    tree stays structure-congruent with the param tree.
    """
    import dataclasses

    padded = tuple(base) + (None,) * (len(qp.shape) - len(tuple(base)))
    scale_spec = P(None, *padded[qp.n_contract:])
    return dataclasses.replace(qp, q=base, scale=scale_spec)


def partition_specs(params, rules=None):
    """PartitionSpec pytree matching ``params``.

    ``QuantizedParam`` leaves are resolved atomically — the rule lookup
    sees the kernel's tree path (".../kernel"), not the dataclass's inner
    ``q``/``scale`` fields — and come back as a QuantizedParam holding one
    spec per data field.
    """

    def _spec(path, leaf):
        spec = spec_for_path(_path_str(path), rules)
        if _is_quantized(leaf):
            return _quantized_specs(leaf, spec)
        return spec

    return jax.tree_util.tree_map_with_path(
        _spec, params, is_leaf=lambda x: _is_quantized(x)
    )


def prune_spec(spec: P, axis_names) -> P:
    """Drop axes absent from the mesh (so the same rules serve a dp-only
    mesh, a dp×tp mesh, etc.).  The single definition used by
    ``shard_params`` and by abstract-lowering tests, so test placement
    can't silently diverge from production placement."""
    return P(*(a if a in axis_names else None for a in spec))


def shard_params(params, mesh: Mesh, rules=None, drop_unused_axes: bool = True):
    """Place a param tree on ``mesh`` according to the rules.

    Axes named in a rule but absent from the mesh are dropped from the
    spec via :func:`prune_spec`.
    """
    axis_names = set(mesh.axis_names)

    def _place(path, leaf):
        spec = spec_for_path(_path_str(path), rules)
        if _is_quantized(leaf):
            import dataclasses

            specs = _quantized_specs(leaf, spec)
            if drop_unused_axes:
                specs = dataclasses.replace(
                    specs,
                    q=prune_spec(specs.q, axis_names),
                    scale=prune_spec(specs.scale, axis_names),
                )
            return dataclasses.replace(
                leaf,
                q=jax.device_put(leaf.q, NamedSharding(mesh, specs.q)),
                scale=jax.device_put(
                    leaf.scale, NamedSharding(mesh, specs.scale)
                ),
            )
        if drop_unused_axes:
            spec = prune_spec(spec, axis_names)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(
        _place, params, is_leaf=lambda x: _is_quantized(x)
    )
