"""Multi-host coordination: the Bcast/Barrier analogues.

The reference synchronizes ranks with ``MPI_Bcast`` (split-file names,
``src/parallel_spotify.c:830-831``) and ``MPI_Barrier`` (``:850,1067``).
Under single-controller JAX a single host drives every chip, so in-process
these are no-ops; under multi-controller (one process per host, as on
multi-host TPU pods) they map onto ``jax.experimental.multihost_utils``.
Every call here degrades to the trivial behavior when only one process is
present, so engine code calls them unconditionally.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    """Process 0 — the analogue of the reference's rank-0 master role."""
    return jax.process_index() == 0


def broadcast_from_coordinator(value: Any) -> Any:
    """Broadcast a pytree of host values from process 0 to all processes."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def all_agree(value) -> bool:
    """Check a host scalar is identical on every process (debug guard)."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(value))
    return bool((gathered == gathered[0]).all())


def allgather_bytes(payload: bytes) -> list:
    """Gather one byte string from every process, in process order.

    The building block for metadata exchange (vocabulary merge) that the
    reference does with serialized ``MPI_Send``/``MPI_Recv`` strings
    (``src/parallel_spotify.c:396-432``).  Collectives need uniform shapes,
    so this is two rounds: an allgather of lengths, then an allgather of
    max-length-padded ``uint8`` rows.
    """
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.asarray([len(payload)], dtype=np.int64)
    ).ravel()
    width = max(1, int(lengths.max()))
    row = np.zeros((width,), dtype=np.uint8)
    row[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    # The collective may return a widened dtype (psum-backed transport
    # upcasts uint8); restore it before reading raw bytes back out.
    rows = np.asarray(
        multihost_utils.process_allgather(row), dtype=np.uint8
    )
    return [
        rows[i, : int(lengths[i])].tobytes()
        for i in range(jax.process_count())
    ]


def broadcast_bytes(payload: Optional[bytes]) -> bytes:
    """Broadcast a byte string from the coordinator to every process.

    The analogue of the reference's ``MPI_Bcast`` of the split-file names
    (``src/parallel_spotify.c:830-831``), for variable-size payloads:
    length first, then the padded byte row, both via
    :func:`broadcast_from_coordinator`.
    """
    if jax.process_count() == 1:
        assert payload is not None
        return payload
    data = payload if is_coordinator() else b""
    length = int(
        broadcast_from_coordinator(np.asarray([len(data)], np.int64))[0]
    )
    row = np.zeros((max(1, length),), dtype=np.uint8)
    if is_coordinator():
        row[:length] = np.frombuffer(data, dtype=np.uint8)
    # Same dtype restore as allgather_bytes: broadcast_one_to_all rides a
    # psum that upcasts uint8, and tobytes() on int32 reads 4x the bytes.
    row = np.asarray(broadcast_from_coordinator(row), dtype=np.uint8)
    return row[:length].tobytes()
