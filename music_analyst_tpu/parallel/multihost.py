"""Multi-host coordination: the Bcast/Barrier analogues.

The reference synchronizes ranks with ``MPI_Bcast`` (split-file names,
``src/parallel_spotify.c:830-831``) and ``MPI_Barrier`` (``:850,1067``).
Under single-controller JAX a single host drives every chip, so in-process
these are no-ops; under multi-controller (one process per host, as on
multi-host TPU pods) they map onto ``jax.experimental.multihost_utils``.
Every call here degrades to the trivial behavior when only one process is
present, so engine code calls them unconditionally.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """Process 0 — the analogue of the reference's rank-0 master role."""
    return jax.process_index() == 0


def broadcast_from_coordinator(value: Any) -> Any:
    """Broadcast a pytree of host values from process 0 to all processes."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def all_agree(value) -> bool:
    """Check a host scalar is identical on every process (debug guard)."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(value))
    return bool((gathered == gathered[0]).all())
