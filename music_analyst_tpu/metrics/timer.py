"""Wall-clock stage timers.

The reference brackets compute and total with ``MPI_Wtime``
(``src/parallel_spotify.c:850-851,1000,1067-1068``).  Under single-controller
JAX the host drives every chip, so stage timing is host wall-clock around
blocking device calls (``block_until_ready``) — which is also the honest
apples-to-apples definition when comparing against the MPI binary
(SURVEY.md §7 "Timing semantics").
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator


class StageTimer:
    """Accumulates named wall-clock stage durations."""

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}

    @contextmanager
    def stage(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] = self.seconds.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def total(self, *names: str) -> float:
        if not names:
            return sum(self.seconds.values())
        return sum(self.seconds.get(n, 0.0) for n in names)
