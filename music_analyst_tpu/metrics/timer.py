"""Wall-clock stage timers.

The reference brackets compute and total with ``MPI_Wtime``
(``src/parallel_spotify.c:850-851,1000,1067-1068``).  Under single-controller
JAX the host drives every chip, so stage timing is host wall-clock around
blocking device calls (``block_until_ready``) — which is also the honest
apples-to-apples definition when comparing against the MPI binary
(SURVEY.md §7 "Timing semantics").
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator


class StageTimer:
    """Accumulates named wall-clock stage durations.

    Also a telemetry span adapter: every stage opens a same-named span on
    the process registry (``music_analyst_tpu/telemetry``), so engines
    keep one timing call-site and the JSONL event log sees the stage
    hierarchy for free.  ``self.seconds`` stays the sole source for
    ``performance_metrics.json`` — its keys and accumulation semantics are
    byte-stable whether telemetry is enabled or not.
    """

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}

    @contextmanager
    def stage(self, name: str) -> Iterator[None]:
        from music_analyst_tpu.telemetry import get_telemetry

        start = time.perf_counter()
        try:
            with get_telemetry().span(name):
                yield
        finally:
            self.seconds[name] = self.seconds.get(name, 0.0) + (
                time.perf_counter() - start
            )

    def total(self, *names: str) -> float:
        if not names:
            return sum(self.seconds.values())
        return sum(self.seconds.get(n, 0.0) for n in names)
