"""Profiler tracing hooks.

The reference's only observability is wall-clock timestamps (SURVEY.md §5
"Tracing/profiling: wall-clock only").  Here any engine run can capture a
full XLA/TPU profiler trace (HLO timelines, per-op device time) viewable in
TensorBoard/Perfetto, via one context manager.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``trace_dir`` when set."""
    if not trace_dir:
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the profiler timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield
