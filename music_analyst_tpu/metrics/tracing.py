"""Deprecated: moved to ``music_analyst_tpu.profiling.trace``.

This shim keeps ``from music_analyst_tpu.metrics.tracing import
maybe_trace, annotate`` working; new code should import from
``profiling.trace`` (which adds :func:`profile_run`, the span-level
Chrome trace, and :func:`force_readback`).
"""

from __future__ import annotations

import warnings

from music_analyst_tpu.profiling.trace import (  # noqa: F401
    annotate,
    force_readback,
    maybe_trace,
    profile_run,
)

warnings.warn(
    "music_analyst_tpu.metrics.tracing is deprecated; import from "
    "music_analyst_tpu.profiling.trace instead",
    DeprecationWarning,
    stacklevel=2,
)

__all__ = ["annotate", "force_readback", "maybe_trace", "profile_run"]
