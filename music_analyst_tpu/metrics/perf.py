"""``performance_metrics.json`` writer — reference schema, superset fields.

The reference writes ``{processes, total_songs, total_words,
compute_time{avg,min,max_seconds}, total_time{...}}`` by hand-formatted
fprintf (``src/parallel_spotify.c:1084-1109``).  This writer reproduces that
schema exactly (keys, nesting, 6-decimal seconds) and appends the TPU-era
extensions required by the north star: a per-chip timing column, device
platform info, and stage breakdowns.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class TimeStats:
    avg_seconds: float
    min_seconds: float
    max_seconds: float

    @classmethod
    def uniform(cls, seconds: float) -> "TimeStats":
        """SPMD timing: one synchronous program — avg == min == max.

        The reference's per-rank min/avg/max spread comes from MPI ranks
        running asynchronously (``src/parallel_spotify.c:1077-1082``); a
        jitted SPMD program is lock-stepped across chips, so the three
        statistics legitimately coincide.  Paths with genuinely per-chip
        phases use :meth:`from_samples` instead.
        """
        return cls(seconds, seconds, seconds)

    @classmethod
    def from_samples(cls, samples: Sequence[float]) -> "TimeStats":
        """min/avg/max over per-chip measurements — the honest analogue of
        the reference's six ``MPI_Reduce`` timing statistics
        (``src/parallel_spotify.c:1077-1082``)."""
        if not samples:
            return cls.uniform(0.0)
        return cls(sum(samples) / len(samples), min(samples), max(samples))

    def as_dict(self) -> Dict[str, float]:
        return {
            "avg_seconds": round(self.avg_seconds, 6),
            "min_seconds": round(self.min_seconds, 6),
            "max_seconds": round(self.max_seconds, 6),
        }


def write_performance_metrics(
    path: str,
    processes: int,
    total_songs: int,
    total_words: int,
    compute_time: TimeStats,
    total_time: TimeStats,
    per_chip: Optional[List[Dict[str, Any]]] = None,
    stages: Optional[Dict[str, float]] = None,
    device_platform: Optional[str] = None,
) -> None:
    payload: Dict[str, Any] = {
        "processes": processes,
        "total_songs": total_songs,
        "total_words": total_words,
        "compute_time": compute_time.as_dict(),
        "total_time": total_time.as_dict(),
    }
    if device_platform is not None:
        payload["device_platform"] = device_platform
    if per_chip is not None:
        payload["per_chip"] = per_chip
    if stages is not None:
        payload["stages"] = {k: round(v, 6) for k, v in stages.items()}
    from music_analyst_tpu.utils.atomic import atomic_write

    with atomic_write(path) as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
