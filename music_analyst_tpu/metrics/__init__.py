"""Timing capture and performance-metrics export."""

from music_analyst_tpu.metrics.perf import TimeStats, write_performance_metrics
from music_analyst_tpu.metrics.timer import StageTimer
