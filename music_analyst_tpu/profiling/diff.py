"""The perf-regression gate: ``profile-diff`` and the bench baseline.

Compares two runs — each a ``run_manifest.json``, a bench.py JSON line,
or a committed ``BENCH_r*.json`` driver capture — and exits nonzero on
regression, so CI (``make smoke``) and the round trajectory can gate on
perf instead of eyeballing it.

What counts as a regression (each guarded by its own threshold):

* **throughput** (bench lines): B's ``value`` dropping more than
  ``threshold`` below A's,
* **wall** (manifests): B's ``wall_seconds`` growing more than
  ``wall_threshold`` over A's,
* **recompiles** (manifests, informational by default): B recompiling
  where A did not usually explains the wall regression; always printed.

Exit codes: 0 = within thresholds, 1 = regression, 2 = unusable input
(missing file, no comparable metric — a gate must fail loudly, not pass
vacuously).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple


def _parse_payload(payload: Dict[str, Any], origin: str) -> Dict[str, Any]:
    """Normalize one loaded JSON object into a comparable record."""
    # Driver capture ({"n", "cmd", "rc", "tail", "parsed"}): unwrap.
    if "parsed" in payload and "rc" in payload:
        parsed = payload.get("parsed")
        if not isinstance(parsed, dict):
            raise ValueError(
                f"{origin}: driver capture has no parsed bench line "
                f"(rc={payload.get('rc')})"
            )
        return _parse_payload(parsed, origin)
    if "metric" in payload and "value" in payload:
        return {
            "kind": "bench",
            "origin": origin,
            "metric": payload["metric"],
            "value": float(payload["value"]),
            "unit": payload.get("unit"),
            "error": payload.get("error"),
        }
    if "wall_seconds" in payload and "schema" in payload:
        counters = payload.get("counters") or {}
        compile_info = payload.get("compile") or {}
        return {
            "kind": "manifest",
            "origin": origin,
            "engine": payload.get("engine"),
            "wall_seconds": float(payload["wall_seconds"]),
            "compile_seconds": float(compile_info.get("seconds") or 0.0),
            "compile_count": int(compile_info.get("count") or 0),
            "recompiles": int(counters.get("profiling.recompiles", 0)),
            "collective_bytes": int(
                counters.get("collectives.total_bytes", 0)
            ),
        }
    raise ValueError(
        f"{origin}: neither a bench line, a driver capture, nor a "
        "run manifest (keys: " + ", ".join(sorted(payload)[:8]) + ")"
    )


def load_metrics(source: str) -> Dict[str, Any]:
    """Load + normalize one comparand: a file path or a literal JSON line."""
    text: Optional[str] = None
    if os.path.exists(source):
        with open(source, "r", encoding="utf-8") as fh:
            text = fh.read()
        origin = source
    else:
        text = source
        origin = "<inline json>"
    try:
        payload = json.loads(text)
    except json.JSONDecodeError as exc:
        raise ValueError(f"{origin}: not JSON ({exc})") from exc
    if not isinstance(payload, dict):
        raise ValueError(f"{origin}: expected a JSON object")
    return _parse_payload(payload, origin)


def compare(
    a: Dict[str, Any],
    b: Dict[str, Any],
    threshold: float = 0.1,
    wall_threshold: float = 0.25,
) -> Tuple[bool, list]:
    """Returns ``(regressed, report_lines)`` for two normalized records."""
    lines = []
    regressed = False
    if a["kind"] != b["kind"]:
        raise ValueError(
            f"cannot compare a {a['kind']} against a {b['kind']} "
            f"({a['origin']} vs {b['origin']})"
        )
    if a["kind"] == "bench":
        if a.get("metric") != b.get("metric"):
            raise ValueError(
                f"metric mismatch: {a.get('metric')} vs {b.get('metric')}"
            )
        va, vb = a["value"], b["value"]
        if va <= 0:
            raise ValueError(
                f"{a['origin']}: baseline value {va} is not a usable "
                "throughput" + (f" (error: {a['error']})" if a.get("error")
                                else "")
            )
        ratio = vb / va
        drop = 1.0 - ratio
        verdict = "REGRESSION" if drop > threshold else "ok"
        regressed = drop > threshold
        lines.append(
            f"throughput {a['metric']}: {va:.1f} -> {vb:.1f} "
            f"({ratio:.3f}x, threshold -{threshold:.0%}) {verdict}"
        )
        if b.get("error"):
            lines.append(f"  note: B carries an error: {b['error']}")
    else:
        wa, wb = a["wall_seconds"], b["wall_seconds"]
        if wa > 0:
            growth = wb / wa - 1.0
            verdict = "REGRESSION" if growth > wall_threshold else "ok"
            regressed |= growth > wall_threshold
            lines.append(
                f"wall_seconds: {wa:.3f} -> {wb:.3f} "
                f"({growth:+.1%}, threshold +{wall_threshold:.0%}) {verdict}"
            )
        else:
            lines.append(f"wall_seconds: {wa:.3f} -> {wb:.3f} (no baseline)")
        lines.append(
            f"compile: {a['compile_count']} compiles/"
            f"{a['compile_seconds']:.2f}s -> {b['compile_count']}/"
            f"{b['compile_seconds']:.2f}s"
        )
        ra, rb = a["recompiles"], b["recompiles"]
        if rb > ra:
            lines.append(
                f"recompiles: {ra} -> {rb} "
                "(new recompile activity — likely shape instability)"
            )
        else:
            lines.append(f"recompiles: {ra} -> {rb}")
        ca, cb = a["collective_bytes"], b["collective_bytes"]
        if ca or cb:
            lines.append(f"collective bytes/device: {ca} -> {cb}")
    return regressed, lines


def run_profile_diff(
    a_source: str,
    b_source: str,
    threshold: float = 0.1,
    wall_threshold: float = 0.25,
) -> int:
    """CLI entry: compare A (baseline) against B (candidate)."""
    import sys

    try:
        a = load_metrics(a_source)
        b = load_metrics(b_source)
        regressed, lines = compare(
            a, b, threshold=threshold, wall_threshold=wall_threshold
        )
    except ValueError as exc:
        print(f"profile-diff: {exc}", file=sys.stderr)
        return 2
    print(f"A: {a['origin']} ({a['kind']})")
    print(f"B: {b['origin']} ({b['kind']})")
    for line in lines:
        print(line)
    print("verdict:", "REGRESSION" if regressed else "ok")
    return 1 if regressed else 0
