"""Compile/device-level profiling layered on the telemetry registry.

Four pieces (PERFORMANCE.md §"Profiling a run"):

* ``profiling/compile.py`` — :func:`profiled_jit` wraps the jit
  lower/compile boundary: per-compile ``cost_analysis()`` FLOPs/bytes,
  ``memory_analysis()``, an HLO fingerprint, and a recompile detector
  keyed on abstract avals (``profiling.compiles`` / ``.recompiles``
  counters + a ``compile``/``recompile`` event per occurrence).
* ``profiling/collectives.py`` — analytic per-step byte estimates for
  ``psum`` / ``all_gather`` / all-to-all / ``ppermute`` from mesh shape
  + payload shape (``collectives.*_bytes`` counters + one ``collective``
  event per call site = the per-stage table in ``telemetry.jsonl``).
* ``profiling/trace.py`` — device-time capture: ``jax.profiler`` traces
  plus a Chrome-trace artifact rendered from this run's telemetry spans
  (``--profile-dir``); wall timings come from forced ``np.asarray``
  readbacks, never ``block_until_ready`` (axon tunnel gotcha).
* ``profiling/diff.py`` — the regression gate behind
  ``python -m music_analyst_tpu profile-diff A B`` and
  ``bench.py --baseline``.

Import discipline: this package (and everything it re-exports here) must
stay importable before jax — ``tests/conftest.py`` forces the CPU
platform first.  Submodules that need jax import it lazily or are only
imported from already-jax-bound modules.
"""

from music_analyst_tpu.profiling.collectives import (
    all_gather_bytes,
    all_to_all_bytes,
    emit_stage_table,
    ppermute_bytes,
    psum_bytes,
    record_collective,
    stage_table,
)
from music_analyst_tpu.profiling.diff import load_metrics, run_profile_diff

__all__ = [
    "all_gather_bytes",
    "all_to_all_bytes",
    "emit_stage_table",
    "ppermute_bytes",
    "psum_bytes",
    "record_collective",
    "stage_table",
    "load_metrics",
    "run_profile_diff",
    "profiled_jit",
    "compile_records",
]


def __getattr__(name):
    # profiled_jit/compile_records live in a jax-importing module; resolve
    # them lazily so `import music_analyst_tpu.profiling` stays jax-free.
    if name in ("profiled_jit", "compile_records", "ProfiledFunction"):
        from music_analyst_tpu.profiling import compile as _compile

        return getattr(_compile, name)
    raise AttributeError(name)
