"""Compile-boundary introspection: :func:`profiled_jit`.

Wraps the jit lower/compile boundary the engines use so every compiled
program records what it costs before it ever runs:

* ``cost_analysis()`` — FLOPs and bytes-accessed per execution,
* ``memory_analysis()`` — temp/argument/output allocation bytes (TPU
  backends implement it; CPU returns nothing and the field stays null),
* an HLO fingerprint (sha256 of the lowered StableHLO text) so two runs
  can prove they executed the same program, and
* a **recompile detector**: calls are keyed on their abstract avals
  (shape/dtype of every array leaf + values of everything static); a new
  key after the first compile bumps ``profiling.recompiles`` and emits a
  ``recompile`` event naming the offending shape change — the telemetry
  answer to "why is this run spending its wall-clock in XLA".

The wrapper is a fallback-safe veneer over ``jax.jit``: the AOT
``lower(...).compile()`` path feeds the records, and any AOT-ineligible
call pattern (donated buffers, weak types the executable rejects, …)
falls through to the plain jitted callable — numerics never depend on the
profiler.  TPU note: executables are *invoked* exactly as jit would; no
``block_until_ready`` anywhere (axon tunnel gotcha — readbacks stay the
caller's ``np.asarray``).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax

# Process-lifetime registry of every ProfiledFunction, in creation order —
# the manifest's ``profiling`` section reads it at run exit.
_REGISTRY: List["ProfiledFunction"] = []
_REGISTRY_LOCK = threading.Lock()


def _leaf_sig(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}{list(shape)}"
    return repr(leaf)


def _aval_key(args: tuple, kwargs: dict) -> str:
    """Abstract signature of a call: array leaves contribute shape/dtype,
    everything else (static ints, strings) its repr."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return ";".join(_leaf_sig(leaf) for leaf in leaves) + f"#{treedef}"


def _wq_param_bytes(args: tuple, kwargs: dict) -> Optional[Dict[str, int]]:
    """Param-bytes breakdown of the call's weight-quantized argument
    trees (stored int codes+scales vs the float bytes a dequantizing
    epilogue transiently touches), or ``None`` for all-float calls —
    the field only appears once quantization is actually in play."""
    try:
        from music_analyst_tpu.ops.quant import (
            QuantizedParam,
            param_tree_bytes,
        )

        def _has_qp(tree) -> bool:
            return any(
                isinstance(leaf, QuantizedParam)
                for leaf in jax.tree_util.tree_leaves(
                    tree, is_leaf=lambda x: isinstance(x, QuantizedParam)
                )
            )

        trees = [
            a for a in list(args) + list(kwargs.values()) if _has_qp(a)
        ]
        if not trees:
            return None
        return param_tree_bytes(trees)
    except Exception:
        return None


def _scalar(analysis: Any, key: str) -> Optional[float]:
    """Pull one metric out of ``cost_analysis()`` output, whose container
    type changed across jax versions (dict vs [dict])."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    value = analysis.get(key)
    try:
        return float(value) if value is not None else None
    except (TypeError, ValueError):
        return None


class CompileRecord:
    """One compiled program's cost/memory/fingerprint digest."""

    __slots__ = (
        "name", "aval_key", "flops", "bytes_accessed", "temp_bytes",
        "argument_bytes", "output_bytes", "hlo_fingerprint",
        "compile_seconds", "param_bytes",
    )

    def __init__(self, name: str, aval_key: str) -> None:
        self.name = name
        self.aval_key = aval_key
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.temp_bytes: Optional[int] = None
        self.argument_bytes: Optional[int] = None
        self.output_bytes: Optional[int] = None
        self.hlo_fingerprint: Optional[str] = None
        self.compile_seconds: float = 0.0
        # Weight-quantized calls only: stored vs dequant-transient bytes
        # of the argument param tree (ops.quant.param_tree_bytes).
        self.param_bytes: Optional[Dict[str, int]] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "aval_key": self.aval_key,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "hlo_fingerprint": self.hlo_fingerprint,
            "compile_seconds": round(self.compile_seconds, 6),
            "param_bytes": self.param_bytes,
        }


class ProfiledFunction:
    """A jitted callable whose compiles are observed and keyed on avals."""

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 **jit_kwargs: Any) -> None:
        self._fn = fn
        self.name = name or getattr(fn, "__name__", None) or "jit_fn"
        self._jit = jax.jit(fn, **jit_kwargs)
        self._lock = threading.Lock()
        self._compiled: Dict[str, Any] = {}  # aval_key -> executable | None
        self.records: Dict[str, CompileRecord] = {}
        with _REGISTRY_LOCK:
            _REGISTRY.append(self)

    # -------------------------------------------------------- introspection

    def _record(self, key: str, lowered: Any, compiled: Any,
                seconds: float) -> CompileRecord:
        rec = CompileRecord(self.name, key)
        rec.compile_seconds = seconds
        try:
            rec.hlo_fingerprint = hashlib.sha256(
                lowered.as_text().encode()
            ).hexdigest()[:16]
        except Exception:
            pass
        try:
            cost = compiled.cost_analysis()
            rec.flops = _scalar(cost, "flops")
            rec.bytes_accessed = _scalar(cost, "bytes accessed")
        except Exception:
            pass
        try:
            mem = compiled.memory_analysis()
            rec.temp_bytes = int(mem.temp_size_in_bytes)
            rec.argument_bytes = int(mem.argument_size_in_bytes)
            rec.output_bytes = int(mem.output_size_in_bytes)
        except Exception:
            pass  # CPU PJRT has no memory_analysis — fields stay null
        return rec

    def _compile_for(self, key: str, args: tuple, kwargs: dict) -> Any:
        """AOT-compile for this aval key; record + count; None on failure."""
        from music_analyst_tpu.telemetry import get_telemetry

        tel = get_telemetry()
        try:
            from music_analyst_tpu.observability import watchdog
            from music_analyst_tpu.resilience.faults import fault_point
            from music_analyst_tpu.resilience.policy import RetryPolicy

            def _lower_and_compile():
                fault_point("compile.first", fn=self.name)
                low = self._jit.lower(*args, **kwargs)
                return low, low.compile()

            t0 = time.perf_counter()
            # First compiles are the classic silent-hang site on the
            # tunneled backend; a watchdog trip here reads compile_hang.
            # Transient failures (tunnel blip, injected compile.first
            # fault) get re-attempted; a persistent one falls through to
            # the plain-jit path below — degraded introspection, same
            # results.
            with watchdog.watch(f"compile:{self.name}", kind="compile"):
                lowered, compiled = RetryPolicy(base_s=0.05, cap_s=1.0).call(
                    _lower_and_compile, site="compile.first"
                )
            seconds = time.perf_counter() - t0
        except Exception as exc:
            # Not AOT-eligible (or the backend refused): the plain jit
            # call still compiles and runs; we just lose the record.
            tel.event("compile_introspection_failed", fn=self.name,
                      error=str(exc)[:200])
            return None
        rec = self._record(key, lowered, compiled, seconds)
        rec.param_bytes = _wq_param_bytes(args, kwargs)
        prior = list(self.records)
        self.records[key] = rec
        tel.count("profiling.compiles")
        attrs = rec.as_dict()
        attrs["fn"] = attrs.pop("name")  # "name" is the event name itself
        tel.event("compile", **attrs)
        if prior:
            # Same function, new avals: that is THE recompile signature —
            # log old→new so the offending shape change is one grep away.
            tel.count("profiling.recompiles")
            tel.event(
                "recompile", fn=self.name, prev_aval=prior[-1],
                new_aval=key, n_variants=len(prior) + 1,
            )
        return compiled

    # --------------------------------------------------------------- call

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        # Called under an outer trace (jit-of-jit): no concrete inputs to
        # AOT-compile against — defer to plain jit, which inlines.
        if any(
            isinstance(leaf, jax.core.Tracer)
            for leaf in jax.tree_util.tree_leaves((args, kwargs))
        ):
            return self._jit(*args, **kwargs)
        key = _aval_key(args, kwargs)
        with self._lock:
            known = key in self._compiled
            executable = self._compiled.get(key)
        if not known:
            executable = self._compile_for(key, args, kwargs)
            with self._lock:
                self._compiled[key] = executable
        if executable is not None:
            try:
                return executable(*args, **kwargs)
            except Exception:
                # Executable/argument mismatch (layout, weak type, …):
                # permanently fall back for this key.
                with self._lock:
                    self._compiled[key] = None
        return self._jit(*args, **kwargs)

    # Parity helpers so a ProfiledFunction drops in where jax.jit was.
    def lower(self, *args: Any, **kwargs: Any):
        return self._jit.lower(*args, **kwargs)

    def _cache_size(self) -> int:
        """Compiled-variant count (jit cache + AOT executables): the
        no-retrace tests assert this stays flat across repeat calls."""
        with self._lock:
            aot = len(self._compiled)
        try:
            return self._jit._cache_size() + aot
        except Exception:
            return aot


def profiled_jit(fn: Callable, name: Optional[str] = None,
                 **jit_kwargs: Any) -> ProfiledFunction:
    """``jax.jit`` with compile introspection + recompile detection.

    Drop-in at the engines' jit boundaries; see the module docstring for
    what each compile records.  ``jit_kwargs`` pass through to ``jax.jit``
    (``static_argnames``, ``out_shardings``, …).
    """
    return ProfiledFunction(fn, name=name, **jit_kwargs)


def compile_records() -> List[Dict[str, Any]]:
    """Every CompileRecord in this process, in compile order per function.

    Process-lifetime (memoized engine callables outlive a single run), so
    the manifest labels it accordingly.
    """
    with _REGISTRY_LOCK:
        fns = list(_REGISTRY)
    out: List[Dict[str, Any]] = []
    for fn in fns:
        out.extend(rec.as_dict() for rec in fn.records.values())
    return out
