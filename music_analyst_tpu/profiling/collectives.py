"""Analytic collective-traffic accounting (DrJAX-style, PAPERS.md).

The engines' collectives are few and regular — the wordcount histogram's
``psum``, sharded inference's gathers, the pipeline's per-tick
``ppermute`` — so per-step bytes moved are *computable* from mesh shape +
payload shape; no device counters needed (the axon plugin exposes none).
Estimators follow the standard ring-algorithm costs per participating
device:

* all-reduce (``psum``):    ``2 · (N-1)/N · payload``  (reduce-scatter +
  all-gather halves),
* ``all_gather``:           ``(N-1) · shard``  (each device receives every
  other shard),
* all-to-all:               ``(N-1)/N · payload``  (each device keeps its
  own 1/N),
* ``ppermute``:             ``payload``  (one neighbor send per tick).

:func:`record_collective` turns an estimate into telemetry: cumulative
``collectives.<kind>_bytes`` / ``collectives.total_bytes`` counters (they
land in the run manifest) and one ``collective`` event per call site —
the per-stage table in ``telemetry.jsonl``.

No jax import here: estimators are pure arithmetic, callable from tests
before the platform override lands.
"""

from __future__ import annotations

import threading
from typing import Dict, List


def psum_bytes(payload_bytes: int, n_devices: int) -> int:
    """Ring all-reduce bytes moved per device."""
    if n_devices <= 1:
        return 0
    return int(2 * (n_devices - 1) * payload_bytes // n_devices)


def all_gather_bytes(shard_bytes: int, n_devices: int) -> int:
    """Bytes received per device gathering every other shard."""
    if n_devices <= 1:
        return 0
    return int((n_devices - 1) * shard_bytes)


def all_to_all_bytes(payload_bytes: int, n_devices: int) -> int:
    """Bytes sent per device; 1/N of the payload stays local."""
    if n_devices <= 1:
        return 0
    return int((n_devices - 1) * payload_bytes // n_devices)


def ppermute_bytes(payload_bytes: int) -> int:
    """One neighbor send: the payload itself."""
    return int(payload_bytes)


_ESTIMATORS = {
    "psum": psum_bytes,
    "all_gather": all_gather_bytes,
    "all_to_all": all_to_all_bytes,
}

# Per-stage accumulator behind the "collective_stage_table" event: rows
# keyed by stage name, process-lifetime (cleared per run by run_scope's
# emit via :func:`emit_stage_table`).
_STAGE_TOTALS: Dict[str, Dict[str, object]] = {}
_STAGE_LOCK = threading.Lock()


def stage_table() -> List[Dict[str, object]]:
    """Snapshot of per-stage collective totals accumulated so far."""
    with _STAGE_LOCK:
        return [
            {"stage": stage, **row} for stage, row in _STAGE_TOTALS.items()
        ]


def emit_stage_table(reset: bool = True) -> List[Dict[str, object]]:
    """Emit the per-stage table as one ``collective_stage_table`` event.

    Engines call this at run end so ``telemetry.jsonl`` carries a single
    digestible table next to the per-call ``collective`` events; ``reset``
    clears the accumulator so back-to-back runs don't bleed rows.
    """
    rows = stage_table()
    if rows:
        from music_analyst_tpu.telemetry import get_telemetry

        get_telemetry().event("collective_stage_table", rows=rows)
    if reset:
        with _STAGE_LOCK:
            _STAGE_TOTALS.clear()
    return rows


def record_collective(
    stage: str,
    kind: str,
    *,
    payload_bytes: int,
    n_devices: int,
    axis: str = "dp",
    count: int = 1,
) -> int:
    """Account one collective call site; returns bytes/device it moves.

    ``stage`` names the engine stage (the JSONL table's row key), ``kind``
    is ``psum`` | ``all_gather`` | ``all_to_all`` | ``ppermute``;
    ``count`` multiplies repeated issues of the same collective (pipeline
    ticks).  Disabled telemetry still returns the estimate so callers can
    use it for their own reporting.
    """
    if kind == "ppermute":
        per_device = ppermute_bytes(payload_bytes)
    else:
        try:
            per_device = _ESTIMATORS[kind](payload_bytes, n_devices)
        except KeyError:
            raise ValueError(
                f"unknown collective kind {kind!r} "
                f"(expected one of {sorted(_ESTIMATORS) + ['ppermute']})"
            )
    total = per_device * count
    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    tel.count(f"collectives.{kind}_bytes", total)
    tel.count("collectives.total_bytes", total)
    with _STAGE_LOCK:
        row = _STAGE_TOTALS.setdefault(
            stage, {"kind": kind, "axis": axis, "calls": 0, "bytes": 0}
        )
        row["calls"] += count
        row["bytes"] += total
    tel.event(
        "collective",
        stage=stage,
        kind=kind,
        axis=axis,
        devices=n_devices,
        payload_bytes=int(payload_bytes),
        bytes_per_device=per_device,
        count=count,
        total_bytes=total,
    )
    return per_device
