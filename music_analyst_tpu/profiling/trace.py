"""Device-time capture: profiler traces + a span-level Chrome trace.

Promoted from the old ``metrics/tracing.py`` (shimmed through PR 3,
removed in PR 4).  Two granularities:

* :func:`maybe_trace` / :func:`annotate` — the raw ``jax.profiler``
  capture (HLO timelines, per-op device time) for TensorBoard/Perfetto,
  unchanged semantics from the old module;
* :func:`profile_run` — the ``--profile-dir`` flag's backing: wraps a run
  in ``jax.profiler`` (tolerating tunnel failures — a dead axon must not
  kill the analysis it was profiling) **and** renders this run's
  telemetry spans into ``<dir>/trace_spans.json``, a self-contained
  Chrome-trace artifact (``chrome://tracing`` / Perfetto) that works even
  where the device-side profiler cannot.

Timing discipline: wall timings everywhere come from forced
``np.asarray`` readbacks at the engines' sync points, never
``block_until_ready`` — the axon loopback tunnel does not reliably honor
it (CLAUDE.md gotcha).
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``trace_dir`` when set."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the profiler timeline."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def force_readback(value: Any) -> np.ndarray:
    """Synchronize by materializing the bytes on the host.

    THE timing barrier for this codebase: ``np.asarray`` forces the device
    to produce the result before the clock reads, which
    ``block_until_ready`` does not guarantee through the axon tunnel.
    """
    return np.asarray(value)


def spans_to_chrome_trace(tel) -> Dict[str, Any]:
    """Render a registry's recorded spans as Chrome-trace JSON.

    Complete events (``ph: "X"``) on the monotonic clock, one ``tid`` per
    thread name; span attributes ride along in ``args``.  Raw spans cap at
    the registry's in-memory bound, so huge runs render their head — the
    aggregate table in the manifest stays exact.
    """
    with tel._lock:
        spans = list(tel.spans)
    if spans:
        base = min(sp.t_mono for sp in spans)
    else:
        base = 0.0
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for sp in spans:
        tid = tids.setdefault(sp.thread, len(tids) + 1)
        event: Dict[str, Any] = {
            "name": sp.name,
            "ph": "X",
            "ts": round((sp.t_mono - base) * 1e6, 3),
            "dur": round(sp.duration_s * 1e6, 3),
            "pid": 1,
            "tid": tid,
        }
        if sp.attrs:
            event["args"] = {k: str(v) for k, v in sp.attrs.items()}
        events.append(event)
    events.extend(
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": thread}}
        for thread, tid in tids.items()
    )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(tel, path: str) -> str:
    payload = spans_to_chrome_trace(tel)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
        fh.write("\n")
    return path


@contextlib.contextmanager
def profile_run(profile_dir: Optional[str]) -> Iterator[None]:
    """``--profile-dir``: device profiler capture + span Chrome trace.

    The ``jax.profiler`` start/stop is best-effort (the device-side
    profiler can refuse over a dead tunnel; the run must still produce its
    analysis); the span-level ``trace_spans.json`` always lands because it
    is rendered purely from host-side telemetry.
    """
    if not profile_dir:
        yield
        return
    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    os.makedirs(profile_dir, exist_ok=True)
    started = False
    try:
        import jax

        jax.profiler.start_trace(profile_dir)
        started = True
    except Exception as exc:
        tel.event("profiler_trace_unavailable", error=str(exc)[:200])
    try:
        yield
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as exc:
                tel.event("profiler_trace_stop_failed", error=str(exc)[:200])
        try:
            write_chrome_trace(
                tel, os.path.join(profile_dir, "trace_spans.json")
            )
        except Exception as exc:
            tel.event("span_trace_write_failed", error=str(exc)[:200])
