"""Per-request distributed tracing with tail-latency attribution.

Aggregate telemetry (histograms, counters) says *that* p99 degraded;
this module says *why one request* was slow.  A trace context —
``{"id": <trace id>, "parent": <parent span id>, "span": <this
process's span id>}`` — is minted at admission (or adopted from the
``ndjson/v1`` wire's optional ``"trace"`` field; absent ⇒ new root) and
carried in ``ServeRequest.meta["trace"]`` across every seam: router
dispatch and requeue hops, WFQ wait and the shed ladder, slot claim,
chunked prefill, decode/verify ticks, preemption + O(1) resume, the
journal group-commit barrier, and the reply write.

**Phases vs details.**  Spans come in two categories.  ``phase`` spans
are a *contiguous partition* of the request's wall time inside one
process (``admit → queue → prefill → decode → commit → reply`` on the
decode path; ``admit → queue → downstream → commit → reply`` in a
router front end), maintained by a per-request wall-clock cursor in
``meta["trace_t"]`` — so their sum covers the wire latency by
construction and ``trace-report`` can attribute the critical path
exactly.  ``detail`` spans (per-chunk prefill, ``journal.sync``) overlap
the phases and never enter the attribution sum.

**Sampling.**  Head sampling is a deterministic function of the trace
id (``crc32(id) / 2^32 < sample``) so every process in the fleet makes
the same decision with zero coordination; tail sampling *always* keeps
a request that was shed, failed, preempted, requeued, or breached its
TTFT/TPOT SLO (the worker's reply carries ``trace_keep`` so the front
end keeps its half of the waterfall too).  Kept traces flush as one
JSON line each into ``<dir>/request_traces.jsonl`` (single appended
``write`` — multi-process safe) plus a Chrome-trace artifact at close;
a flush failure (fault site ``reqtrace.flush``) degrades to a counted
``trace_drops`` and never blocks the reply path.

Disabled (no ``--profile-dir`` / ``$MUSICAAL_TRACE_DIR``) the recorder
is inert: one attribute check per seam, no minting, no extra reply
fields — byte-for-byte the untraced wire.

Host-side only, no jax imports — importable before the test harness
pins ``JAX_PLATFORMS``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional

DEFAULT_TRACE_SAMPLE = 0.0
TRACE_FILE = "request_traces.jsonl"

_ENV_SAMPLE = "MUSICAAL_TRACE_SAMPLE"
_ENV_DIR = "MUSICAAL_TRACE_DIR"

# Bounded per-process buffers: live traces (in-flight requests) and the
# flushed-trace ring behind exemplars + the Chrome artifact.  Overflow
# drops the *oldest* (a leaked live trace from a client that vanished
# must not pin memory) and is counted, never silent.
_MAX_LIVE = 4096
_MAX_SPANS = 512
_MAX_FINISHED = 4096
_MAX_CHROME_EVENTS = 50_000

# Phase names that partition wall time (the attribution set).  Anything
# else in a trace is a detail span; trace-report uses the same set.
PHASE_NAMES = frozenset((
    "admit", "queue", "batch", "prefill", "decode", "gap.preempt",
    "hop.requeue", "downstream", "commit", "reply",
))


def resolve_trace_sample(value: Optional[Any] = None) -> float:
    """Head-sampling probability: explicit flag > $MUSICAAL_TRACE_SAMPLE
    > 0.0.  A malformed/out-of-range flag raises (usage error); a
    malformed env var falls back to the default, like every other
    ``resolve_*`` in serving/batcher.py."""
    if value is not None:
        try:
            sample = float(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"--trace-sample expects a float in [0, 1], got {value!r}"
            )
        if not 0.0 <= sample <= 1.0:
            raise ValueError(
                f"--trace-sample expects a float in [0, 1], got {sample!r}"
            )
        return sample
    raw = os.environ.get(_ENV_SAMPLE)
    if raw:
        try:
            sample = float(raw)
        except ValueError:
            return DEFAULT_TRACE_SAMPLE
        if 0.0 <= sample <= 1.0:
            return sample
    return DEFAULT_TRACE_SAMPLE


def resolve_trace_dir(value: Optional[str] = None) -> Optional[str]:
    """Trace output directory: explicit (``--profile-dir``) >
    $MUSICAAL_TRACE_DIR > None (tracing disabled)."""
    if value:
        return value
    return os.environ.get(_ENV_DIR) or None


class RequestTraceRecorder:
    """One process's half of the fleet's request traces."""

    def __init__(self, sample: float = 0.0,
                 directory: Optional[str] = None,
                 role: str = "server") -> None:
        self.sample = float(sample)
        self.directory = directory
        self.role = role
        self.enabled = directory is not None
        self.path = (
            os.path.join(directory, TRACE_FILE) if directory else None
        )
        self._lock = threading.Lock()
        self._local = threading.local()
        self._seq = 0
        # trace id -> {"spans": [...], "keep": reason|None, "dropped": n}
        self._live: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._finished: List[Dict[str, Any]] = []
        self._chrome: List[Dict[str, Any]] = []
        self._chrome_tids: Dict[str, int] = {}
        self._stats = {
            "started": 0, "flushed": 0, "discarded": 0, "tail_kept": 0,
            "trace_drops": 0, "spans_dropped": 0, "live_evicted": 0,
        }
        self._closed = False

    # ------------------------------------------------------------ context

    def mint(self, wire: Optional[Any] = None) -> Dict[str, Any]:
        """Adopt the wire's trace context, or mint a new root.

        Every process gets its own ``span`` id (the id downstream hops
        name as their ``parent``); the trace id itself is shared by the
        whole request across the fleet."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        span = f"{os.getpid():x}-{seq:x}"
        if isinstance(wire, dict) and isinstance(wire.get("id"), str):
            parent = wire.get("span")
            return {
                "id": wire["id"][:64],
                "parent": parent if isinstance(parent, str) else None,
                "span": span,
            }
        return {
            "id": os.urandom(8).hex(),
            "parent": None,
            "span": span,
        }

    def sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling: every process in the fleet makes
        the same call for the same trace id, no coordination."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return (zlib.crc32(trace_id.encode("utf-8", "replace"))
                / 4294967296.0) < self.sample

    def set_pending(self, trace: Dict[str, Any], t_admit: float) -> None:
        """Stash the freshly minted wire context for the ``submit`` the
        parser is about to make on this same thread; ``begin_request``
        consumes it (programmatic submitters skip this and mint there)."""
        self._local.pending = (trace, t_admit)

    def _take_pending(self):
        pend = getattr(self._local, "pending", None)
        self._local.pending = None
        return pend

    def begin_request(self, req: Any) -> None:
        """Attach the trace context + wall-clock cursor to one admitted
        (or about-to-be-shed) request.  Called from every ``submit``
        right after the ``ServeRequest`` is built — *before* the shed
        ladder, so sheds carry trace ids too."""
        if not self.enabled:
            return
        pend = self._take_pending()
        now = time.time()
        trace = req.meta.get("trace")
        t_admit = now
        if trace is None:
            if pend is not None:
                trace, t_admit = pend
            else:
                trace = self.mint()
            req.meta["trace"] = trace
        tt = req.meta.setdefault("trace_t", {})
        tt.setdefault("admit", t_admit)
        tt["cursor"] = now
        with self._lock:
            if trace["id"] not in self._live:
                self._stats["started"] += 1
                self._live[trace["id"]] = {
                    "spans": [], "keep": None, "dropped": 0,
                }
                while len(self._live) > _MAX_LIVE:
                    self._live.popitem(last=False)
                    self._stats["live_evicted"] += 1
        self.phase(req, "admit", t_admit, now, op=req.op,
                   tenant=req.tenant, priority=req.priority)

    # -------------------------------------------------------------- spans

    def _span(self, trace_id: str, name: str, t0: float, t1: float,
              cat: str, attrs: Dict[str, Any]) -> None:
        span = {
            "name": name,
            "cat": cat,
            "t": round(t0, 6),
            "dur": round(max(t1 - t0, 0.0), 6),
        }
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            entry = self._live.get(trace_id)
            if entry is None:
                # Late span (trace already flushed) or a keep() that
                # arrived before begin: resurrect a bounded entry.
                entry = self._live[trace_id] = {
                    "spans": [], "keep": None, "dropped": 0,
                }
                while len(self._live) > _MAX_LIVE:
                    self._live.popitem(last=False)
                    self._stats["live_evicted"] += 1
            if len(entry["spans"]) >= _MAX_SPANS:
                entry["dropped"] += 1
                self._stats["spans_dropped"] += 1
                return
            entry["spans"].append(span)

    def phase(self, req: Any, name: str, t0: Optional[float],
              t1: Optional[float], **attrs: Any) -> None:
        """One attribution phase (see PHASE_NAMES): a slice of the
        cursor partition.  No-op for untraced requests."""
        if not self.enabled:
            return
        trace = req.meta.get("trace")
        if trace is None or t0 is None or t1 is None:
            return
        self._span(trace["id"], name, t0, t1, "phase", attrs)

    def detail(self, req: Any, name: str, t0: Optional[float],
               t1: Optional[float], **attrs: Any) -> None:
        """One overlapping detail span (never enters attribution)."""
        if not self.enabled:
            return
        trace = req.meta.get("trace")
        if trace is None or t0 is None or t1 is None:
            return
        self._span(trace["id"], name, t0, t1, "detail", attrs)

    def advance(self, req: Any, name: str, **attrs: Any) -> Optional[float]:
        """Record the phase from the request's cursor to now, then move
        the cursor — the one-liner the hot seams use.  Returns the new
        cursor (now) for callers that chain."""
        if not self.enabled:
            return None
        trace = req.meta.get("trace")
        if trace is None:
            return None
        tt = req.meta.setdefault("trace_t", {})
        now = time.time()
        t0 = tt.get("cursor", now)
        self._span(trace["id"], name, t0, now, "phase", attrs)
        tt["cursor"] = now
        return now

    def keep(self, req: Any, reason: str) -> None:
        """Tail-sampling mark: this request's trace flushes regardless
        of the head-sampling coin (shed / SLO breach / preemption /
        requeue)."""
        if not self.enabled:
            return
        trace = req.meta.get("trace")
        if trace is None:
            return
        with self._lock:
            entry = self._live.get(trace["id"])
            if entry is None:
                entry = self._live[trace["id"]] = {
                    "spans": [], "keep": None, "dropped": 0,
                }
            if entry["keep"] is None:
                entry["keep"] = str(reason)[:80]
                self._stats["tail_kept"] += 1

    def keep_reason(self, req: Any) -> Optional[str]:
        """The tail-keep reason (None when only head-sampled)."""
        if not self.enabled:
            return None
        trace = req.meta.get("trace")
        if trace is None:
            return None
        with self._lock:
            entry = self._live.get(trace["id"])
            return entry["keep"] if entry is not None else None

    # ----------------------------------------------------------- settling

    def on_complete(self, req: Any, payload: Dict[str, Any]) -> None:
        """``ServeRequest.complete`` hook — ONE place that covers every
        settle path (succeed, every shed kind, failures, router replies):
        stamps the reply with the trace id, records the settle wall
        clock, and tail-keeps failures + downstream keep marks."""
        trace = req.meta.get("trace")
        if trace is None:
            return
        payload.setdefault("trace_id", trace["id"])
        tt = req.meta.setdefault("trace_t", {})
        tt["settle"] = time.time()
        downstream_keep = payload.get("trace_keep")
        if isinstance(downstream_keep, str):
            self.keep(req, downstream_keep)
        elif not payload.get("ok"):
            error = payload.get("error")
            kind = (error or {}).get("kind") if isinstance(error, dict) \
                else None
            self.keep(req, kind or "failed")

    def annotate_reply(self, req: Any) -> None:
        """Right before the reply line is written: carry the tail-keep
        verdict on the wire so an upstream router keeps its half of the
        waterfall for a request its worker found interesting."""
        if not self.enabled:
            return
        reason = self.keep_reason(req)
        if reason and isinstance(req.response, dict):
            req.response.setdefault("trace_keep", reason)

    def finish_request(self, req: Any) -> None:
        """The reply left this process: decide keep-vs-discard and flush
        this process's span record as one JSONL line.  Never raises —
        the reply path is already done and must not be re-entered."""
        if not self.enabled:
            return
        trace = req.meta.get("trace")
        if trace is None:
            return
        with self._lock:
            entry = self._live.pop(trace["id"], None)
        if entry is None:
            return
        kept = entry["keep"]
        if kept is None and not self.sampled(trace["id"]):
            with self._lock:
                self._stats["discarded"] += 1
            return
        tt = req.meta.get("trace_t") or {}
        spans = entry["spans"]
        record: Dict[str, Any] = {
            "schema": 1,
            "trace_id": trace["id"],
            "span": trace.get("span"),
            "parent": trace.get("parent"),
            "pid": os.getpid(),
            "role": self.role,
            "req_id": str(req.id),
            "op": req.op,
            "tenant": req.tenant,
            "priority": req.priority,
            "kept": kept or "head",
            "spans": spans,
        }
        t_admit, t_settle = tt.get("admit"), tt.get("settle")
        if t_admit is not None and t_settle is not None:
            record["wire_s"] = round(max(t_settle - t_admit, 0.0), 6)
        if entry["dropped"]:
            record["spans_dropped"] = entry["dropped"]
        try:
            self._flush(record)
        except Exception:  # noqa: BLE001 — never block the reply path
            with self._lock:
                self._stats["trace_drops"] += 1
            return
        with self._lock:
            self._stats["flushed"] += 1
            self._finished.append({
                "trace_id": trace["id"],
                "wire_s": record.get("wire_s"),
                "kept": record["kept"],
                "op": req.op,
                "t": round(time.time(), 6),
            })
            if len(self._finished) > _MAX_FINISHED:
                del self._finished[: len(self._finished) - _MAX_FINISHED]
            self._remember_chrome(record)

    def _flush(self, record: Dict[str, Any]) -> None:
        """One appended write per trace: atomic enough for concurrent
        replica processes sharing the file.  The fault gate sits INSIDE
        so an injected failure exercises the real degradation path."""
        from music_analyst_tpu.resilience.faults import fault_point

        fault_point("reqtrace.flush", trace_id=record["trace_id"])
        os.makedirs(self.directory, exist_ok=True)
        line = json.dumps(record, separators=(",", ":"), default=str)
        fd = os.open(self.path,
                     os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, (line + "\n").encode("utf-8"))
        finally:
            os.close(fd)

    # ----------------------------------------------------- chrome + stats

    def _remember_chrome(self, record: Dict[str, Any]) -> None:
        """Caller holds ``_lock``.  Chrome ``X`` events, one tid per
        trace (profiling/trace.py's shape, µs timestamps)."""
        if len(self._chrome) >= _MAX_CHROME_EVENTS:
            return
        tid = self._chrome_tids.get(record["trace_id"])
        if tid is None:
            tid = len(self._chrome_tids) + 1
            self._chrome_tids[record["trace_id"]] = tid
            self._chrome.append({
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid,
                "args": {"name": f"trace {record['trace_id'][:12]}"},
            })
        for span in record["spans"]:
            self._chrome.append({
                "name": span["name"],
                "cat": span.get("cat", "phase"),
                "ph": "X",
                "ts": round(span["t"] * 1e6, 3),
                "dur": round(span["dur"] * 1e6, 3),
                "pid": os.getpid(),
                "tid": tid,
                "args": {
                    k: str(v)
                    for k, v in (span.get("attrs") or {}).items()
                },
            })

    def write_chrome(self, path: Optional[str] = None) -> Optional[str]:
        """The flushed traces as one chrome://tracing-loadable artifact
        (per process — the pid suffix keeps replica workers from
        clobbering the front end's file)."""
        if not self.enabled:
            return None
        with self._lock:
            events = list(self._chrome)
        if not events:
            return None
        if path is None:
            path = os.path.join(
                self.directory,
                f"request_traces_chrome.{os.getpid()}.json",
            )
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(
                    {"traceEvents": events, "displayTimeUnit": "ms"}, fh
                )
        except OSError:
            return None
        return path

    def close(self) -> Optional[str]:
        """End of serving: write the Chrome artifact once."""
        if self._closed:
            return None
        self._closed = True
        return self.write_chrome()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self._stats)
            out["live"] = len(self._live)
        out["sample"] = self.sample
        out["directory"] = self.directory
        return out

    def exemplars(self) -> Dict[str, Any]:
        """Tail exemplars for the latency quantile blocks: the flushed
        trace nearest each wire-latency quantile, so "show me p99"
        dereferences to an actual request in request_traces.jsonl."""
        with self._lock:
            finished = [
                f for f in self._finished
                if isinstance(f.get("wire_s"), (int, float))
            ]
        if not finished:
            return {}
        finished.sort(key=lambda f: f["wire_s"])
        n = len(finished)

        def pick(p: float) -> Dict[str, Any]:
            f = finished[min(n - 1, int(round(p * (n - 1))))]
            return {"trace_id": f["trace_id"],
                    "wire_s": round(f["wire_s"], 6),
                    "kept": f["kept"]}

        return {
            "serving.request_seconds": {
                "n": n,
                "p50": pick(0.50),
                "p95": pick(0.95),
                "p99": pick(0.99),
            }
        }

    def nearest_kept(self, t_wall: Optional[float] = None
                     ) -> Optional[Dict[str, Any]]:
        """The tail-kept flushed trace nearest wall-clock ``t_wall`` —
        what a burn-rate alert embeds so the breach dereferences to a
        request waterfall.  Falls back to head-sampled traces when
        nothing was tail-kept, and to the newest flush when no
        timestamp is given."""
        with self._lock:
            finished = list(self._finished)
        if not finished:
            return None
        kept = [f for f in finished if f.get("kept") not in (None, "head")]
        pool = kept or finished
        if t_wall is None:
            return pool[-1]
        return min(
            pool, key=lambda f: abs((f.get("t") or 0.0) - float(t_wall))
        )


_DISABLED = RequestTraceRecorder()
_RECORDER: RequestTraceRecorder = _DISABLED


def get_reqtrace() -> RequestTraceRecorder:
    return _RECORDER


def configure_reqtrace(
    sample: Optional[Any] = None,
    directory: Optional[str] = None,
    role: str = "server",
) -> RequestTraceRecorder:
    """Install the process recorder.  When enabled, the resolved dir and
    sample are exported to the environment so spawned replica workers
    inherit the fleet's tracing configuration without extra plumbing."""
    global _RECORDER
    resolved_sample = resolve_trace_sample(sample)
    resolved_dir = resolve_trace_dir(directory)
    recorder = RequestTraceRecorder(
        resolved_sample, resolved_dir, role=role
    )
    if recorder.enabled:
        os.environ[_ENV_DIR] = resolved_dir
        os.environ[_ENV_SAMPLE] = repr(resolved_sample)
    _RECORDER = recorder
    return recorder
