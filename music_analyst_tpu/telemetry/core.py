"""Process-wide run telemetry: spans, counters, gauges, histograms, sinks.

The reference's only observability is hand-printed wall-clock timestamps
in one JSON file (SURVEY.md §5 "Tracing/profiling: wall-clock only").
This registry is the framework-wide replacement: every engine opens
hierarchical **spans** (start/end wall + monotonic time, parent linkage,
thread-safe), bumps **counters/gauges** (songs ingested, rows classified,
HTTP retries, …), and the registry fans the stream out to two sinks —

* an append-only JSONL event log (``<dir>/telemetry.jsonl``, one event
  per line, both clocks on every line), and
* a run manifest written when the owning scope exits
  (``<dir>/run_manifest.json`` — see ``telemetry/introspect.py``).

Design rules:

* **Zero hard deps on jax** — this module must be importable before
  ``tests/conftest.py`` forces the CPU platform; anything device-aware
  lives in ``introspect.py`` behind lazy imports.
* **Cheap when disabled** — every public entry point no-ops off one flag
  so engines instrument unconditionally.
* **One registry per process** — mirrors the reference's one-metrics-file
  worldview and keeps the CLI/engine/library entry points coherent; the
  owning :func:`Telemetry.run_scope` resets per-run state so back-to-back
  runs in one process (the sweep engine, the test suite) don't bleed
  counters into each other's manifests.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

# Latency-shaped default buckets (seconds): spans from sub-ms device
# dispatches up to the Ollama client's 120 s HTTP timeout.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0,
)

# Raw spans kept in memory per run — aggregates are unbounded-safe, the
# raw list is a debugging convenience and must not grow with corpus size.
_MAX_RAW_SPANS = 10_000


class Span:
    """One completed (or in-flight) named region."""

    __slots__ = (
        "name", "span_id", "parent_id", "thread", "t_wall", "t_mono",
        "duration_s", "attrs",
    )

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 thread: str, t_wall: float, t_mono: float) -> None:
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.thread = thread
        self.t_wall = t_wall
        self.t_mono = t_mono
        self.duration_s = 0.0
        self.attrs: Dict[str, Any] = {}

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes (row counts, byte counts, …) to the span."""
        self.attrs.update(attrs)
        return self

    def as_event(self) -> Dict[str, Any]:
        event: Dict[str, Any] = {
            "type": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "t_wall": round(self.t_wall, 6),
            "t_mono": round(self.t_mono, 6),
            "dur_s": round(self.duration_s, 9),
        }
        if self.attrs:
            event["attrs"] = self.attrs
        return event


class _NullSpan:
    """Shared do-nothing span handle for the disabled path."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


# Reservoir size for streaming quantiles.  Exact below the cap; above it
# a seeded uniform reservoir keeps quantile error ~1/sqrt(cap) — plenty
# for p99 latency reporting, and deterministic for a fixed value stream.
_QUANTILE_SAMPLE_CAP = 4096

# The quantiles every histogram summary exports (serving latency
# reporting reads these; telemetry-report renders them).
QUANTILES: Tuple[Tuple[str, float], ...] = (
    ("p50", 0.50), ("p95", 0.95), ("p99", 0.99),
)


class Histogram:
    """Fixed-bucket latency histogram (upper-bound buckets + overflow)
    with streaming min/max and reservoir-sampled p50/p95/p99."""

    __slots__ = ("buckets", "counts", "total", "n", "vmin", "vmax",
                 "_sample", "_rng")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._sample: List[float] = []
        # Seeded per histogram: the same value stream always yields the
        # same quantile estimates (reproducible manifests).
        self._rng = random.Random(0x5EED)

    def observe(self, value: float) -> None:
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.total += value
        self.n += 1
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if len(self._sample) < _QUANTILE_SAMPLE_CAP:
            self._sample.append(value)
        else:
            j = self._rng.randrange(self.n)
            if j < _QUANTILE_SAMPLE_CAP:
                self._sample[j] = value

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile over the (reservoir) sample; exact while
        fewer than ``_QUANTILE_SAMPLE_CAP`` values have been observed."""
        if not self._sample:
            return None
        ordered = sorted(self._sample)
        rank = max(0, math.ceil(q * len(ordered)) - 1)
        return ordered[min(rank, len(ordered) - 1)]

    def quantiles(self) -> Dict[str, Optional[float]]:
        ordered = sorted(self._sample)
        out: Dict[str, Optional[float]] = {}
        for name, q in QUANTILES:
            if not ordered:
                out[name] = None
                continue
            rank = max(0, math.ceil(q * len(ordered)) - 1)
            out[name] = ordered[min(rank, len(ordered) - 1)]
        return out

    def as_dict(self) -> Dict[str, Any]:
        out = {
            "buckets_le": list(self.buckets) + ["inf"],
            "counts": list(self.counts),
            "count": self.n,
            "sum_s": round(self.total, 9),
        }
        if self.n:
            out["min_s"] = round(self.vmin, 9)
            out["max_s"] = round(self.vmax, 9)
            out["avg_s"] = round(self.total / self.n, 9)
            for name, value in self.quantiles().items():
                out[f"{name}_s"] = (
                    None if value is None else round(value, 9)
                )
        return out


class Telemetry:
    """Thread-safe span/counter registry with an optional JSONL sink."""

    def __init__(self, enabled: bool = True) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.enabled = enabled
        self.directory: Optional[str] = None  # explicit --telemetry-dir
        # Event taps (observability flight recorder): process-lifetime
        # observers, deliberately OUTSIDE _reset_run_state so a recorder
        # installed once keeps seeing events across runs/configure().
        self._taps: List = []
        self._reset_run_state()

    # ---------------------------------------------------------- run state

    def _reset_run_state(self) -> None:
        self._ids = itertools.count(1)
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, Any] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.spans: List[Span] = []
        self.span_aggregates: Dict[str, List[float]] = {}  # name -> [n, total, max]
        self.context: Dict[str, Any] = {}  # annotate() → manifest fields
        self.jax_events: Dict[str, List[float]] = {}  # key -> [n, total_s]
        self.pipelines: Dict[str, Any] = {}  # record_pipeline() → manifest
        self.events = 0
        self._sink = None
        self._sink_path: Optional[str] = None
        self._run_depth = 0
        self._run_started_mono: Optional[float] = None

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # --------------------------------------------------------------- sink

    def open_sink(self, directory: str) -> None:
        """Open (or keep) the append-only JSONL log in ``directory``."""
        with self._lock:
            if self._sink is not None:
                return
            os.makedirs(directory, exist_ok=True)
            self._sink_path = os.path.join(directory, "telemetry.jsonl")
            self._sink = open(self._sink_path, "a", encoding="utf-8")

    def close_sink(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    @property
    def sink_path(self) -> Optional[str]:
        return self._sink_path

    def add_tap(self, fn) -> None:
        """Register a process-lifetime event observer (called with every
        emitted event dict).  Survives run resets and ``configure()`` —
        the flight recorder's ring must keep filling across runs."""
        with self._lock:
            if fn not in self._taps:
                self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    def _emit(self, event: Dict[str, Any]) -> None:
        """Count the event and append it to the JSONL sink if one is open.

        Callers hold no lock; this takes it once per event.
        """
        with self._lock:
            self.events += 1
            if self._sink is not None:
                self._sink.write(json.dumps(event, default=str) + "\n")
                self._sink.flush()
            taps = list(self._taps) if self._taps else None
        if taps:
            # Outside the lock: a tap may itself emit (re-entrancy) and
            # must never be able to wedge the registry.
            for tap in taps:
                try:
                    tap(event)
                except Exception:
                    pass

    # -------------------------------------------------------------- spans

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Any]:
        """Hierarchical timed region; nests via a thread-local stack."""
        if not self.enabled:
            yield _NULL_SPAN
            return
        stack = self._stack()
        with self._lock:
            span_id = next(self._ids)
        sp = Span(
            name,
            span_id,
            stack[-1].span_id if stack else None,
            threading.current_thread().name,
            time.time(),
            time.monotonic(),
        )
        sp.attrs.update(attrs)
        stack.append(sp)
        start = time.perf_counter()
        try:
            yield sp
        finally:
            sp.duration_s = time.perf_counter() - start
            stack.pop()
            self._record_span(sp)

    def record_span(self, name: str, duration_s: float, **attrs: Any) -> None:
        """Record an already-measured region (hot loops, worker threads)."""
        if not self.enabled:
            return
        stack = self._stack()
        with self._lock:
            span_id = next(self._ids)
        sp = Span(
            name,
            span_id,
            stack[-1].span_id if stack else None,
            threading.current_thread().name,
            time.time() - duration_s,
            time.monotonic() - duration_s,
        )
        sp.duration_s = duration_s
        sp.attrs.update(attrs)
        self._record_span(sp)

    def _record_span(self, sp: Span) -> None:
        with self._lock:
            agg = self.span_aggregates.setdefault(sp.name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += sp.duration_s
            agg[2] = max(agg[2], sp.duration_s)
            if len(self.spans) < _MAX_RAW_SPANS:
                self.spans.append(sp)
        self._emit(sp.as_event())

    # ----------------------------------------------- counters/gauges/hist

    def count(self, name: str, n: int = 1) -> None:
        """Bump a monotonic counter.  Totals land in the manifest and the
        run-end ``counters`` event — per-increment events would swamp the
        log on million-row runs."""
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        if not self.enabled:
            return
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram(buckets)
            hist.observe(value)

    def record_jax_event(self, key: str, duration_s: float = 0.0) -> None:
        """Aggregate a ``jax.monitoring`` event (compile timings etc.)."""
        if not self.enabled:
            return
        with self._lock:
            agg = self.jax_events.setdefault(key, [0, 0.0])
            agg[0] += 1
            agg[1] += duration_s

    def event(self, name: str, **attrs: Any) -> None:
        """A discrete point-in-time event (run_start, retry, …)."""
        if not self.enabled:
            return
        payload: Dict[str, Any] = {
            "type": "event",
            "name": name,
            "t_wall": round(time.time(), 6),
            "t_mono": round(time.monotonic(), 6),
        }
        if attrs:
            payload["attrs"] = attrs
        self._emit(payload)

    def record_pipeline(self, name: str, summary: Dict[str, Any]) -> None:
        """Store a prefetch pipeline's end-of-run stats (depth, per-stage
        stall/backpressure seconds, queue-depth high-water marks) under its
        pipeline name — the run manifest's ``pipeline`` section.  A name
        reused within one run (e.g. a sweep looping an engine) keeps the
        latest stats; the per-pipeline gauges/spans retain the history.
        """
        if not self.enabled:
            return
        with self._lock:
            self.pipelines[name] = summary

    def pipeline_summary(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.pipelines)

    def annotate(self, **context: Any) -> None:
        """Attach run-level context (mesh shape, backend name, …) that the
        manifest should carry verbatim."""
        if not self.enabled:
            return
        with self._lock:
            self.context.update(context)

    # ----------------------------------------------------------- readouts

    def compile_stats(self) -> Dict[str, Any]:
        """XLA compile count/seconds harvested from ``jax.monitoring``.

        ``backend_compile`` is THE compile event (one per XLA compilation,
        ``jax/_src/dispatch.py:BACKEND_COMPILE_EVENT``); the sibling
        trace/lowering durations stay visible in ``jax_events`` but would
        triple-count here.
        """
        with self._lock:
            compiles = [0, 0.0]
            for key, (n, total) in self.jax_events.items():
                if "backend_compile" in key:
                    compiles[0] += n
                    compiles[1] += total
            return {"count": compiles[0], "seconds": round(compiles[1], 6)}

    def top_spans(self, n: int = 3) -> List[Dict[str, Any]]:
        with self._lock:
            ranked = sorted(
                self.span_aggregates.items(), key=lambda kv: -kv[1][1]
            )[:n]
        return [
            {
                "name": name,
                "count": int(count),
                "total_s": round(total, 6),
                "max_s": round(peak, 6),
            }
            for name, (count, total, peak) in ranked
        ]

    def summary(self, top: int = 3) -> Dict[str, Any]:
        """Compact JSON-able digest (bench.py's ``telemetry`` sub-object).

        The ``pipeline`` key appears only when a prefetch pipeline ran —
        runs without one keep the original three-key shape
        (tests/test_telemetry_contract.py pins it).
        """
        out = {
            "events": self.events,
            "top_spans": self.top_spans(top),
            "compile": self.compile_stats(),
        }
        pipelines = self.pipeline_summary()
        if pipelines:
            out["pipeline"] = pipelines
        return out

    # ---------------------------------------------------------- run scope

    @contextmanager
    def run_scope(
        self,
        engine: str,
        output_dir: Optional[str] = None,
        argv: Optional[List[str]] = None,
    ) -> Iterator[None]:
        """One engine run: the outermost scope owns the sinks.

        The owner resets per-run state, opens the JSONL sink (explicit
        ``--telemetry-dir`` wins over the engine's ``output_dir``), emits
        ``run_start``/``run_end`` events, and writes the run manifest on
        exit.  Nested scopes (the joint pipeline calling the wordcount and
        sentiment engines, the sweep looping over analyses) degrade to a
        plain ``engine:<name>`` span under the owner.
        """
        if not self.enabled:
            yield
            return
        with self._lock:
            self._run_depth += 1
            owner = self._run_depth == 1
        directory = None
        if owner:
            self._reset_run_state()
            self._run_depth = 1  # _reset_run_state cleared it
            self._run_started_mono = time.monotonic()
            directory = self.directory or output_dir
            if directory:
                self.open_sink(directory)
            import sys

            self.annotate(engine=engine)
            self.event(
                "run_start", engine=engine,
                argv=list(argv) if argv is not None else list(sys.argv[1:]),
            )
            if "jax" in sys.modules:
                from music_analyst_tpu.telemetry.introspect import (
                    install_jax_listeners,
                )

                install_jax_listeners()
        try:
            with self.span(f"engine:{engine}"):
                yield
        finally:
            if owner:
                wall = time.monotonic() - (self._run_started_mono or 0.0)
                # Per-stage collective table: one digestible event next to
                # the per-call ``collective`` stream (and reset, so the
                # next run starts clean).  Lazy import — collectives is
                # jax-free but telemetry must not hard-require profiling.
                try:
                    from music_analyst_tpu.profiling.collectives import (
                        emit_stage_table,
                    )

                    emit_stage_table()
                except Exception:
                    pass
                with self._lock:
                    counters = dict(self.counters)
                    gauges = dict(self.gauges)
                self.event("run_end", engine=engine, counters=counters,
                           gauges=gauges)
                if directory:
                    from music_analyst_tpu.telemetry.introspect import (
                        write_run_manifest,
                    )

                    write_run_manifest(self, directory, wall_seconds=wall)
                self.close_sink()
            with self._lock:
                self._run_depth = max(0, self._run_depth - 1)


# ------------------------------------------------------- process registry

_TELEMETRY = Telemetry(enabled=True)


def get_telemetry() -> Telemetry:
    """The process-wide registry (always callable; may be disabled)."""
    return _TELEMETRY


def configure(
    enabled: bool = True, directory: Optional[str] = None
) -> Telemetry:
    """(Re)configure the process-wide registry — the CLI's entry point.

    ``directory`` pins the sink location for the whole run (the
    ``--telemetry-dir`` flag); ``None`` lets each run scope default to the
    engine's output directory.
    """
    tel = _TELEMETRY
    tel.close_sink()
    tel.enabled = enabled
    tel.directory = directory
    tel._reset_run_state()
    return tel
