"""Device/compile introspection + the run-manifest sink.

Everything here may import jax — it runs at run-scope exit or inside
``bench.py``'s measurement child, never at package import time (the test
harness must force ``JAX_PLATFORMS=cpu`` before the first jax import,
``tests/conftest.py``).

Compile visibility comes from ``jax.monitoring``: jax times every trace /
MLIR-lowering / backend-compile under ``/jax/core/compile/*_duration``
events (``jax/_src/dispatch.py``), and the persistent-compilation-cache
hit/miss counters ride the same bus.  One listener pair routes them into
the process registry; the manifest then reports XLA compile count/seconds
per run without wrapping any jax API.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional

from music_analyst_tpu.telemetry.core import Telemetry, get_telemetry

_LISTENERS_INSTALLED = False
_GIT_DESCRIBE: Optional[str] = None
_GIT_PROBED = False


def install_jax_listeners() -> bool:
    """Route ``jax.monitoring`` events into the process registry.

    Idempotent; jax offers no per-listener deregistration, so the
    callbacks stay for the process lifetime and route to whatever the
    registry's current run is (disabled registries drop them).
    """
    global _LISTENERS_INSTALLED
    if _LISTENERS_INSTALLED:
        return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax always present in-repo
        return False

    def _on_event(event: str, **kwargs: Any) -> None:
        get_telemetry().record_jax_event(event)

    def _on_duration(event: str, duration: float, **kwargs: Any) -> None:
        get_telemetry().record_jax_event(event, duration)

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    _LISTENERS_INSTALLED = True
    return True


def git_describe() -> Optional[str]:
    """``git describe --always --dirty`` of the repo, cached per process."""
    global _GIT_DESCRIBE, _GIT_PROBED
    if _GIT_PROBED:
        return _GIT_DESCRIBE
    _GIT_PROBED = True
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty"],
            cwd=repo_root, capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            _GIT_DESCRIBE = out.stdout.strip() or None
    except Exception:
        _GIT_DESCRIBE = None
    return _GIT_DESCRIBE


def peak_rss_bytes() -> Optional[int]:
    try:
        import resource

        # Linux reports ru_maxrss in KiB.
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # pragma: no cover - non-POSIX
        return None


def collect_device_info() -> Dict[str, Any]:
    """Platform, device count, and per-device ``memory_stats()`` where the
    plugin exposes them (TPU does; CPU-emulated meshes return None)."""
    import jax

    devices = jax.devices()
    per_device: List[Optional[Dict[str, Any]]] = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        per_device.append(stats)
    return {
        "platform": devices[0].platform if devices else "unknown",
        "count": len(devices),
        "kinds": sorted({d.device_kind for d in devices}),
        "memory_stats": per_device,
    }


def write_run_manifest(
    tel: Telemetry, directory: str, wall_seconds: float = 0.0
) -> str:
    """Write ``<directory>/run_manifest.json`` from the registry's state.

    The manifest is the one-glance answer to "what ran, where, and what
    did it cost": CLI argv, device platform/count/memory, mesh shape (when
    an engine annotated one), jax/jaxlib versions, git describe, peak RSS,
    XLA compile count/seconds, and the final counter/gauge/histogram/span
    aggregates.
    """
    import jax
    import jaxlib

    install_jax_listeners()
    with tel._lock:
        context = dict(tel.context)
        counters = dict(tel.counters)
        gauges = dict(tel.gauges)
        histograms = {k: h.as_dict() for k, h in tel.histograms.items()}
        jax_events = {
            k: {"count": int(n), "seconds": round(t, 6)}
            for k, (n, t) in sorted(tel.jax_events.items())
        }
        events = tel.events
        pipelines = dict(tel.pipelines)
    manifest: Dict[str, Any] = {
        "schema": 1,
        "engine": context.pop("engine", None),
        "argv": list(sys.argv[1:]),
        "wall_seconds": round(wall_seconds, 6),
        "python_version": sys.version.split()[0],
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "git_describe": git_describe(),
        "device": collect_device_info(),
        "peak_rss_bytes": peak_rss_bytes(),
        "compile": tel.compile_stats(),
        "jax_events": jax_events,
        "context": context,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "spans": tel.top_spans(n=20),
        "pipeline": pipelines,
        "event_count": events,
        "telemetry_log": tel.sink_path,
    }
    # Failover degradation is a headline fact about the run — hoist it
    # out of the annotation context so readers (and telemetry-report)
    # never dig for it.  Only present when a failover actually degraded,
    # so healthy runs keep the original key set.
    if context.get("degraded"):
        manifest["degraded"] = True
        for key in ("degraded_site", "degraded_reason"):
            if key in context:
                manifest[key] = context[key]
    # An unclean previous shutdown (journal without its clean marker, or
    # a stale non-drain flight record) is the same class of headline
    # fact: hoisted so telemetry-report and operators see it at a glance,
    # absent on runs that started clean.
    if context.get("unclean_shutdown"):
        manifest["unclean_shutdown"] = True
        if "unclean_witness" in context:
            manifest["unclean_witness"] = context["unclean_witness"]
    try:
        # Fault-injection + retry digest (resilience/): per-site trips and
        # per-site retry/recovery counts — only when something tripped or
        # retried, so fault-free runs keep the original key set.
        from music_analyst_tpu.resilience import fault_stats, retry_stats

        faults = fault_stats()
        # attempts bumps on every guarded call; a site earns a manifest
        # row only once it actually retried / recovered / gave up.
        retries = {
            site: counts
            for site, counts in retry_stats().items()
            if counts.get("retries") or counts.get("gave_up")
        }
        if faults or retries:
            resilience: Dict[str, Any] = {}
            if faults:
                resilience["faults"] = faults
            if retries:
                resilience["retries"] = retries
            manifest["resilience"] = resilience
    except Exception:
        pass
    try:
        # Persistent-corpus-cache hit/miss/bytes-saved — process-lifetime,
        # like the XLA cache stats; only present once the cache has been
        # consulted, so cache-free runs keep the original key set.
        from music_analyst_tpu.data.corpus_cache import cache_stats

        corpus_stats = cache_stats()
        if any(corpus_stats.values()):
            manifest["corpus_cache"] = corpus_stats
    except Exception:
        pass
    try:
        # Quantized-checkpoint cache hit/miss/stores/bytes-saved plus the
        # most recent streaming load's peak-host-staging digest — same
        # only-when-consulted posture as corpus_cache above.
        from music_analyst_tpu.engines.checkpoint import last_load_stats
        from music_analyst_tpu.engines.wq_cache import (
            cache_stats as wq_stats,
        )

        stats = wq_stats()
        load = last_load_stats()
        if any(stats.values()) or load:
            manifest["wq_cache"] = dict(stats)
            if load:
                manifest["wq_cache"]["last_load"] = load
    except Exception:
        pass
    try:
        # Process-lifetime compile records (memoized engine callables
        # outlive a single run) — guarded so a jax-free manifest path or
        # a partial install never blocks the write.
        from music_analyst_tpu.profiling.compile import compile_records

        manifest["profiling"] = {
            "scope": "process",
            "compiles": compile_records(),
        }
    except Exception:
        pass
    try:
        # Serving-layer snapshot (protocol, admission counters, batch
        # occupancy, latency quantiles, residency/warmup state) — present
        # only when a server ran in this process, so batch runs keep the
        # original key set.
        from music_analyst_tpu.serving.server import serving_stats

        serving = serving_stats()
        if serving:
            manifest["serving"] = serving
    except Exception:
        pass
    try:
        # Request-trace recorder digest + tail exemplars: quantile trace
        # ids a reader can resolve against request_traces.jsonl — only
        # when tracing was enabled, so untraced runs keep the key set.
        from music_analyst_tpu.telemetry.reqtrace import get_reqtrace

        rt = get_reqtrace()
        if rt.enabled:
            manifest["reqtrace"] = rt.stats()
            exemplars = rt.exemplars()
            if exemplars:
                manifest["trace_exemplars"] = exemplars
    except Exception:
        pass
    try:
        # Metrics plane digest (observability/metrics_plane.py): series
        # counters, active burn-rate alerts, fleet merge — only when
        # sampling was on, so unmetered runs keep the key set.
        from music_analyst_tpu.observability.metrics_plane import (
            get_metrics_plane,
        )

        plane = get_metrics_plane()
        if plane.enabled:
            manifest["metrics"] = plane.snapshot()
    except Exception:
        pass
    try:
        # Watchdog verdicts + flight-record pointer — only when there is
        # something to say, so unwatched runs keep the original key set.
        from music_analyst_tpu.observability.flight import get_flight_recorder
        from music_analyst_tpu.observability.watchdog import get_watchdog

        obs: Dict[str, Any] = {}
        wd = get_watchdog()
        if wd is not None:
            obs["watchdog"] = wd.snapshot()
        rec = get_flight_recorder()
        if rec.last_dump_path:
            obs["flight_record"] = rec.last_dump_path
        if obs:
            manifest["observability"] = obs
    except Exception:
        pass
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "run_manifest.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, default=str)
        fh.write("\n")
    return path
