"""Unified run telemetry: spans, counters, JSONL event log, run manifest.

Usage (every engine follows this shape):

    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    with tel.run_scope("wordcount", output_dir):      # owns the sinks
        with tel.span("ingest") as sp:
            ...
            sp.set(bytes=n_bytes)
        tel.count("songs_ingested", n)

Artifacts (when a sink directory resolves — ``--telemetry-dir`` or the
engine's output dir): ``telemetry.jsonl`` (append-only, one event per
line) and ``run_manifest.json`` (device/compile/version/counter digest).
Schemas are documented in PERFORMANCE.md §"How to read a run".
"""

from music_analyst_tpu.telemetry.core import (
    DEFAULT_BUCKETS,
    Histogram,
    Span,
    Telemetry,
    configure,
    get_telemetry,
)
from music_analyst_tpu.telemetry.introspect import (
    collect_device_info,
    git_describe,
    install_jax_listeners,
    write_run_manifest,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "Histogram",
    "Span",
    "Telemetry",
    "configure",
    "get_telemetry",
    "collect_device_info",
    "git_describe",
    "install_jax_listeners",
    "write_run_manifest",
]
