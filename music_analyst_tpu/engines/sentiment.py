"""Batched sentiment pipeline (``sentiment_classifier.py`` parity).

Where the reference classifies one song per blocking HTTP round-trip
(``scripts/sentiment_classifier.py:144-154``), this engine batches songs and
dispatches whole batches to an on-device classifier backend:

* ``mock``   — the vectorized keyword kernel (``ops/keyword_sentiment.py``);
* ``distilbert`` — encoder classifier (``models/distilbert.py``);
* ``llama``  — zero-shot decoder LM (``models/llama.py``).

Outputs are byte-for-byte the reference artifact formats:
``sentiment_totals.json`` (label→count, 2-space JSON) and
``sentiment_details.csv`` (``artist,song,label,latency_seconds`` with
4-decimal latency) — ``scripts/sentiment_classifier.py:156-164``.
"""

from __future__ import annotations

import contextlib
import csv
import dataclasses
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from music_analyst_tpu.data.csv_io import iter_songs
from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.runtime import (
    PrefetchPipeline,
    Stage,
    resolve_prefetch_depth,
)
from music_analyst_tpu.resilience.failover import run_with_failover
from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.telemetry import get_telemetry
from music_analyst_tpu.utils.atomic import atomic_write
from music_analyst_tpu.utils.labels import SUPPORTED_LABELS


@dataclasses.dataclass
class SentimentRow:
    artist: str
    song: str
    label: str
    latency_seconds: float


@dataclasses.dataclass
class SentimentResult:
    counts: Dict[str, int]
    rows: List[SentimentRow]
    output_paths: Dict[str, str]
    songs_per_second: float


class ClassifierBackend:
    """Interface all sentiment backends implement."""

    name = "base"
    # Whether per-song latency is meaningful for this backend.  The
    # reference's mock path always records 0.0 (scripts/
    # sentiment_classifier.py:83) — mock sets this False to keep
    # sentiment_details.csv byte-identical; device model backends report
    # amortized batch latency instead of the reference's per-song HTTP time.
    reports_latency = True

    def classify_batch(self, texts: Sequence[str]) -> List[str]:
        """Labels for a batch of raw lyric strings."""
        raise NotImplementedError

    # Staged hooks for the host↔device prefetch pipeline
    # (music_analyst_tpu/runtime/prefetch.py).  The engine runs
    # ``prepare`` (host tokenize + batch planning), ``transfer``
    # (``jax.device_put`` of the wire payload), and ``launch`` (dispatch
    # the jitted forwards without blocking) in separate pipeline stages,
    # then blocks on ``collect`` in the consumer — so batch i+2 tokenizes
    # and batch i+1 transfers while batch i runs on the chips.  The
    # defaults collapse the three stages into ``submit``, so a backend
    # that only implements submit/collect (or just classify_batch) works
    # unchanged — the pipeline simply gets no tokenize/transfer overlap
    # from it.
    def prepare(self, texts: Sequence[str]):
        """Host-only work: tokenize + plan the batch.  Must not touch the
        device."""
        return texts

    def transfer(self, prepared):
        """Ship the prepared payload host→device (``jax.device_put``)."""
        return prepared

    def launch(self, transferred):
        """Dispatch device work for a transferred payload; returns the
        handle ``collect`` blocks on."""
        return self.submit(transferred)

    # Async pair kept as the single-call surface: ``submit`` does the host
    # work and dispatches device work without blocking; ``collect`` blocks
    # on the result.  Backends that implement the staged hooks above
    # compose them here so direct submit/collect callers see one behavior.
    def submit(self, texts: Sequence[str]):
        return self.classify_batch(texts)

    def collect(self, handle) -> List[str]:
        return handle


def _has_buckets(length_buckets) -> bool:
    """Whether a ``length_buckets`` value actually requests bucketing.

    ``None`` and an empty sequence both mean "unset"; `len(...)` (not
    truthiness) so numpy arrays work as sequences; strings ("auto" or a
    mistaken "32,64") count as set and defer to the classifier's own
    validation for a clear message.  Shared by ``get_backend`` and
    ``run_sentiment``'s injected-backend guard so the two entry points
    agree on what "unset" means (r4 advisor finding).
    """
    if length_buckets is None:
        return False
    if isinstance(length_buckets, str):
        return True
    try:
        return len(length_buckets) > 0
    except TypeError:
        # A scalar (length_buckets=32) is a plausible slip for a
        # one-bucket list; name the misuse instead of letting a bare
        # `len(int)` TypeError surface from deep inside either caller.
        raise TypeError(
            "length_buckets must be a string ('auto') or a sequence of "
            f"ints, got {type(length_buckets).__name__}"
        ) from None


def get_backend(
    model: str,
    mock: bool = False,
    mesh=None,
    length_buckets: Optional[Sequence[int]] = None,
    weight_quant: Optional[str] = None,
    **kwargs,
) -> ClassifierBackend:
    """Resolve the ``--model``/``--mock`` flag surface to a backend.

    Mirrors the reference's dispatch (``--mock`` wins over ``--model``,
    ``scripts/sentiment_classifier.py:140``); model names map to on-device
    families instead of Ollama model tags.

    The dispatch also owns per-family capabilities, so callers pass
    ``mesh``/``length_buckets`` unconditionally: ``mesh`` shards model
    batches over dp and places params per the TP rules but is dropped for
    the mesh-incapable families (the keyword kernel, the Ollama HTTP
    passthrough); ``length_buckets`` is encoder-only and *raises* elsewhere
    (silently running every row at full length would defeat the flag).
    """
    has_buckets = _has_buckets(length_buckets)
    if has_buckets and (mock or not model.startswith("distilbert")):
        raise ValueError(
            "length_buckets is an encoder-classifier option; "
            f"model {model!r} does not support it"
        )
    has_wq = weight_quant not in (None, "none")
    if has_wq and (
        mock or not (model.startswith("distilbert")
                     or model.startswith("llama"))
    ):
        # Same posture as length_buckets: silently running float would
        # defeat the flag.
        raise ValueError(
            "weight_quant is an on-device model option; "
            f"model {model!r} does not support it"
        )
    if mock or model == "mock":
        from music_analyst_tpu.models.mock import MockKeywordClassifier

        return MockKeywordClassifier(**kwargs)
    if model.startswith("ollama:") or model == "ollama":
        from music_analyst_tpu.models.ollama import OllamaClassifier

        tag = model.split(":", 1)[1] if ":" in model else "llama3"
        return OllamaClassifier(model=tag, **kwargs)
    if mesh is not None:
        kwargs["mesh"] = mesh
    if has_wq:
        kwargs["weight_quant"] = weight_quant
    try:
        if model.startswith("distilbert"):
            from music_analyst_tpu.models.distilbert import DistilBertClassifier

            if has_buckets:
                # Strings pass through (the classifier validates "auto" vs
                # mistakes); a sequence is normalized to a tuple.
                kwargs["length_buckets"] = (
                    length_buckets if isinstance(length_buckets, str)
                    else tuple(int(b) for b in length_buckets)
                )
            return DistilBertClassifier.from_pretrained_or_random(model, **kwargs)
        if model.startswith("llama"):
            from music_analyst_tpu.models.llama import LlamaZeroShotClassifier

            return LlamaZeroShotClassifier.from_pretrained_or_random(
                model, **kwargs
            )
    except ImportError as exc:
        raise RuntimeError(
            f"model backend {model!r} is unavailable ({exc}); "
            "use --mock or --model mock for the keyword kernel"
        ) from exc
    raise ValueError(
        f"unknown model {model!r}: expected 'mock', 'distilbert*' or 'llama*'"
    )


def _read_completed_details(details_path: str) -> Tuple[int, Dict[str, int]]:
    """Rows already classified in a previous (partial) run + their counts.

    A kill can land mid-write, leaving a torn final row (the writer flushes
    per batch, but the OS doesn't promise line atomicity).  Truncate the
    file to the last newline at even quote parity — a newline inside an
    open quoted field (multi-line artist/song) is row *content*, not a row
    end — so the torn row is re-classified instead of being counted done
    and appended onto.
    """
    with open(details_path, "rb+") as raw:
        # One forward streaming pass in bounded chunks: a newline is a row
        # boundary iff the quote count of the prefix ending there is even
        # ('""' escapes contribute two quotes, preserving parity; a newline
        # inside an open quoted field is row content).  Track the last such
        # boundary — everything after it is the torn row.  No copy of a
        # multi-GB details file is ever materialized.
        keep = 0
        quotes = 0
        size = 0
        while chunk := raw.read(1 << 22):
            start = 0
            while (nl := chunk.find(b"\n", start)) >= 0:
                quotes += chunk.count(b'"', start, nl)
                if quotes % 2 == 0:
                    keep = size + nl + 1
                start = nl + 1
            quotes += chunk.count(b'"', start)
            size += len(chunk)
        if keep != size:
            raw.truncate(keep)
    done = 0
    counts: Dict[str, int] = {label: 0 for label in SUPPORTED_LABELS}
    with open(details_path, newline="", encoding="utf-8") as fh:
        for row in csv.DictReader(fh):
            label = row.get("label", "")
            if label in counts:
                counts[label] += 1
            done += 1
    return done, counts


def _mesh_capable(model: str, mock: bool) -> bool:
    """Whether the resolved backend family takes a device mesh (the
    on-device model families do; the keyword kernel and the Ollama HTTP
    passthrough do not).  Callers that just want a backend should pass
    ``mesh=`` to :func:`get_backend`, which drops it where inapplicable;
    this predicate exists for callers deciding whether to *build* a mesh
    at all (mesh construction initializes the device backend)."""
    return not mock and (
        model.startswith("distilbert") or model.startswith("llama")
    )


def run_sentiment(
    dataset_path: str,
    model: str = "mock",
    mock: bool = False,
    limit: Optional[int] = None,
    output_dir: str = "output",
    batch_size: int = 4096,
    backend: Optional[ClassifierBackend] = None,
    quiet: bool = False,
    resume: bool = False,
    songs: Optional[Iterable[Tuple[str, str, str]]] = None,
    mesh=None,
    length_buckets: Optional[Sequence[int]] = None,
    prefetch_depth: Optional[int] = None,
    weight_quant: Optional[str] = None,
) -> SentimentResult:
    """Classify the dataset and write the reference output artifacts.

    Rows stream into ``sentiment_details.csv`` as each batch completes, so a
    killed run leaves a valid prefix on disk; ``resume=True`` picks up from
    it (skipping already-classified rows and seeding the totals).  The
    reference has no recovery at all — every failure recomputes from the CSV
    (SURVEY.md §5 "Checkpoint/resume: none").

    ``songs`` overrides the dataset read with an already-parsed iterable of
    ``(artist, song, text)`` rows — the fused joint pipeline passes the
    records its single ingest captured, so the file is opened once per run
    (``limit`` is ignored then; the producer already applied it).

    ``prefetch_depth`` bounds how many batches ride ahead of the device in
    the tokenize→transfer pipeline (``--prefetch-depth``; default 2 via
    ``$MUSICAAL_PREFETCH_DEPTH``); 0 disables overlap entirely.  Output
    artifacts are byte-identical at every depth — only wall time changes.
    """
    if songs is not None and resume:
        # The resume skip count indexes the DictReader row order of a prior
        # standalone run; a captured-records stream uses the exact parser,
        # which counts malformed rows differently — mixing the two would
        # silently misattribute rows.  Checked before any output file is
        # touched.
        raise ValueError("resume=True cannot be combined with songs=")
    tel = get_telemetry()
    with tel.run_scope("sentiment", output_dir):
        return _run_sentiment_impl(
            tel, dataset_path, model, mock, limit, output_dir, batch_size,
            backend, quiet, resume, songs, mesh, length_buckets,
            prefetch_depth, weight_quant,
        )


def _timed_source(tel, source):
    """Yield rows from ``source`` while accumulating pure read time; the
    total lands as ONE ``ingest`` span (per-row spans would swamp the log
    on million-row datasets)."""
    read_s = 0.0
    n = 0
    it = iter(source)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            break
        read_s += time.perf_counter() - t0
        n += 1
        yield item
    tel.record_span("ingest", read_s, rows=n)


def _run_sentiment_impl(
    tel, dataset_path, model, mock, limit, output_dir, batch_size,
    backend, quiet, resume, songs, mesh, length_buckets,
    prefetch_depth, weight_quant=None,
) -> SentimentResult:
    os.makedirs(output_dir, exist_ok=True)
    depth = resolve_prefetch_depth(prefetch_depth)
    if backend is not None and (
            mesh is not None or _has_buckets(length_buckets)
            or weight_quant not in (None, "none")):
        # An injected backend was constructed by the caller; silently
        # dropping construction-time options here would be a lie.
        raise ValueError(
            "mesh=/length_buckets=/weight_quant= configure backend "
            "construction and cannot be combined with an explicit "
            "backend="
        )
    # One owner for the backend lifetime, batch runs included: residency
    # enables the persistent compile cache before the first build, and
    # the device-loss recovery below reloads through the same object the
    # server's failover hook uses (serving/residency.py).
    from music_analyst_tpu.serving.residency import ModelResidency

    residency = ModelResidency(
        model=model, mock=mock, weight_quant=weight_quant, mesh=mesh,
        backend=backend, length_buckets=length_buckets,
    )
    with tel.span("backend_init", model=model, mock=bool(mock)):
        clf = residency.acquire()
    tel.annotate(backend=clf.name, batch_size=batch_size, prefetch_depth=depth)

    totals_path = os.path.join(output_dir, "sentiment_totals.json")
    details_path = os.path.join(output_dir, "sentiment_details.csv")

    skip = 0
    counts: Dict[str, int] = {label: 0 for label in SUPPORTED_LABELS}
    if resume and os.path.exists(details_path):
        skip, counts = _read_completed_details(details_path)

    rows: List[SentimentRow] = []  # rows classified by THIS run
    start = time.perf_counter()

    details_fh = open(
        details_path, "a" if skip else "w", newline="", encoding="utf-8"
    )
    writer = csv.DictWriter(
        details_fh, fieldnames=["artist", "song", "label", "latency_seconds"]
    )
    if not skip:
        writer.writeheader()

    def finish(rows_batch, handle, t_submit, measured) -> None:
        with tel.span("compute", rows=len(rows_batch)):
            # collect() is the device-blocking edge — over the loopback
            # tunnel it can hang without erroring; let the watchdog
            # classify that as device_stall instead of silence.  On a
            # CLASSIFIED device loss the batch is re-submitted once —
            # through a freshly-built backend when this engine owns
            # backend construction — before the failure propagates.
            state = {"handle": handle}

            def _collect():
                with watchdog.watch("sentiment.collect", kind="device"):
                    return clf.collect(state["handle"])

            def _reinit():
                nonlocal clf
                if backend is None:
                    clf = residency.reload()
                state["handle"] = clf.submit(
                    [text for _, _, text in rows_batch]
                )

            labels, _ = run_with_failover(
                _collect, site="sentiment.collect", reinit=_reinit
            )
        elapsed = time.perf_counter() - t_submit
        # Submit→collect wall time per batch — the batched analogue of the
        # reference's per-song HTTP latency column.
        tel.observe("sentiment.batch_seconds", elapsed)
        tel.count("rows_classified", len(rows_batch))
        # Per-song latency: exact when the backend measures it (Ollama
        # passthrough), amortized batch time for device backends, 0.0 for
        # mock — matching the reference's per-row semantics.
        per_song = (
            elapsed / max(1, len(rows_batch)) if clf.reports_latency else 0.0
        )
        with tel.span("write", rows=len(rows_batch)):
            for i, ((artist, song, text), label) in enumerate(
                zip(rows_batch, labels)
            ):
                if measured and len(measured) == len(rows_batch):
                    latency = measured[i]
                else:
                    latency = 0.0 if not text.strip() else per_song
                counts[label] += 1
                rows.append(SentimentRow(artist, song, label, latency))
                writer.writerow(
                    {
                        "artist": artist,
                        "song": song,
                        "label": label,
                        "latency_seconds": f"{latency:.4f}",
                    }
                )
            details_fh.flush()

    def batches(source):
        batch: List[Tuple[str, str, str]] = []
        for idx, row in enumerate(source):
            if idx < skip:
                continue
            batch.append(row)
            if len(batch) >= batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    # Duck-typed backends (test doubles, user plugins) predate the staged
    # hooks — the historical floor is submit/collect, so missing hooks
    # degrade to that exact behavior: everything happens in the launch
    # stage, with no tokenize/transfer overlap.
    clf_prepare = getattr(clf, "prepare", None) or (lambda texts: texts)
    clf_transfer = getattr(clf, "transfer", None) or (lambda prepared: prepared)
    clf_launch = getattr(clf, "launch", None) or clf.submit

    def tokenize_stage(rows_batch):
        # Host half only: tokenization + batch planning.  Device dispatch
        # happens downstream so a slow tokenizer can't serialize the chip.
        texts = [text for _, _, text in rows_batch]
        return rows_batch, clf_prepare(texts)

    def h2d_stage(item):
        rows_batch, prepared = item
        # Injected h2d.transfer faults recover via the prefetch stage
        # retry (the whole stage body re-runs; launch is idempotent).
        fault_point("h2d.transfer", rows=len(rows_batch))
        t0 = time.perf_counter()
        handle = clf_launch(clf_transfer(prepared))
        # Snapshot measured latencies NOW: synchronous backends (Ollama)
        # classify inside launch() and overwrite last_latencies on the
        # next launch, which would mis-attribute them across batches.
        measured = getattr(clf, "last_latencies", None)
        return rows_batch, handle, t0, list(measured) if measured else None

    # Replaces the old hand-rolled one-deep submit/collect overlap: up to
    # ``depth`` batches tokenize and transfer ahead of the device, each hop
    # bounded (backpressure), stalls accounted per stage (the reference is
    # strictly serial, one HTTP call per song, SURVEY.md §3.2).
    pipe = PrefetchPipeline(
        [Stage("tokenize", tokenize_stage), Stage("h2d", h2d_stage)],
        depth=depth,
        name="pipeline",
        sink_name="compute",
    )
    source = _timed_source(
        tel,
        songs if songs is not None else iter_songs(dataset_path, limit=limit),
    )
    try:
        # closing(): a collect()/write error below must cancel and join the
        # pipeline threads, not leave them prefetching into a dead run.
        with contextlib.closing(pipe.run(batches(source))) as results:
            for rows_batch, handle, t_submit, measured in results:
                finish(rows_batch, handle, t_submit, measured)
    finally:
        details_fh.close()
    wall = time.perf_counter() - start

    with atomic_write(totals_path) as fh:
        json.dump(counts, fh, indent=2)

    if not quiet:
        print("Sentiment summary:")
        for label in SUPPORTED_LABELS:
            print(f"  {label}: {counts[label]}")
        print(f"Detailed results -> {details_path}")
        print(f"Aggregated counts -> {totals_path}")

    return SentimentResult(
        counts=counts,
        rows=rows,
        output_paths={"totals": totals_path, "details": details_path},
        songs_per_second=(len(rows) / wall if wall > 0 else 0.0),
    )
