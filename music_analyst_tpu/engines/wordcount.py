"""The word/artist-count analysis engine (``bin/parallel_spotify`` parity).

Pipeline (cf. the reference call stack, SURVEY.md §3.1):

1. preprocessing — header labels + column split artifacts
   (``output/split_columns/<artist>.csv``, ``<text>.csv``), exactly like
   rank 0 of the reference (``src/parallel_spotify.c:778-828``);
2. host ingest — C++/Python tokenizer builds vocab + dense id arrays
   (replaces the per-rank byte-slice read loops, ``:918-998``);
3. device compute — id shards over the mesh ``dp`` axis, per-chip dense
   histogram, one ``psum`` (replaces hash-table Send/Recv + rank-0 merge,
   ``:1002-1065``);
4. export — count-desc/strcmp-asc sorted CSVs, console report, and
   ``performance_metrics.json`` with per-chip timings
   (``:1027-1053,1084-1109``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional, Tuple

import numpy as np

from music_analyst_tpu.data.corpus_cache import resolve_cache_dir
from music_analyst_tpu.data.csv_io import sort_count_entries, write_count_csv
from music_analyst_tpu.data.ingest import IngestResult, ingest_dataset
from music_analyst_tpu.data.splitter import (
    read_header_labels,
    sanitize_header_name,
    split_dataset_columns,
)
from music_analyst_tpu.metrics.perf import TimeStats, write_performance_metrics
from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.metrics.timer import StageTimer
from music_analyst_tpu.ops.histogram import (
    resolve_chunk_songs,
    sharded_histogram,
    sharded_histogram_hostlocal_timed,
    sharded_histogram_streaming,
)
from music_analyst_tpu.parallel.mesh import data_parallel_mesh
from music_analyst_tpu.profiling.trace import annotate
from music_analyst_tpu.resilience.failover import run_with_failover


@dataclasses.dataclass
class AnalysisResult:
    word_entries: List[Tuple[str, int]]    # sorted count-desc, tie bytewise-asc
    artist_entries: List[Tuple[str, int]]
    total_songs: int
    total_words: int
    timings: dict
    output_paths: dict
    # Measured per-chip compute seconds — identical to the metrics file's
    # per_chip column and the samples behind compute_time (ingest share +
    # the chip's own count/merge time).
    per_chip_compute: List[float] = dataclasses.field(default_factory=list)


def run_analysis(
    dataset_path: str,
    output_dir: str = "output",
    word_limit: int = 0,
    artist_limit: int = 0,
    limit: Optional[int] = None,
    mesh=None,
    write_split: bool = True,
    ingest_backend: str = "auto",
    count_mode: str = "host-shard",
    quiet: bool = False,
    corpus: Optional[IngestResult] = None,
    ingest_seconds: float = 0.0,
    corpus_cache_dir: Optional[str] = None,
    use_corpus_cache: bool = True,
    chunk_songs=None,
) -> AnalysisResult:
    """Run the full analysis and write the reference's output artifacts.

    ``corpus`` supplies an already-ingested dataset (the fused joint
    pipeline parses once and shares the result); ``ingest_seconds`` is then
    the caller's measured ingest time, folded into the timing stats exactly
    as an in-engine ingest would be.

    ``corpus_cache_dir``/``use_corpus_cache`` control the persistent
    ingest cache (``data/corpus_cache.py``); ``chunk_songs`` selects the
    chunked streaming device path (``None`` = auto by corpus size, ``0`` =
    off, ``N`` = songs per chunk).  Every combination writes byte-identical
    CSVs — they only move where time and memory are spent.
    """
    from music_analyst_tpu.telemetry import get_telemetry
    from music_analyst_tpu.utils.cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    tel = get_telemetry()
    timer = StageTimer()
    os.makedirs(output_dir, exist_ok=True)
    split_dir = os.path.join(output_dir, "split_columns")

    with tel.run_scope("wordcount", output_dir):
        return _run_analysis_instrumented(
            tel, timer, dataset_path, output_dir, split_dir, word_limit,
            artist_limit, limit, mesh, write_split, ingest_backend,
            count_mode, quiet, corpus, ingest_seconds,
            resolve_cache_dir(corpus_cache_dir, use_corpus_cache),
            chunk_songs,
        )


def _run_analysis_instrumented(
    tel, timer, dataset_path, output_dir, split_dir, word_limit,
    artist_limit, limit, mesh, write_split, ingest_backend, count_mode,
    quiet, corpus, ingest_seconds, cache_dir, chunk_songs,
) -> AnalysisResult:
    with timer.stage("split"):
        if write_split:
            artist_label, text_label = read_header_labels(dataset_path)
            split_dataset_columns(
                dataset_path,
                split_dir,
                sanitize_header_name(artist_label),
                sanitize_header_name(text_label),
                artist_label,
                text_label,
            )

    if corpus is None:
        with timer.stage("ingest"):
            corpus = ingest_dataset(
                dataset_path, limit=limit, backend=ingest_backend,
                cache_dir=cache_dir,
            )
    else:
        timer.seconds["ingest"] = ingest_seconds

    default_mesh = mesh is None
    if mesh is None:
        mesh = data_parallel_mesh()

    n_chips = mesh.devices.size
    chunk = resolve_chunk_songs(
        chunk_songs, corpus.song_count, corpus.token_count
    )
    tel.count("songs_ingested", corpus.song_count)
    tel.count("words_counted", corpus.token_count)
    tel.annotate(
        mesh_shape={
            name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        },
        count_mode=count_mode,
        chunk_songs=chunk,
    )
    def _device_counts():
        # np.asarray is the synchronization point: block_until_ready is not
        # reliable on every PJRT plugin, and the engine needs the host
        # copies anyway.  "host-shard" (default, and the faster layout on
        # every corpus measured) counts each shard where it was ingested
        # and psums dense vectors (O(vocab) transfer); "device-ids" ships
        # the id stream to HBM and scatter-adds there — the right layout
        # when the ids are already device-resident (selectable via
        # ``analyze --count-mode``).
        if chunk > 0:
            # Streaming path: the word histogram (the O(tokens) payload)
            # walks bounded chunks through the prefetch pipeline — its
            # chips are lock-stepped, so its wall-clock is every shard's
            # share.  The artist histogram is O(songs), far too small for
            # chunking to pay, and staying host-local keeps the measured
            # per-shard timing spread.
            with annotate("wordcount.word_histogram"):
                t0 = time.perf_counter()
                word_counts = sharded_histogram_streaming(
                    corpus.word_ids, corpus.word_offsets,
                    max(1, len(corpus.word_vocab)), mesh,
                    chunk_songs=chunk,
                )
                word_wall = time.perf_counter() - t0
            with annotate("wordcount.artist_histogram"):
                artist_counts, artist_times = (
                    sharded_histogram_hostlocal_timed(
                        corpus.artist_ids, max(1, len(corpus.artist_vocab)),
                        mesh,
                    )
                )
            per_shard = [
                word_wall + a for a in artist_times.per_chip_seconds()
            ]
            dp_coord = np.indices(mesh.devices.shape)[
                mesh.axis_names.index("dp")
            ].flatten()
            per_chip_compute = [per_shard[c] for c in dp_coord]
        elif count_mode == "host-shard":
            with annotate("wordcount.word_histogram"):
                word_counts, word_times = sharded_histogram_hostlocal_timed(
                    corpus.word_ids, max(1, len(corpus.word_vocab)), mesh
                )
            with annotate("wordcount.artist_histogram"):
                artist_counts, artist_times = (
                    sharded_histogram_hostlocal_timed(
                        corpus.artist_ids, max(1, len(corpus.artist_vocab)),
                        mesh,
                    )
                )
            # Shard i's measured compute: its own count phases plus the
            # lock-stepped collective merges every chip sits in together.
            per_shard = [
                w + a
                for w, a in zip(
                    word_times.per_chip_seconds(),
                    artist_times.per_chip_seconds(),
                )
            ]
            # One timing per dp shard; on a multi-axis mesh every device in
            # a dp row shares its shard's time (the non-dp axes replicate
            # the histogram work).  Map by each device's dp coordinate so
            # per_chip always has exactly one entry per device.
            dp_coord = np.indices(mesh.devices.shape)[
                mesh.axis_names.index("dp")
            ].flatten()
            per_chip_compute = [per_shard[c] for c in dp_coord]
        else:
            with annotate("wordcount.word_histogram"):
                word_counts = np.asarray(
                    sharded_histogram(
                        corpus.word_ids, max(1, len(corpus.word_vocab)), mesh
                    )
                )
            with annotate("wordcount.artist_histogram"):
                artist_counts = np.asarray(
                    sharded_histogram(
                        corpus.artist_ids, max(1, len(corpus.artist_vocab)),
                        mesh,
                    )
                )
            # One fused SPMD program: chips are lock-stepped, so each
            # chip's compute IS the program wall-clock (documented
            # TimeStats.uniform semantics).
            per_chip_compute = None
        return word_counts, artist_counts, per_chip_compute

    def _host_counts():
        # Degraded CPU path: the device layouts and this bincount compute
        # the SAME dense histograms, so the exported CSVs stay
        # byte-identical (golden contract) — only the per-chip timing
        # story is lost (uniform wall-clock, like the fused layout).
        word_ids = np.asarray(corpus.word_ids)
        artist_ids = np.asarray(corpus.artist_ids)
        word = np.bincount(
            word_ids[word_ids >= 0], minlength=max(1, len(corpus.word_vocab))
        )
        artist = np.bincount(
            artist_ids[artist_ids >= 0],
            minlength=max(1, len(corpus.artist_vocab)),
        )
        return word, artist, None

    def _reinit_mesh():
        # A fresh Mesh re-keys the cached psum programs, forcing a clean
        # lower+compile against the (possibly recovered) backend.  A
        # caller-supplied mesh is left alone — replacing it behind the
        # caller's back could change axis names mid-run.
        nonlocal mesh
        if default_mesh:
            mesh = data_parallel_mesh()

    with timer.stage("device_compute"), watchdog.watch(
        "wordcount.device_compute", kind="device"
    ):
        # Classified backend loss (tunnel_dead / device_stall / injected
        # transient) gets one re-init-and-retry, then degrades to the
        # host bincount path with a `degraded: true` manifest stamp.
        (word_counts, artist_counts, per_chip_compute), _ = run_with_failover(
            _device_counts,
            site="wordcount.device_compute",
            reinit=_reinit_mesh,
            degrade=_host_counts,
        )
    if per_chip_compute is None:
        per_chip_compute = [timer.seconds["device_compute"]] * n_chips
    # Grand totals are already global on the host (the reference needs an
    # MPI_Reduce only because each rank holds a partial count).
    total_words = corpus.token_count
    total_songs = corpus.song_count

    with timer.stage("aggregate_export"):
        word_entries = sort_count_entries(
            corpus.word_vocab.counts_to_entries(word_counts)
        )
        artist_entries = sort_count_entries(
            corpus.artist_vocab.counts_to_entries(artist_counts)
        )
        word_path = os.path.join(output_dir, "word_counts.csv")
        artist_path = os.path.join(output_dir, "top_artists.csv")
        write_count_csv(word_path, "word", word_entries, word_limit)
        write_count_csv(artist_path, "artist", artist_entries, artist_limit)

    # Reference timing semantics (src/parallel_spotify.c:850-851,1000,1068):
    # compute = local read+count; total = compute + aggregation/export.
    # Each chip's compute = the shared host ingest (one pass serves every
    # chip — the single-controller analogue of each rank's read) plus its
    # own measured count/merge time, so the min/avg/max spread is real
    # (cf. the reference's six MPI_Reduce stats, :1077-1082).
    ingest_seconds = timer.seconds.get("ingest", 0.0)
    export_seconds = timer.seconds.get("aggregate_export", 0.0)
    # From here on, "per-chip compute" MEANS ingest share + own count/merge
    # — the same quantity compute_time aggregates and per_chip lists, so
    # the metrics file is internally consistent.
    per_chip_compute = [ingest_seconds + c for c in per_chip_compute]
    compute_time = TimeStats.from_samples(per_chip_compute)
    total_time = TimeStats.from_samples(
        [c + export_seconds for c in per_chip_compute]
    )
    metrics_path = os.path.join(output_dir, "performance_metrics.json")
    devices = mesh.devices.flatten().tolist()
    write_performance_metrics(
        metrics_path,
        processes=len(devices),
        total_songs=total_songs,
        total_words=total_words,
        compute_time=compute_time,
        total_time=total_time,
        per_chip=[
            {
                "device": str(d),
                "platform": d.platform,
                # 9 decimals: the per-shard spread is microseconds on small
                # corpora; 6 would round distinct measurements together.
                "compute_seconds": round(seconds, 9),
            }
            for d, seconds in zip(devices, per_chip_compute)
        ],
        stages=dict(timer.seconds),
        device_platform=devices[0].platform if devices else "unknown",
    )

    if not quiet:
        print("=== Parallel Spotify Analysis ===")
        print(f"Total songs processed: {total_songs}")
        print(f"Total words counted: {total_words}")
        preview_words = word_entries[:10]
        print(f"Top {len(preview_words)} words:")
        for key, value in preview_words:
            print(f"  {key}: {value}")
        preview_artists = artist_entries[:10]
        print(f"Top {len(preview_artists)} artists:")
        for key, value in preview_artists:
            print(f"  {key}: {value} songs")

    return AnalysisResult(
        word_entries=word_entries,
        artist_entries=artist_entries,
        total_songs=total_songs,
        total_words=total_words,
        timings=dict(timer.seconds),
        output_paths={
            "word_counts": word_path,
            "top_artists": artist_path,
            "performance_metrics": metrics_path,
            "split_dir": split_dir,
        },
        per_chip_compute=list(per_chip_compute),
    )
