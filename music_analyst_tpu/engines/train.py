"""Distributed training step for the decoder LM family.

The reference has no training at all; this engine exists because the
framework's model families must be trainable at scale (fine-tuning the
sentiment classifier, continued pretraining on lyrics).  The step is a
single jitted SPMD program over a named mesh:

* ``dp`` — batch axis of the token batch;
* ``sp`` — sequence axis of the token batch (GSPMD inserts the attention
  collectives from the shardings; the hand-rolled ring attention in
  ``ops/ring_attention.py`` is the ICI-optimal manual variant);
* ``tp`` — parameter/optimizer-state sharding via ``parallel/sharding.py``;
* ``ep`` — MoE expert stacks when the config enables experts.

Gradients reduce over ``dp``/``sp`` automatically (XLA derives the psums
from the shardings — the scaling-book recipe, not hand-written collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from music_analyst_tpu.models.layers import causal_mask
from music_analyst_tpu.parallel.sharding import partition_specs


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[]
)


def causal_lm_loss(model, params, token_ids, lengths, segment_ids=None):
    """Next-token cross-entropy with padding masked out.

    ``segment_ids`` ``[B, S]`` (contiguous document ids per row, 0 = pad)
    turns a row into a *pack* of documents — the standard pretraining
    data-efficiency move: attention is restricted to same-document pairs,
    positions restart at every document boundary, and the loss skips the
    cross-document boundary target (token t never predicts another
    document's token t+1).  A packed row's per-token losses equal the
    per-document rows' exactly (``tests/test_packed_training.py``).
    """
    inputs = token_ids[:, :-1]
    targets = token_ids[:, 1:]
    S = inputs.shape[1]
    s_idx = jnp.arange(S)[None, :]
    flash = model.config.attn_impl == "flash"
    if segment_ids is None:
        positions = jnp.broadcast_to(s_idx, inputs.shape)
        logits, _ = model.apply(
            {"params": params}, inputs, positions, causal_mask(S, S, 0)
        )
    else:
        from music_analyst_tpu.models.layers import segment_mask

        seg = segment_ids[:, :-1].astype(jnp.int32)
        # Position = offset from the document's first token: cummax of
        # the segment-start indices (contiguous ids ⇒ a start is any
        # index whose left neighbor differs).
        is_start = jnp.concatenate(
            [jnp.ones((seg.shape[0], 1), bool), seg[:, 1:] != seg[:, :-1]],
            axis=1,
        )
        start_idx = jax.lax.cummax(jnp.where(is_start, s_idx, 0), axis=1)
        positions = s_idx - start_idx
        # The flash path discards mask arrays by contract (models/llama.py)
        # and takes the segment ids natively; the dense path folds them
        # into the mask array.  Routing by impl here keeps both honest —
        # tests pin packed ≡ separate on each.
        if flash:
            logits, _ = model.apply(
                {"params": params}, inputs, positions, None,
                segment_ids=seg,
            )
        else:
            logits, _ = model.apply(
                {"params": params}, inputs, positions,
                causal_mask(S, S, 0) & segment_mask(seg),
            )
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    valid = (s_idx < (lengths - 1)[:, None]).astype(jnp.float32)
    if segment_ids is not None:
        # Drop pad tokens and the last token of every document: its
        # "next token" belongs to a different document.
        same_doc = (segment_ids[:, :-1] == segment_ids[:, 1:])
        valid = valid * (same_doc & (segment_ids[:, :-1] > 0)).astype(
            jnp.float32
        )
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def make_optimizer(
    learning_rate: float = 3e-4, weight_decay: float = 0.01
) -> optax.GradientTransformation:
    return optax.adamw(learning_rate, weight_decay=weight_decay)


def zero1_shard_opt_state(opt_state, mesh: Mesh):
    """Shard optimizer-state arrays over the ``dp`` axis (ZeRO stage 1).

    Data-parallel replicas don't need replicated Adam moments — each can
    own a slice of them (cross-replica sharding of the weight update,
    arXiv:2004.13336; PAPERS.md).  Each moment leaf gets ``dp`` assigned to
    its first divisible, still-unsharded dimension, composing with the
    tp/ep specs it inherited from the params.  GSPMD derives the
    reduce-scatter/all-gather pair around the update from the sharding
    mismatch — no hand-written collectives.
    """
    dp = mesh.shape.get("dp", 1)
    if dp <= 1:
        return opt_state

    def place(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return leaf
        spec = list(getattr(getattr(leaf, "sharding", None), "spec", ()))
        spec += [None] * (leaf.ndim - len(spec))
        for i in range(leaf.ndim):
            if spec[i] is None and leaf.shape[i] % dp == 0:
                spec[i] = "dp"
                return jax.device_put(leaf, NamedSharding(mesh, P(*spec)))
        return leaf  # no divisible free axis — stays as-is

    return jax.tree_util.tree_map(place, opt_state)


def init_train_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_batch: Tuple[jax.Array, jax.Array],
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    zero1: bool = False,
) -> TrainState:
    """Initialize params + optimizer state, sharded over ``mesh`` if given.

    Parameters and every optimizer-state leaf that mirrors a parameter
    (Adam moments) share the same partition spec, so optimizer memory
    scales down with ``tp``/``ep`` exactly like the weights.  With
    ``zero1=True`` the moments additionally shard over ``dp``
    (:func:`zero1_shard_opt_state`).
    """
    token_ids, lengths = sample_batch
    S = token_ids.shape[1] - 1
    positions = jnp.zeros((1, S), jnp.int32)
    params = model.init(
        jax.random.key(seed),
        jnp.zeros((1, S), jnp.int32),
        positions,
        causal_mask(S, S, 0),
    )["params"]
    opt_state = optimizer.init(params)
    if mesh is not None:
        specs = partition_specs(params)
        axis_names = set(mesh.axis_names)

        def prune(spec: P) -> P:
            return P(*(a if a in axis_names else None for a in spec))

        def place_params(spec, leaf):
            return jax.device_put(leaf, NamedSharding(mesh, prune(spec)))

        params = jax.tree_util.tree_map(
            lambda spec, leaf: place_params(spec, leaf), specs, params
        )
        # Re-initializing from the sharded params makes every Adam moment
        # (zeros_like of a sharded leaf) inherit that leaf's sharding.
        opt_state = optimizer.init(params)
        if zero1:
            opt_state = zero1_shard_opt_state(opt_state, mesh)
    return TrainState(
        params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
    )


def _with_step_telemetry(step):
    """Wrap a (possibly jitted) train step with a telemetry span + counter.

    The span measures *dispatch* time: the jitted program is asynchronous,
    so the first call's duration includes trace+compile while steady-state
    calls are near-instant enqueues.  That asymmetry is exactly what makes
    the span useful — compile stalls show up as outlier ``train_step``
    spans next to the jax backend_compile events in the same log.
    """
    import functools

    from music_analyst_tpu.observability import watchdog
    from music_analyst_tpu.telemetry import get_telemetry

    @functools.wraps(step)
    def timed_step(state, token_ids, lengths, segment_ids=None):
        tel = get_telemetry()
        with tel.span("train_step"):
            # A dispatch that never returns (tunnel hang mid-step) is a
            # device stall; the watchdog names it instead of a dead bench.
            with watchdog.watch("train.step", kind="device"):
                out = step(state, token_ids, lengths, segment_ids)
        tel.count("train_steps")
        return out

    return timed_step


def make_train_step(model, optimizer, mesh: Optional[Mesh] = None):
    """Build the jitted SPMD train step.

    With a mesh, the token batch shards ``P('dp', 'sp')`` (batch over data
    ranks, sequence over sequence ranks) and the output state is pinned to
    the *input* state's shardings (derived per distinct input sharding
    layout) — required for ZeRO-1, where the moments' dp-sharding must
    survive the update instead of being re-replicated by the compiler, and
    harmless otherwise.
    """

    def step_fn(state: TrainState, token_ids, lengths, segment_ids=None):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(model, p, token_ids, lengths,
                                     segment_ids=segment_ids)
        )(state.params)
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(new_params, new_opt, state.step + 1),
            loss,
        )

    from music_analyst_tpu.profiling.compile import profiled_jit

    if mesh is None:
        # Donate the incoming state: state-in and state-out are the same
        # pytree of shapes, so params + both Adam moments update in place
        # instead of holding two full copies live across the step.  Callers
        # must reassign (`state, loss = step(state, ...)`) — every loop in
        # this repo does, and the donated buffers error loudly if reused.
        return _with_step_telemetry(
            profiled_jit(step_fn, name="train_step", donate_argnums=(0,))
        )

    data_axes = [a for a in ("dp", "sp") if a in mesh.axis_names]
    dp = data_axes[0] if data_axes else None
    sp = data_axes[1] if len(data_axes) > 1 else None
    batch_sharding = NamedSharding(mesh, P(dp, sp))
    lengths_sharding = NamedSharding(mesh, P(dp))

    def sharded_step(state, token_ids, lengths, segment_ids=None):
        token_ids = jax.lax.with_sharding_constraint(token_ids, batch_sharding)
        lengths = jax.lax.with_sharding_constraint(lengths, lengths_sharding)
        if segment_ids is not None:
            # Packed-document ids shard exactly like the tokens they label.
            segment_ids = jax.lax.with_sharding_constraint(
                segment_ids, batch_sharding
            )
        return step_fn(state, token_ids, lengths, segment_ids)

    def _shardings_of(state):
        return jax.tree_util.tree_map(
            lambda x: x.sharding
            if isinstance(getattr(x, "sharding", None), NamedSharding)
            else None,
            state,
        )

    # Output shardings derive from each call's concrete input state, keyed
    # by the state's sharding layout: init_train_state(zero1=True) is the
    # only knob, and a step function reused across differently-sharded
    # states (e.g. a plain smoke state, then a ZeRO-1 state) pins each
    # layout separately instead of freezing the first one seen.  The
    # common case — the caller feeding back the state this step returned —
    # is an identity check, so the steady-state loop never re-derives the
    # layout (NamedSharding is hashable, so the cold-path key is the
    # sharding tuple itself, no string formatting).
    import weakref

    jitted_by_layout = {}
    # Weakref so the cache never pins the caller's dropped TrainState
    # (params + both Adam moments) in device memory.
    last_out = [None, None]  # [weakref to output state, jitted fn]

    def pinned_step(state, token_ids, lengths, segment_ids=None):
        if last_out[0] is not None and last_out[0]() is state:
            jitted = last_out[1]
        else:
            shardings = _shardings_of(state)
            key = tuple(
                jax.tree_util.tree_leaves(
                    shardings, is_leaf=lambda x: x is None
                )
            )
            jitted = jitted_by_layout.get(key)
            if jitted is None:
                # donate_argnums=(0,): the output state is pinned to the
                # input state's shardings, so every leaf aliases exactly —
                # in-place update, halving peak optimizer memory.
                jitted = profiled_jit(
                    sharded_step, name="train_step_sharded",
                    out_shardings=(shardings, None),
                    donate_argnums=(0,),
                )
                jitted_by_layout[key] = jitted
        new_state, loss = jitted(state, token_ids, lengths, segment_ids)
        last_out[0], last_out[1] = weakref.ref(new_state), jitted
        return new_state, loss

    return _with_step_telemetry(pinned_step)


def prefetch_batches(batches, mesh: Optional[Mesh] = None, depth=None):
    """Device-put training batches up to ``depth`` ahead of the step loop.

    ``batches`` yields ``(token_ids, lengths)`` or ``(token_ids, lengths,
    segment_ids)`` host arrays; each comes back with lengths/segment ids
    narrowed to int16 where the sequence length allows (they widen inside
    the loss) and every array already placed — sharded ``P('dp','sp')``
    when a mesh is given — so the train loop's ``jitted(state, *batch)``
    never blocks on the ~10 MB/s H2D tunnel.  The transfer overlaps the
    previous step's device time through the shared bounded pipeline
    (``runtime/prefetch.py``); stalls land in the manifest's ``pipeline``
    section under ``train_pipeline``.
    """
    from music_analyst_tpu.runtime import (
        PrefetchPipeline,
        Stage,
        resolve_prefetch_depth,
    )
    from music_analyst_tpu.runtime.wire import count_h2d_bytes, narrow_lengths

    depth = resolve_prefetch_depth(depth)
    if mesh is not None:
        data_axes = [a for a in ("dp", "sp") if a in mesh.axis_names]
        dp = data_axes[0] if data_axes else None
        sp = data_axes[1] if len(data_axes) > 1 else None
        batch_sharding = NamedSharding(mesh, P(dp, sp))
        lengths_sharding = NamedSharding(mesh, P(dp))
    else:
        batch_sharding = lengths_sharding = None

    def h2d(batch):
        token_ids, lengths, *rest = batch
        segment_ids = rest[0] if rest else None
        S = token_ids.shape[1]
        lengths = narrow_lengths(lengths, S)
        arrays = [token_ids, lengths]
        shardings = [batch_sharding, lengths_sharding]
        if segment_ids is not None:
            # Contiguous per-row document ids are bounded by S.
            arrays.append(narrow_lengths(segment_ids, S))
            shardings.append(batch_sharding)
        count_h2d_bytes(arrays, prefix="train_pipeline")
        placed = tuple(
            jax.device_put(a, s) for a, s in zip(arrays, shardings)
        )
        if segment_ids is None and rest:
            return (*placed, None)
        return placed

    pipe = PrefetchPipeline(
        [Stage("h2d", h2d)],
        depth=depth,
        name="train_pipeline",
        sink_name="step",
    )
    return pipe.run(iter(batches))
