"""Real-weight validation harness (VERDICT r4 missing #2).

The reference's live path produces real labels from a real model
(``scripts/sentiment_classifier.py:85-108``); this framework's neural
backends run random weights in the zero-egress build environment, with
checkpoint loaders oracle-tested at the tensor level.  This module closes
the remaining certification gap: ONE command that, the moment real
weights are available via the ``MUSICAAL_*_CKPT`` env vars, runs a
dataset slice through the TPU backend AND through an independent
HuggingFace-``transformers`` torch oracle built from the same checkpoint
file, and reports label agreement.

    MUSICAAL_DISTILBERT_CKPT=…/pytorch_model.bin \\
        python -m music_analyst_tpu validate data.csv --model distilbert

The oracle is deliberately *not* this package's model code: logits come
from ``transformers``' own ``DistilBertForSequenceClassification`` /
``LlamaForCausalLM`` modules loaded with the checkpoint's state dict, so
a mapping or architecture bug on our side cannot cancel out.  Token ids
are shared (the backend's tokenizer feeds both), so the report isolates
model-path fidelity; tokenizer fidelity is covered by its own oracle
tests.  CI exercises the whole harness with crafted tiny checkpoints
(``tests/test_validate_weights.py``).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np

from music_analyst_tpu.utils.labels import SUPPORTED_LABELS

_ENV_BY_FAMILY = {
    "distilbert": "MUSICAAL_DISTILBERT_CKPT",
    "llama": "MUSICAAL_LLAMA_CKPT",
}


def _family(model: str) -> str:
    for family in _ENV_BY_FAMILY:
        if model.startswith(family):  # "llama" also covers "llama3*"
            return family
    raise ValueError(
        f"validate supports distilbert[-*] and llama[3*] models, got "
        f"{model!r} (mock/ollama have no checkpoint to validate)"
    )


def _oracle_distilbert_labels(
    checkpoint_path: str, clf, texts: Sequence[str]
) -> List[str]:
    """Labels from transformers' own DistilBERT given the same checkpoint,
    the same token ids, and the same documented 2→3-label rule."""
    import torch
    import transformers

    cfg = clf.config
    hf_cfg = transformers.DistilBertConfig(
        vocab_size=cfg.vocab_size,
        dim=cfg.dim,
        n_layers=cfg.n_layers,
        n_heads=cfg.n_heads,
        hidden_dim=cfg.hidden_dim,
        max_position_embeddings=cfg.max_positions,
        num_labels=cfg.n_classes,
        dropout=0.0,
        attention_dropout=0.0,
        seq_classif_dropout=0.0,
    )
    model = transformers.DistilBertForSequenceClassification(hf_cfg)
    sd = torch.load(checkpoint_path, map_location="cpu", weights_only=True)
    missing, unexpected = model.load_state_dict(sd, strict=False)
    unexpected = [k for k in unexpected if not k.endswith("position_ids")]
    if missing or unexpected:
        raise ValueError(
            "oracle could not consume the checkpoint exactly: "
            f"missing={sorted(missing)[:4]} unexpected={sorted(unexpected)[:4]}"
        )
    model.eval()

    ids, lengths = clf.tokenizer.encode_batch(texts, clf.max_len)
    attention = (
        np.arange(clf.max_len)[None, :] < lengths[:, None]
    ).astype(np.int64)
    with torch.no_grad():
        logits = model(
            input_ids=torch.tensor(np.asarray(ids, dtype=np.int64)),
            attention_mask=torch.tensor(attention),
        ).logits
    probs = torch.softmax(logits, dim=-1)
    conf, cls = probs.max(dim=-1)
    labels = []
    for text, c, k in zip(texts, conf.tolist(), cls.tolist()):
        if not text.strip():
            labels.append("Neutral")  # reference empty-lyric rule
        elif c < clf.neutral_threshold:
            labels.append("Neutral")
        else:
            labels.append(clf._CLASS_LABELS[int(k)])
    return labels


def build_llama_oracle(checkpoint_path: str, cfg):
    """transformers' own LlamaForCausalLM loaded from the checkpoint.

    Exposed separately from the label scoring so tests can pin logit
    parity directly (label agreement on random tiny fixtures is chaotic
    over ~250-token prompts — fp reduction-order noise can flip a near-tie
    even when both models are exact; real finetuned weights separate the
    labels by orders of magnitude more).
    """
    import transformers

    from music_analyst_tpu.models.llama import load_torch_state_dict

    # Same shard-merging reader as the backend: MUSICAAL_LLAMA_CKPT may be
    # a single file or a directory of pytorch_model-*.bin shards.
    sd = load_torch_state_dict(checkpoint_path)
    if not any(k.startswith("model.") for k in sd):
        # The backend tolerates bare-model keys; HF's module names don't.
        sd = {
            (k if k == "lm_head.weight" else "model." + k): v
            for k, v in sd.items()
        }
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        intermediate_size=cfg.hidden_dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq_len,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=1e-5,  # models/layers.py RMSNorm epsilon
        attention_bias=False,
        tie_word_embeddings="lm_head.weight" not in sd,
    )
    model = transformers.LlamaForCausalLM(hf_cfg)
    missing, unexpected = model.load_state_dict(sd, strict=False)
    missing = [k for k in missing if k != "lm_head.weight"]  # tied
    unexpected = [k for k in unexpected if "rotary_emb" not in k]
    if missing or unexpected:
        raise ValueError(
            "oracle could not consume the checkpoint exactly: "
            f"missing={sorted(missing)[:4]} unexpected={sorted(unexpected)[:4]}"
        )
    model.eval()
    return model


def _oracle_llama_labels(
    checkpoint_path: str, clf, texts: Sequence[str]
) -> List[str]:
    """Labels from transformers' LlamaForCausalLM, scoring the same label
    continuations teacher-forced after the same prompt ids."""
    import torch

    from music_analyst_tpu.models.llama import (
        LYRICS_TRUNCATION,
        PROMPT_TEMPLATE,
    )

    model = build_llama_oracle(checkpoint_path, clf.config)

    label_ids = [
        [int(t) for t in clf._label_ids[k][: clf._label_lens[k]]]
        for k in range(len(SUPPORTED_LABELS))
    ]
    labels = []
    for text in texts:
        if not text.strip():
            labels.append("Neutral")  # reference empty-lyric rule
            continue
        prompt = PROMPT_TEMPLATE.format(lyrics=text.strip()[:LYRICS_TRUNCATION])
        row, n = clf.tokenizer.encode(prompt, clf.max_prompt_len)
        prompt_ids = [int(t) for t in row[:n]]
        # One batched forward scores all three right-padded continuations
        # (the rows differ only in their ≤8-token tails; per-label
        # forwards would recompute the ~250-token prompt three times).
        width = n + max(len(c) for c in label_ids)
        batch = torch.zeros((len(label_ids), width), dtype=torch.long)
        attention = torch.zeros_like(batch)
        for k, cont in enumerate(label_ids):
            seq = prompt_ids + cont
            batch[k, : len(seq)] = torch.tensor(seq)
            attention[k, : len(seq)] = 1
        with torch.no_grad():
            logits = model(batch, attention_mask=attention).logits
        logp = torch.log_softmax(logits.float(), dim=-1)
        scores = []
        for k, cont in enumerate(label_ids):
            # Token cont[j] is predicted by the position before it.
            total = sum(
                float(logp[k, n - 1 + j, tok])
                for j, tok in enumerate(cont)
            )
            # Length-normalized, like the backend's scorer: summed
            # log-probs would favor the shortest label
            # (models/llama.py:_score_labels).
            scores.append(total / max(1, len(cont)))
        labels.append(SUPPORTED_LABELS[int(np.argmax(scores))])
    return labels


def run_validation(
    dataset_path: str,
    model: str = "distilbert",
    limit: int = 64,
    output_dir: Optional[str] = None,
    checkpoint_path: Optional[str] = None,
    quiet: bool = False,
    backend=None,
    weight_quant: Optional[str] = None,
):
    """Classify a slice with the TPU backend and with the HF torch oracle;
    return the agreement report (and write ``weight_validation.json``).

    ``backend`` is injectable for tests; by default the model name
    resolves through :func:`get_backend`, which picks the checkpoint up
    from the same ``MUSICAAL_*_CKPT`` env var a production run uses.
    """
    from music_analyst_tpu.data.csv_io import iter_songs
    from music_analyst_tpu.serving.residency import ModelResidency

    family = _family(model)
    checkpoint_path = checkpoint_path or os.environ.get(
        _ENV_BY_FAMILY[family]
    )
    if not checkpoint_path:
        raise RuntimeError(
            f"no checkpoint to validate: set {_ENV_BY_FAMILY[family]} (or "
            "pass checkpoint_path=)"
        )
    clf = ModelResidency(
        model, backend=backend, weight_quant=weight_quant,
        checkpoint_path=checkpoint_path,
    ).acquire()
    if not getattr(clf, "pretrained", False):
        raise RuntimeError(
            "backend did not load the checkpoint — validating random "
            "weights would certify nothing"
        )

    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    with tel.run_scope("validate", output_dir):
        tel.annotate(model=model, backend=getattr(clf, "name", model))
        with tel.span("ingest"):
            songs = []
            for artist, song, text in iter_songs(dataset_path):
                songs.append((artist, song, text))
                if limit and len(songs) >= limit:
                    break
            texts = [text for _, _, text in songs]
        tel.count("rows_validated", len(texts))

        with tel.span("compute", rows=len(texts)):
            ours = clf.classify_batch(texts)
        with tel.span("oracle", rows=len(texts)):
            oracle = (
                _oracle_distilbert_labels(checkpoint_path, clf, texts)
                if family == "distilbert"
                else _oracle_llama_labels(checkpoint_path, clf, texts)
            )

        disagreements = [
            {"artist": a, "song": s, "ours": o, "oracle": h}
            for (a, s, _), o, h in zip(songs, ours, oracle)
            if o != h
        ]
        confusion = {
            want: {got: 0 for got in SUPPORTED_LABELS}
            for want in SUPPORTED_LABELS
        }
        for o, h in zip(ours, oracle):
            confusion[h][o] += 1
        report = {
            "model": model,
            "checkpoint": checkpoint_path,
            "rows": len(texts),
            # Unrounded: the CLI --min-agreement gate compares this value,
            # and rounding could nudge a just-failing run over the bar.
            "agreement": sum(
                o == h for o, h in zip(ours, oracle)
            ) / max(1, len(texts)),
            "oracle": "transformers torch forward, shared tokenizer ids",
            "confusion_oracle_to_ours": confusion,
            "disagreements": disagreements[:20],
        }
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            path = os.path.join(output_dir, "weight_validation.json")
            with tel.span("write"), open(path, "w", encoding="utf-8") as fh:
                json.dump(report, fh, indent=2)
            if not quiet:
                print(f"Validation report -> {path}")
    if not quiet:
        print(
            f"{report['rows']} rows: {report['agreement'] * 100:.1f}% label "
            f"agreement vs the transformers oracle"
        )
        for d in disagreements[:5]:
            print(f"  differs: {d['song']!r} ours={d['ours']} "
                  f"oracle={d['oracle']}")
    return report
