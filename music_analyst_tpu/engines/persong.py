"""Per-song word counts — the serial/threaded oracle tool.

Capability parity with the reference's per-song counter
(``scripts/word_count_per_song.py``, SURVEY.md §2.2 P7/P8): same two
artifacts (``word_counts_by_song.csv`` streamed in row order,
``word_counts_global.csv`` ranked count-desc with ties in first-seen
order — deliberately *not* the strcmp tie-break of the parallel engine;
that divergence exists in the reference and is preserved).

The implementation follows this repo's histogram idiom rather than the
reference's ``Counter``-based script: words get dense first-seen integer
ids and fold into a flat count vector (the host-side analogue of
``ops/histogram.py``'s vocab + dense-counts design), and the global
ranking is a single stable sort on ``-count`` — which reproduces
``Counter.most_common()`` tie order without materializing a ``Counter``.
Tokenization runs on a chunked submit/collect thread pipeline (bounded
in-flight window, results folded strictly in submission order), the same
shape as the sentiment engine's batch pipeline.
"""

from __future__ import annotations

import contextlib
import csv
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from music_analyst_tpu.data.csv_io import sniff_delimiter
from music_analyst_tpu.data.tokenizer import tokenize_latin1
from music_analyst_tpu.observability import watchdog
from music_analyst_tpu.runtime import PrefetchPipeline, Stage
from music_analyst_tpu.telemetry import get_telemetry

# Rows per pool task.  Large enough to amortize future/queue overhead,
# small enough that the bounded window keeps memory flat on 1M-row files.
_CHUNK_ROWS = 512
# Chunks allowed in flight ahead of the fold (per worker).
_WINDOW_PER_WORKER = 2

# One song's tokenization: (artist, song, ((word, count), ...)) with words
# in first-appearance order, or None when the lyric produced no tokens.
_SongCounts = Optional[Tuple[str, str, Tuple[Tuple[str, int], ...]]]


@dataclass
class _DenseHistogram:
    """Insertion-ordered word→count accumulator.

    Host-side mirror of the device histogram design: a vocab dict handing
    out dense first-seen ids plus a flat count vector, instead of the
    reference's ``collections.Counter``.
    """

    ids: Dict[str, int] = field(default_factory=dict)
    counts: List[int] = field(default_factory=list)

    def add(self, word: str, n: int) -> None:
        idx = self.ids.setdefault(word, len(self.counts))
        if idx == len(self.counts):
            self.counts.append(n)
        else:
            self.counts[idx] += n

    def ranked(self) -> Iterator[Tuple[str, int]]:
        """Count-desc; ties keep first-seen order (stable sort), matching
        the ``most_common()`` semantics the reference's output exposes."""
        order = sorted(range(len(self.counts)), key=lambda i: -self.counts[i])
        words = list(self.ids)
        return ((words[i], self.counts[i]) for i in order)

    @property
    def total(self) -> int:
        return sum(self.counts)


def _tokenize_chunk(
    rows: Sequence[Tuple[str, str, str]],
) -> List[_SongCounts]:
    """Pool task: tokenize a block of (artist, song, text) rows.

    Per-song word order is first-appearance order (dict insertion), which
    both artifacts expose and the differential tests pin.
    """
    import time

    start = time.perf_counter()
    out: List[_SongCounts] = []
    for artist, song, text in rows:
        per_song: Dict[str, int] = {}
        for token in tokenize_latin1(text):
            per_song[token] = per_song.get(token, 0) + 1
        out.append((artist, song, tuple(per_song.items())) if per_song else None)
    # Recorded from the pool worker thread — the registry's span path is
    # thread-safe by contract (tests/test_telemetry.py pins it).
    get_telemetry().record_span(
        "tokenize", time.perf_counter() - start, rows=len(rows)
    )
    return out


def _iter_chunks(
    reader: Iterable[Dict[str, str]], chunk_rows: int
) -> Iterator[List[Tuple[str, str, str]]]:
    chunk: List[Tuple[str, str, str]] = []
    for row in reader:
        # Short rows yield None for missing columns; treat them as empty
        # (robustness divergence documented in MIGRATION.md — the
        # reference would crash on None.strip()).
        chunk.append(
            (
                (row.get("artist") or "").strip(),
                (row.get("song") or "").strip(),
                row.get("text") or "",
            )
        )
        if len(chunk) >= chunk_rows:
            yield chunk
            chunk = []
    if chunk:
        yield chunk


def run_per_song_wordcount(
    csv_path: str,
    output_dir: str = "output/serial_word_counts",
    encoding: str = "utf-8-sig",
    delimiter: Optional[str] = None,
    workers: int = 0,
    quiet: bool = False,
    chunk_rows: int = _CHUNK_ROWS,
) -> Tuple[Path, Path, int]:
    """Write both artifacts; returns (global_path, per_song_path, rows).

    Artifact bytes match ``scripts/word_count_per_song.py`` exactly
    (``tests/test_reference_scripts_differential.py``); the engine shape
    does not.  ``chunk_rows`` is this engine's streaming-granularity knob
    (rows per pool task — the corpus cache doesn't apply here: the
    ``csv.DictReader``/latin-1 parse is a different artifact from
    ``IngestResult`` by design).
    """
    src = Path(csv_path)
    if not src.exists():
        raise FileNotFoundError(str(src))
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    global_path = out / "word_counts_global.csv"
    per_song_path = out / "word_counts_by_song.csv"

    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    n_workers = workers if workers > 0 else max(1, os.cpu_count() or 1)
    histogram = _DenseHistogram()
    total_rows = 0

    tel = get_telemetry()
    with tel.run_scope("persong", str(out)):
        total_rows = _persong_stream(
            src, per_song_path, global_path, encoding, delimiter,
            n_workers, histogram, tel, chunk_rows,
        )
        tel.count("rows_processed", total_rows)
        tel.count("distinct_words", len(histogram.counts))
        tel.count("words_counted", histogram.total)

    if not quiet:
        print(
            f"Processed {total_rows} row(s); "
            f"{len(histogram.counts)} distinct words, {histogram.total} total."
        )
        print(f"  global ranking: {global_path}")
        print(f"  per-song rows:  {per_song_path}")
    return global_path, per_song_path, total_rows


def _persong_stream(
    src, per_song_path, global_path, encoding, delimiter, n_workers,
    histogram, tel, chunk_rows,
) -> int:
    total_rows = 0
    with tel.span("ingest", workers=n_workers), \
            open(src, "r", encoding=encoding, newline="") as fh:
        delim = delimiter or sniff_delimiter(fh.read(65536))
        fh.seek(0)
        reader = csv.DictReader(fh, delimiter=delim)
        missing = {"artist", "song", "text"} - set(reader.fieldnames or ())
        if missing:
            raise ValueError(
                "CSV is missing expected columns: " + ", ".join(sorted(missing))
            )

        with open(per_song_path, "w", encoding="utf-8", newline="") as ps_fh:
            by_song = csv.writer(ps_fh)
            by_song.writerow(["artist", "song", "word", "count"])

            def fold(chunk_result: List[_SongCounts]) -> None:
                nonlocal total_rows
                # Per-chunk heartbeat: a healthy fold beats often; a
                # wedged writer or reader goes silent and the enclosing
                # watch classifies it as host_stall.
                watchdog.beat("persong.fold")
                for song_counts in chunk_result:
                    total_rows += 1
                    if song_counts is None:
                        continue
                    artist, song, items = song_counts
                    for word, count in items:
                        histogram.add(word, count)
                        by_song.writerow([artist, song, word, count])

            # Shared bounded pipeline (runtime/prefetch.py) with a
            # multi-worker tokenize stage — same semantics the old
            # hand-rolled deque window had: tokenization overlaps the
            # fold+write, results land strictly in submission order, at
            # most workers×2 chunks in flight.  _tokenize_chunk records
            # its own "tokenize" spans → record_spans=False here.
            pipe = PrefetchPipeline(
                [
                    Stage(
                        "tokenize", _tokenize_chunk,
                        workers=n_workers, record_spans=False,
                    )
                ],
                depth=_WINDOW_PER_WORKER,
                name="persong",
                sink_name="fold",
            )
            # closing(): the pipeline must be cancelled and joined before
            # the reader's file handle goes away.
            with contextlib.closing(
                pipe.run(_iter_chunks(reader, chunk_rows))
            ) as results, watchdog.watch("persong.fold", kind="host"):
                for chunk_result in results:
                    fold(chunk_result)

    with tel.span("write", rows=total_rows), \
            open(global_path, "w", encoding="utf-8", newline="") as g_fh:
        ranked = csv.writer(g_fh)
        ranked.writerow(["word", "count"])
        ranked.writerows(histogram.ranked())

    return total_rows
