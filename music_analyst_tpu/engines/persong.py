"""Per-song word counts — the serial/threaded oracle tool.

Behavioral clone of ``scripts/word_count_per_song.py`` (SURVEY.md §2.2
P7/P8): Latin-1-aware regex tokenizer, thread-pool row processing, two
artifacts — ``word_counts_by_song.csv`` streamed in row order and
``word_counts_global.csv`` via ``Counter.most_common()`` (ties in insertion
order, deliberately *not* the strcmp tie-break of the parallel engine —
that divergence exists in the reference and is preserved).
"""

from __future__ import annotations

import csv
import os
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Dict, Optional, Tuple

from music_analyst_tpu.data.tokenizer import tokenize_latin1


def detect_delimiter(sample: str) -> str:
    """``csv.Sniffer`` over the sample, fallback ``,`` (reference :42-49)."""
    try:
        return csv.Sniffer().sniff(sample).delimiter
    except csv.Error:
        return ","


def resolve_workers(requested: int) -> int:
    """0/negative → one thread per CPU (reference :84-88)."""
    if requested and requested > 0:
        return requested
    return max(1, os.cpu_count() or 1)


def process_row(row: Dict[str, str]) -> Optional[Tuple[str, str, Counter]]:
    """Tokenize one row; ``None`` when the lyric has no tokens (ref :91-99)."""
    artist = (row.get("artist") or "").strip()
    song = (row.get("song") or "").strip()
    text = row.get("text") or ""
    word_counter: Counter = Counter(tokenize_latin1(text))
    if not word_counter:
        return None
    return artist, song, word_counter


def run_per_song_wordcount(
    csv_path: str,
    output_dir: str = "output/serial_word_counts",
    encoding: str = "utf-8-sig",
    delimiter: Optional[str] = None,
    workers: int = 0,
    quiet: bool = False,
) -> Tuple[Path, Path, int]:
    """Write both artifacts; returns their paths and the row count."""
    src = Path(csv_path)
    if not src.exists():
        raise FileNotFoundError(str(src))
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    global_path = out / "word_counts_global.csv"
    per_song_path = out / "word_counts_by_song.csv"

    with open(src, "r", encoding=encoding, newline="") as fh:
        sample = fh.read(65536)
        fh.seek(0)
        delim = delimiter or detect_delimiter(sample)
        reader = csv.DictReader(fh, delimiter=delim)
        required = {"artist", "song", "text"}
        if not required.issubset(reader.fieldnames or {}):
            raise ValueError(
                "CSV is missing expected columns: artist, song, text"
            )

        global_counter: Counter = Counter()
        total_rows = 0
        with open(per_song_path, "w", encoding="utf-8", newline="") as ps_fh:
            per_song_writer = csv.writer(ps_fh)
            per_song_writer.writerow(["artist", "song", "word", "count"])
            # Same split of work as the reference (:132-140): tokenization in
            # the pool, the fold + write on the main thread, chunksize 32.
            with ThreadPoolExecutor(max_workers=resolve_workers(workers)) as pool:
                for result in pool.map(process_row, reader, chunksize=32):
                    total_rows += 1
                    if result is None:
                        continue
                    artist, song, word_counter = result
                    for word, count in word_counter.items():
                        global_counter[word] += count
                        per_song_writer.writerow([artist, song, word, count])

    with open(global_path, "w", encoding="utf-8", newline="") as g_fh:
        writer = csv.writer(g_fh)
        writer.writerow(["word", "count"])
        writer.writerows(global_counter.most_common())

    if not quiet:
        print("Concluído. Processadas", total_rows, "linhas. Arquivos gerados em", os.fspath(out))
        print(" -", os.fspath(global_path))
        print(" -", os.fspath(per_song_path))
    return global_path, per_song_path, total_rows
