"""Checkpoint / resume for training state (Orbax-backed) and the
streaming weight-quantized inference loader.

The reference has no checkpointing at all — every run recomputes from the
CSV (SURVEY.md §5 "Checkpoint/resume: none").  Training at framework scale
needs real save/restore: Orbax handles sharded arrays natively, so a
TrainState saved from a dp×tp mesh restores onto any mesh with the same
global shapes.

``load_quantized_params`` is the inference-side counterpart: HF torch
tensors are read layer-by-layer (the model families expose per-unit
iterators over mmap'd shards), quantized on host in numpy, and device-put
through the bounded-depth ``runtime/prefetch.py`` pipeline — H2D of layer
*k+1* overlaps quantization of layer *k*, and the full float tree never
exists (peak host staging is O(one layer); ``last_load_stats()`` exposes
the measured peak for the test that pins this).  Quantized leaves are
optionally persisted through the content-addressed ``engines/wq_cache.py``
so the quantize + transfer costs are paid once per (checkpoint, scheme).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from music_analyst_tpu.engines.train import TrainState
from music_analyst_tpu.resilience.faults import fault_point


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_train_state(state: TrainState, path: str) -> str:
    """Save to ``path`` (absolute or cwd-relative); returns the path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    _checkpointer().save(
        path,
        {
            "params": state.params,
            "opt_state": state.opt_state,
            "step": np.asarray(state.step),
        },
        force=True,
    )
    return path


def restore_train_state(
    path: str,
    like: Optional[TrainState] = None,
) -> TrainState:
    """Restore; with ``like`` given, restores onto its shardings/structure."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if like is not None:
        template = {
            "params": like.params,
            "opt_state": like.opt_state,
            "step": np.asarray(like.step),
        }
        restored = _checkpointer().restore(path, item=template)
    else:
        restored = _checkpointer().restore(path)
    return TrainState(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=jax.numpy.asarray(restored["step"]),
    )


# ---------------------------------------------------------------------------
# Streaming weight-quantized load (quantize-on-load + bounded-depth H2D)
# ---------------------------------------------------------------------------

# Stats of the most recent load_quantized_params call in this process —
# read by tests (O(one layer) peak-staging assertion) and the wq_store
# bench suite.  Guarded by a lock only for the in-flight byte accounting;
# the snapshot is written once at the end of a load.
_LOAD_LOCK = threading.Lock()
_LAST_LOAD_STATS: Dict[str, Any] = {}


def last_load_stats() -> Dict[str, Any]:
    """Snapshot of the most recent quantized load (empty before any)."""
    with _LOAD_LOCK:
        return dict(_LAST_LOAD_STATS)


def _leaf_bytes(leaf) -> int:
    from music_analyst_tpu.ops.quant import QuantizedParam

    if isinstance(leaf, QuantizedParam):
        return _leaf_bytes(leaf.q) + _leaf_bytes(leaf.scale)
    return int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize


def _set_tree_path(tree, path: str, leaf) -> None:
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[part]
    if parts[-1] not in node:
        raise KeyError(path)
    node[parts[-1]] = leaf


def _device_put_leaf(leaf, path: str, mesh, axis_names):
    """Place one (possibly quantized) leaf per the TP sharding rules."""
    from music_analyst_tpu.ops.quant import QuantizedParam
    from music_analyst_tpu.parallel import sharding as sh

    if mesh is None:
        return jax.tree_util.tree_map(jax.device_put, leaf)
    from jax.sharding import NamedSharding

    base = sh.spec_for_path(path)
    if isinstance(leaf, QuantizedParam):
        import dataclasses

        specs = sh._quantized_specs(leaf, base)
        return dataclasses.replace(
            leaf,
            q=jax.device_put(
                np.ascontiguousarray(leaf.q),
                NamedSharding(mesh, sh.prune_spec(specs.q, axis_names)),
            ),
            scale=jax.device_put(
                np.ascontiguousarray(leaf.scale),
                NamedSharding(mesh, sh.prune_spec(specs.scale, axis_names)),
            ),
        )
    return jax.device_put(
        np.ascontiguousarray(leaf),
        NamedSharding(mesh, sh.prune_spec(base, axis_names)),
    )


def load_quantized_params(
    params_shape,
    unit_source: Callable[[], Iterable[Tuple[str, List[Tuple[str, Any]]]]],
    scheme: str,
    group_size: Optional[int] = None,
    mesh=None,
    cache_dir: Optional[str] = None,
    cache_key: Optional[str] = None,
    prefetch_depth: Optional[int] = None,
):
    """Stream a checkpoint into a device-resident weight-quantized tree.

    ``params_shape`` — the float param tree's *structure* (arrays or
    ``ShapeDtypeStruct``s; never materialized).  ``unit_source`` — a
    zero-arg callable yielding ``(unit_name, [(tree_path, np_array), …])``
    per layer-sized unit (``models/llama.py`` / ``models/distilbert.py``
    iterators); it is only invoked on a cache miss, so a warm load never
    touches torch.  Returns the param tree with ``QuantizedParam`` leaves
    for every rule-matched kernel, every leaf on device.
    """
    from music_analyst_tpu.engines import wq_cache
    from music_analyst_tpu.ops.quant import (
        WQ_DEFAULT_GROUP,
        quantize_array,
        wq_rule_for_path,
    )
    from music_analyst_tpu.runtime.prefetch import (
        PrefetchPipeline,
        Stage,
        resolve_prefetch_depth,
    )

    group_size = WQ_DEFAULT_GROUP if group_size is None else group_size
    depth = resolve_prefetch_depth(prefetch_depth)
    axis_names = set(mesh.axis_names) if mesh is not None else ()
    t0 = time.monotonic()

    cached = wq_cache.iter_entry_or_none(cache_dir, cache_key)
    cache_state = "off" if not (cache_dir and cache_key) else (
        "hit" if cached is not None else "miss"
    )
    writer = None
    if cached is not None:
        # Warm path: leaves come back quantized (mmap'd) — H2D only.  One
        # pipeline item per leaf keeps the in-flight window bounded just
        # like the cold path's layer units.
        units: Iterable = [(path, [(path, leaf)]) for path, leaf in cached]
    else:
        units = unit_source()
        if cache_dir and cache_key:
            writer = wq_cache.WqCacheWriter(cache_dir, cache_key)

    staged = {"now": 0, "peak": 0, "units": 0, "leaves": 0}

    def _stage_quantize(item):
        unit_name, leaves = item
        # First statement on purpose: an injected checkpoint.load trip
        # raises before any staging/writer side effect, so the prefetch
        # stage retry re-runs the unit from scratch.
        fault_point("checkpoint.load", unit=unit_name)
        float_bytes = sum(_leaf_bytes(leaf) for _, leaf in leaves)
        with _LOAD_LOCK:
            staged["now"] += float_bytes
            staged["peak"] = max(staged["peak"], staged["now"])
            staged["units"] += 1
            staged["leaves"] += len(leaves)
        out = []
        for path, leaf in leaves:
            n_contract = wq_rule_for_path(path)
            if n_contract is not None and not _is_quantized(leaf):
                leaf = quantize_array(
                    np.asarray(leaf), scheme, n_contract, group_size
                )
            if writer is not None:
                writer.add(path, leaf)
            out.append((path, leaf))
        with _LOAD_LOCK:
            staged["now"] -= float_bytes
        return unit_name, out

    def _is_quantized(leaf) -> bool:
        from music_analyst_tpu.ops.quant import QuantizedParam

        return isinstance(leaf, QuantizedParam)

    def _stage_h2d(item):
        unit_name, leaves = item
        fault_point("h2d.transfer", unit=unit_name)
        return unit_name, [
            (path, _device_put_leaf(leaf, path, mesh, axis_names))
            for path, leaf in leaves
        ]

    # None marks a not-yet-loaded slot; built with a plain dict walk (NOT
    # tree_map) because jax treats None as an *empty subtree*, which would
    # make the completeness check below vacuous.
    def _none_like(node):
        if isinstance(node, dict):
            return {k: _none_like(v) for k, v in node.items()}
        return None

    def _missing_paths(node, prefix=""):
        if isinstance(node, dict):
            out = []
            for k, v in node.items():
                out.extend(_missing_paths(v, f"{prefix}{k}/"))
            return out
        return [prefix[:-1]] if node is None else []

    out_tree = _none_like(params_shape)
    pipeline = PrefetchPipeline(
        [
            Stage("wq_quantize", _stage_quantize),
            Stage("wq_h2d", _stage_h2d),
        ],
        depth=depth,
        name="wq_load",
        sink_name="assemble",
    )
    for _, leaves in pipeline.run(units):
        for path, leaf in leaves:
            _set_tree_path(out_tree, path, leaf)
    published = writer.publish() if writer is not None else False

    missing = _missing_paths(out_tree)
    if missing:
        raise ValueError(
            "checkpoint stream did not cover the param tree; missing: "
            + ", ".join(missing[:8])
        )

    stats = {
        "scheme": scheme,
        "group_size": group_size,
        "cache": cache_state,
        "cache_stored": bool(published),
        "peak_host_staging_bytes": staged["peak"],
        "units": staged["units"],
        "leaves": staged["leaves"],
        "prefetch_depth": depth,
        "load_seconds": round(time.monotonic() - t0, 6),
    }
    with _LOAD_LOCK:
        _LAST_LOAD_STATS.clear()
        _LAST_LOAD_STATS.update(stats)
    try:
        from music_analyst_tpu.telemetry import get_telemetry

        tel = get_telemetry()
        tel.gauge("wq_load.peak_host_staging_bytes", staged["peak"])
        tel.gauge("wq_load.seconds", stats["load_seconds"])
        tel.count(f"wq_load.cache_{cache_state}")
    except Exception:
        pass
    return out_tree
