"""Checkpoint / resume for training state (Orbax-backed).

The reference has no checkpointing at all — every run recomputes from the
CSV (SURVEY.md §5 "Checkpoint/resume: none").  Training at framework scale
needs real save/restore: Orbax handles sharded arrays natively, so a
TrainState saved from a dp×tp mesh restores onto any mesh with the same
global shapes.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from music_analyst_tpu.engines.train import TrainState


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_train_state(state: TrainState, path: str) -> str:
    """Save to ``path`` (absolute or cwd-relative); returns the path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    _checkpointer().save(
        path,
        {
            "params": state.params,
            "opt_state": state.opt_state,
            "step": np.asarray(state.step),
        },
        force=True,
    )
    return path


def restore_train_state(
    path: str,
    like: Optional[TrainState] = None,
) -> TrainState:
    """Restore; with ``like`` given, restores onto its shardings/structure."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if like is not None:
        template = {
            "params": like.params,
            "opt_state": like.opt_state,
            "step": np.asarray(like.step),
        }
        restored = _checkpointer().restore(path, item=template)
    else:
        restored = _checkpointer().restore(path)
    return TrainState(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=jax.numpy.asarray(restored["step"]),
    )
