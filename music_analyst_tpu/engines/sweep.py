"""Scaling-sweep driver (the ``run_performance.sh`` equivalent, fixed).

The reference sweeps ``mpirun -np N`` over process counts but every run
overwrites ``output/performance_metrics.json``
(``scripts/run_performance.sh:21-26``, SURVEY.md §3.5) — nothing archives
per-N results.  This driver sweeps *device counts* over the mesh, archives
each run's metrics as ``performance_metrics_np{N}.json``, and writes a
``sweep_summary.json`` with wall-clock and speedup per point.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Optional, Sequence

import jax

from music_analyst_tpu.engines.wordcount import run_analysis
from music_analyst_tpu.parallel.mesh import data_parallel_mesh


def run_sweep(
    dataset_path: str,
    device_counts: Optional[Sequence[int]] = None,
    output_dir: str = "output",
    ingest_backend: str = "auto",
    quiet: bool = True,
    corpus_cache_dir: Optional[str] = None,
    use_corpus_cache: bool = True,
    chunk_songs=None,
) -> dict:
    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    os.makedirs(output_dir, exist_ok=True)
    n_available = len(jax.devices())
    if device_counts is None:
        device_counts = [n for n in (1, 2, 4, 8) if n <= n_available]
    summary: dict = {"dataset": dataset_path, "runs": []}
    with tel.run_scope("sweep", output_dir):
        _sweep_points(
            tel, summary, dataset_path, device_counts, n_available,
            output_dir, ingest_backend, quiet,
            corpus_cache_dir, use_corpus_cache, chunk_songs,
        )
    summary_path = os.path.join(output_dir, "sweep_summary.json")
    with open(summary_path, "w", encoding="utf-8") as fh:
        json.dump(summary, fh, indent=2)
        fh.write("\n")
    return summary


def _sweep_points(
    tel, summary, dataset_path, device_counts, n_available, output_dir,
    ingest_backend, quiet, corpus_cache_dir, use_corpus_cache, chunk_songs,
) -> None:
    def _profile_counters() -> dict:
        with tel._lock:
            return {
                k: v for k, v in tel.counters.items()
                if k.startswith(("profiling.", "collectives."))
            }

    base_wall = None
    for n in device_counts:
        if n > n_available:
            print(f"skipping np={n}: only {n_available} devices")
            continue
        mesh = data_parallel_mesh(n)
        before = _profile_counters()
        start = time.perf_counter()
        with tel.span("sweep_point", devices=n):
            # With the corpus cache on, the first point ingests cold and
            # stores; every later point is a warm hit — the sweep's wall
            # times then measure device scaling, not repeated parsing.
            run_analysis(
                dataset_path,
                output_dir=output_dir,
                mesh=mesh,
                write_split=(n == device_counts[0]),  # split artifacts once
                ingest_backend=ingest_backend,
                quiet=quiet,
                corpus_cache_dir=corpus_cache_dir,
                use_corpus_cache=use_corpus_cache,
                chunk_songs=chunk_songs,
            )
        wall = time.perf_counter() - start
        tel.count("sweep_points")
        # Per-point profiling delta: each point's own compiles/collective
        # bytes, not the cumulative totals — the per-N scaling signal
        # (bytes should grow ~linearly in N for the psum merges).
        after = _profile_counters()
        delta = {
            k: after[k] - before.get(k, 0)
            for k in after
            if after[k] != before.get(k, 0)
        }
        tel.event("sweep_point_profile", devices=n,
                  wall_seconds=round(wall, 6), **delta)
        # Archive this point's metrics (the reference overwrites them).
        src = os.path.join(output_dir, "performance_metrics.json")
        dst = os.path.join(output_dir, f"performance_metrics_np{n}.json")
        shutil.copyfile(src, dst)
        if base_wall is None:
            base_wall = wall
        summary["runs"].append(
            {
                "devices": n,
                "wall_seconds": round(wall, 6),
                "speedup_vs_first": round(base_wall / wall, 3),
                "metrics_file": os.path.basename(dst),
            }
        )
        if not quiet:
            print(f"np={n}: {wall:.3f}s")
