"""End-to-end pipelines wiring data → mesh → ops → artifacts."""
