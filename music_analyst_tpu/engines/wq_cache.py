"""Content-addressed quantized-checkpoint cache.

Quantizing an HF checkpoint is cheap next to what it buys, but the costs
it amortizes are the expensive ones in this environment: re-reading the
torch shards (the 8B state dict is ~16 GB of host I/O) and — on the real
chip — pushing bytes through the ~10 MB/s loopback tunnel.  The cache
stores the *already quantized* leaves (int8/int4 codes + scales), so a
second load of the same (checkpoint, scheme) pays neither torch nor the
quantizer, and the bytes that do move are the quantized ~8 GB (int8) or
~4 GB (int4), not the float 16 GB.

Modeled on ``data/corpus_cache.py`` (same resolution precedence, atomic
tmp+rename publish, mmap'd ``.npy`` readback, corrupt-entry eviction,
and hit/miss/bytes-saved stats mirrored into telemetry and the run
manifest's ``wq_cache`` section):

* **Key** — (schema version, family, scheme, group size, per-shard sizes
  + BLAKE2b content hash of the source checkpoint).  Renames don't
  invalidate; any byte change, or a different quant scheme, does.
* **Layout** — one directory per entry: ``meta.json`` listing the
  "/"-joined param-tree paths in load order, plus indexed ``.npy`` files
  per leaf (``<i>.q.npy``/``<i>.scale.npy`` for quantized kernels,
  ``<i>.npy`` for float passthrough leaves).
* **Streaming writer** — leaves are appended as the quantize→H2D
  pipeline (``engines/checkpoint.py``) produces them, so the store obeys
  the same O(one layer) host-memory bound as the load; ``publish()``
  renames the staged dir into place, concurrent writers race benignly.
* **Corruption-tolerant** — any readback failure (truncated ``.npy``,
  stale schema, shape drift) counts ``wq_cache.corrupt``, best-effort
  evicts the entry, and reports a miss; the cache can never fail a load.

Resolution: explicit ``cache_dir`` wins, then ``$MUSICAAL_WQ_CACHE`` (a
directory, or ``0``/``off``/``false``/``no`` to disable), then
``~/.cache/musicaal_wq``.  Tests point the env var at a per-session
tmpdir (``tests/conftest.py``).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from music_analyst_tpu.resilience.faults import fault_point
from music_analyst_tpu.resilience.policy import RetryPolicy

SCHEMA_VERSION = 1

# Publish is a single rename; transient FS hiccups get a couple of fast
# retries before the store degrades to un-cached (never fails the load).
_PUBLISH_RETRY = RetryPolicy(base_s=0.02, cap_s=0.2)

_META_NAME = "meta.json"
_HASH_CHUNK = 1 << 22  # 4 MiB reads: streaming hash, bounded memory

_STATS_LOCK = threading.Lock()
_STATS: Dict[str, int] = {
    "hits": 0,
    "misses": 0,
    "stores": 0,
    "corrupt": 0,
    "bytes_saved": 0,
}


def _bump(name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[name] += n
    try:
        from music_analyst_tpu.telemetry import get_telemetry

        get_telemetry().count(f"wq_cache.{name}", n)
    except Exception:
        pass


def cache_stats() -> Dict[str, int]:
    """Snapshot of this process's hit/miss/store/corrupt/bytes-saved."""
    with _STATS_LOCK:
        return dict(_STATS)


def resolve_cache_dir(
    cache_dir: Optional[str] = None, use_cache: Optional[bool] = None
) -> Optional[str]:
    """The directory to cache under, or ``None`` when caching is off."""
    if use_cache is False:
        return None
    if cache_dir:
        return cache_dir
    env = os.environ.get("MUSICAAL_WQ_CACHE", "").strip()
    if env.lower() in ("0", "off", "false", "no"):
        return None
    if env:
        return env
    return os.path.expanduser("~/.cache/musicaal_wq")


def checkpoint_files(path: str) -> List[str]:
    """The weight shard files a checkpoint path denotes (one file, or the
    same shard set ``models/llama.py::load_torch_state_dict`` merges)."""
    if not os.path.isdir(path):
        return [path]
    names = sorted(os.listdir(path))
    shards = [n for n in names
              if n.startswith("pytorch_model") and n.endswith(".bin")]
    if not shards:
        shards = [n for n in names
                  if n.endswith((".bin", ".pt"))
                  and n not in ("training_args.bin", "optimizer.pt",
                                "scheduler.pt", "rng_state.pth")]
    return [os.path.join(path, n) for n in shards]


def wq_key(
    checkpoint_path: str, family: str, scheme: str, group_size: int
) -> str:
    """Content-addressed entry name for (checkpoint bytes, quant scheme)."""
    digest = hashlib.blake2b(digest_size=16)
    total = 0
    for shard in checkpoint_files(checkpoint_path):
        size = os.path.getsize(shard)
        total += size
        digest.update(os.path.basename(shard).encode("utf-8"))
        digest.update(str(size).encode("ascii"))
        with open(shard, "rb") as fh:
            while True:
                block = fh.read(_HASH_CHUNK)
                if not block:
                    break
                digest.update(block)
    group = f"-g{int(group_size)}" if scheme == "int4" else ""
    return (
        f"v{SCHEMA_VERSION}-{family}-{scheme}{group}"
        f"-{total}-{digest.hexdigest()}"
    )


def _entry_bytes(entry: str) -> int:
    total = 0
    for name in os.listdir(entry):
        try:
            total += os.path.getsize(os.path.join(entry, name))
        except OSError:
            pass
    return total


class WqCacheWriter:
    """Streaming store: leaves appended in load order, one atomic publish.

    Never raises out of ``add``/``publish`` — a failed store degrades to
    an un-cached load, mirroring the corpus cache's never-fail contract.
    """

    def __init__(self, cache_dir: str, key: str) -> None:
        self._final = os.path.join(cache_dir, key)
        self._tmp = os.path.join(
            cache_dir, f"{key}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        self._leaves: List[dict] = []
        self._broken = os.path.exists(self._final)  # already published
        if not self._broken:
            try:
                os.makedirs(self._tmp, exist_ok=True)
            except OSError:
                self._broken = True

    def add(self, path_str: str, leaf) -> None:
        from music_analyst_tpu.ops.quant import QuantizedParam

        if self._broken:
            return
        idx = len(self._leaves)
        try:
            if isinstance(leaf, QuantizedParam):
                np.save(os.path.join(self._tmp, f"{idx}.q.npy"),
                        np.asarray(leaf.q))
                np.save(os.path.join(self._tmp, f"{idx}.scale.npy"),
                        np.asarray(leaf.scale))
                self._leaves.append({
                    "path": path_str, "kind": "qp", "index": idx,
                    "scheme": leaf.scheme, "shape": list(leaf.shape),
                    "n_contract": leaf.n_contract,
                    "group_size": leaf.group_size,
                })
            else:
                arr = np.asarray(leaf)
                np.save(os.path.join(self._tmp, f"{idx}.npy"), arr)
                self._leaves.append({
                    "path": path_str, "kind": "array", "index": idx,
                    "shape": list(arr.shape), "dtype": str(arr.dtype),
                })
        except Exception:
            self.abort()

    def publish(self) -> bool:
        if self._broken:
            self.abort()
            return False
        try:
            meta = {"schema": SCHEMA_VERSION, "leaves": self._leaves}
            with open(os.path.join(self._tmp, _META_NAME), "w",
                      encoding="utf-8") as fh:
                json.dump(meta, fh)

            def _publish() -> None:
                fault_point("corpus_cache.publish", key=self._final)
                os.rename(self._tmp, self._final)

            _PUBLISH_RETRY.call(_publish, site="corpus_cache.publish")
        except Exception:
            # Benign race: another writer published first (or an injected
            # fault exhausted its retries — store degrades, never raises).
            self.abort()
            return os.path.isdir(self._final)
        _bump("stores")
        return True

    def abort(self) -> None:
        self._broken = True
        shutil.rmtree(self._tmp, ignore_errors=True)


def load_entry(
    cache_dir: str, key: str
) -> Optional[List[Tuple[str, object]]]:
    """Warm-path readback: ``[(tree_path, leaf), ...]`` in stored order,
    arrays mmap'd; ``None`` on miss or corruption (entry evicted)."""
    from music_analyst_tpu.ops.quant import QuantizedParam

    entry = os.path.join(cache_dir, key)
    if not os.path.isdir(entry):
        _bump("misses")
        return None
    try:
        with open(os.path.join(entry, _META_NAME), encoding="utf-8") as fh:
            meta = json.load(fh)
        if meta.get("schema") != SCHEMA_VERSION:
            raise ValueError(f"stale cache schema {meta.get('schema')!r}")
        out: List[Tuple[str, object]] = []
        for rec in meta["leaves"]:
            idx = rec["index"]
            if rec["kind"] == "qp":
                q = np.load(os.path.join(entry, f"{idx}.q.npy"),
                            mmap_mode="r")
                scale = np.load(os.path.join(entry, f"{idx}.scale.npy"),
                                mmap_mode="r")
                qp = QuantizedParam(
                    q=q, scale=scale, scheme=rec["scheme"],
                    shape=tuple(rec["shape"]),
                    n_contract=int(rec["n_contract"]),
                    group_size=int(rec["group_size"]),
                )
                expect0 = (qp.shape[0] // 2 if qp.scheme == "int4"
                           else qp.shape[0])
                if (q.shape[0] != expect0
                        or tuple(q.shape[1:]) != qp.shape[1:]):
                    raise ValueError(
                        f"cached codes shape {q.shape} inconsistent with "
                        f"kernel {qp.shape} ({qp.scheme})"
                    )
                out.append((rec["path"], qp))
            else:
                arr = np.load(os.path.join(entry, f"{idx}.npy"),
                              mmap_mode="r")
                if tuple(arr.shape) != tuple(rec["shape"]):
                    raise ValueError(
                        f"cached array shape {arr.shape} != meta "
                        f"{rec['shape']}"
                    )
                out.append((rec["path"], arr))
    except Exception:
        _bump("corrupt")
        _bump("misses")
        shutil.rmtree(entry, ignore_errors=True)
        return None
    _bump("hits")
    _bump("bytes_saved", _entry_bytes(entry))
    return out


def iter_entry_or_none(
    cache_dir: Optional[str], key: Optional[str]
) -> Optional[Iterable[Tuple[str, object]]]:
    """``load_entry`` guarded for a disabled cache (no stats noise)."""
    if not cache_dir or not key:
        return None
    return load_entry(cache_dir, key)
