"""Joint pipeline: word/artist histogram + sentiment from ONE ingest pass.

BASELINE.json config[4]: "joint word-histogram + sentiment pipeline, full
1M songs".  The reference has no fused mode — config[4] is two separate
tools reading the dataset twice with two different parsers
(``src/parallel_spotify.c:918-998`` then
``scripts/sentiment_classifier.py:144-154``), which even disagree on the
song count for malformed rows.  Here the native ingest parses the file
once with record capture: the dense id arrays feed the sharded histogram
and the captured ``(artist, song, text)`` records feed the classifier
batches — one parse, one parser, ONE consistent song count across all
five artifacts.

Parser note: the fused run classifies exactly the records the exact
(reference-C-semantics) parser accepts.  A standalone ``sentiment`` run
keeps the reference script's ``csv.DictReader`` semantics for byte parity,
so on datasets with short/malformed rows the standalone tools can disagree
with each other just like the reference's do; the joint run cannot.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from music_analyst_tpu.data.ingest import ingest_dataset
from music_analyst_tpu.engines.sentiment import SentimentResult, run_sentiment
from music_analyst_tpu.engines.wordcount import AnalysisResult, run_analysis
from music_analyst_tpu.metrics.perf import TimeStats, write_performance_metrics
from music_analyst_tpu.metrics.timer import StageTimer


@dataclasses.dataclass
class JointResult:
    analysis: AnalysisResult
    sentiment: SentimentResult
    songs_per_second: float


def run_joint(
    dataset_path: str,
    output_dir: str = "output",
    model: str = "mock",
    mock: bool = False,
    word_limit: int = 0,
    artist_limit: int = 0,
    limit: Optional[int] = None,
    batch_size: int = 4096,
    mesh=None,
    write_split: bool = True,
    ingest_backend: str = "auto",
    quiet: bool = False,
    prefetch_depth: Optional[int] = None,
    corpus_cache_dir: Optional[str] = None,
    use_corpus_cache: bool = True,
    chunk_songs=None,
) -> JointResult:
    from music_analyst_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    # Owner scope: the nested wordcount/sentiment engines' run scopes
    # degrade to spans under this one — ONE manifest for the fused run.
    with tel.run_scope("joint", output_dir):
        return _run_joint_impl(
            dataset_path, output_dir, model, mock, word_limit, artist_limit,
            limit, batch_size, mesh, write_split, ingest_backend, quiet,
            prefetch_depth, corpus_cache_dir, use_corpus_cache, chunk_songs,
        )


def _run_joint_impl(
    dataset_path, output_dir, model, mock, word_limit, artist_limit,
    limit, batch_size, mesh, write_split, ingest_backend, quiet,
    prefetch_depth, corpus_cache_dir, use_corpus_cache, chunk_songs,
) -> JointResult:
    from music_analyst_tpu.data.corpus_cache import resolve_cache_dir

    timer = StageTimer()
    with timer.stage("ingest"):
        # capture_records=True keys its own cache entries (the record
        # arena rides along), so a joint warm hit restores the classifier
        # input too — still one parse, now amortized across runs.
        corpus = ingest_dataset(
            dataset_path,
            limit=limit,
            backend=ingest_backend,
            capture_records=True,
            cache_dir=resolve_cache_dir(corpus_cache_dir, use_corpus_cache),
        )
    with timer.stage("wordcount"):
        analysis = run_analysis(
            dataset_path,
            output_dir=output_dir,
            word_limit=word_limit,
            artist_limit=artist_limit,
            limit=limit,
            mesh=mesh,
            write_split=write_split,
            quiet=quiet,
            corpus=corpus,
            ingest_seconds=timer.seconds["ingest"],
            chunk_songs=chunk_songs,
        )
    with timer.stage("sentiment"):
        sentiment = run_sentiment(
            dataset_path,
            model=model,
            mock=mock,
            output_dir=output_dir,
            batch_size=batch_size,
            quiet=quiet,
            songs=corpus.iter_records(),
            mesh=mesh,
            prefetch_depth=prefetch_depth,
        )
    total = timer.total("ingest", "wordcount", "sentiment")
    songs_per_second = analysis.total_songs / total if total > 0 else 0.0

    # One parse ⇒ one song count everywhere.
    assert sum(sentiment.counts.values()) == analysis.total_songs, (
        "fused pipeline produced inconsistent song counts"
    )

    # Re-emit the metrics file with the joint stage breakdown layered in.
    # Per-chip compute: the wordcount engine's measured per-shard timings
    # plus the classifier stage, which is a lock-stepped SPMD batch program
    # (every chip spends it together — TimeStats.uniform semantics).
    import jax

    devices = (
        mesh.devices.flatten().tolist() if mesh is not None else jax.devices()
    )
    sentiment_seconds = timer.seconds["sentiment"]
    ingest_seconds = timer.seconds["ingest"]
    # analysis.per_chip_compute already folds in the (shared) ingest time;
    # add only the sentiment stage on top.
    per_chip = analysis.per_chip_compute
    assert len(per_chip) == len(devices), (len(per_chip), len(devices))
    per_chip_total = [c + sentiment_seconds for c in per_chip]
    write_performance_metrics(
        os.path.join(output_dir, "performance_metrics.json"),
        processes=len(devices),
        total_songs=analysis.total_songs,
        total_words=analysis.total_words,
        compute_time=TimeStats.from_samples(per_chip_total),
        total_time=TimeStats.uniform(total),
        per_chip=[
            {
                "device": str(d),
                "platform": d.platform,
                "compute_seconds": round(seconds, 9),
            }
            for d, seconds in zip(devices, per_chip_total)
        ],
        stages={
            **analysis.timings,
            "ingest": ingest_seconds,
            "sentiment": sentiment_seconds,
        },
        device_platform=devices[0].platform if devices else "unknown",
    )
    if not quiet:
        print(
            f"Joint pipeline: {analysis.total_songs} songs in {total:.2f}s "
            f"({songs_per_second:.0f} songs/s)"
        )
    return JointResult(analysis, sentiment, songs_per_second)
