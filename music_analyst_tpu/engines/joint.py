"""Joint pipeline: word/artist histogram + sentiment in one run.

BASELINE.json config[4]: "joint word-histogram + sentiment pipeline, full
1M songs".  The word/artist counts go through the native ingest + sharded
psum histogram; sentiment batches stream through the classifier backend
with the host/device pipeline.  One run, all five reference artifacts,
one metrics file with the combined stage breakdown.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from music_analyst_tpu.engines.sentiment import SentimentResult, run_sentiment
from music_analyst_tpu.engines.wordcount import AnalysisResult, run_analysis
from music_analyst_tpu.metrics.perf import TimeStats, write_performance_metrics
from music_analyst_tpu.metrics.timer import StageTimer


@dataclasses.dataclass
class JointResult:
    analysis: AnalysisResult
    sentiment: SentimentResult
    songs_per_second: float


def run_joint(
    dataset_path: str,
    output_dir: str = "output",
    model: str = "mock",
    mock: bool = False,
    word_limit: int = 0,
    artist_limit: int = 0,
    limit: Optional[int] = None,
    batch_size: int = 4096,
    mesh=None,
    write_split: bool = True,
    ingest_backend: str = "auto",
    quiet: bool = False,
) -> JointResult:
    timer = StageTimer()
    with timer.stage("wordcount"):
        analysis = run_analysis(
            dataset_path,
            output_dir=output_dir,
            word_limit=word_limit,
            artist_limit=artist_limit,
            limit=limit,
            mesh=mesh,
            write_split=write_split,
            ingest_backend=ingest_backend,
            quiet=quiet,
        )
    with timer.stage("sentiment"):
        sentiment = run_sentiment(
            dataset_path,
            model=model,
            mock=mock,
            limit=limit,
            output_dir=output_dir,
            batch_size=batch_size,
            quiet=quiet,
        )
    total = timer.total("wordcount", "sentiment")
    songs_per_second = analysis.total_songs / total if total > 0 else 0.0

    # Re-emit the metrics file with the joint stage breakdown layered in.
    import jax

    devices = (
        mesh.devices.flatten().tolist() if mesh is not None else jax.devices()
    )
    write_performance_metrics(
        os.path.join(output_dir, "performance_metrics.json"),
        processes=len(devices),
        total_songs=analysis.total_songs,
        total_words=analysis.total_words,
        compute_time=TimeStats.uniform(total),
        total_time=TimeStats.uniform(total),
        per_chip=[
            {
                "device": str(d),
                "platform": d.platform,
                "compute_seconds": round(total, 6),
            }
            for d in devices
        ],
        stages={**analysis.timings, "sentiment": timer.seconds["sentiment"]},
        device_platform=devices[0].platform if devices else "unknown",
    )
    if not quiet:
        print(
            f"Joint pipeline: {analysis.total_songs} songs in {total:.2f}s "
            f"({songs_per_second:.0f} songs/s)"
        )
    return JointResult(analysis, sentiment, songs_per_second)
